"""ctypes loader for the native runtime library (native/libtfs_native.so).

The native layer carries the framework's non-JAX native components
(SURVEY.md §2.4): GraphDef wire parsing + validation + toposort in C++
(`native/graphdef.cc`) and the ragged columnar conversion kernels
(`native/convert.cc`). Everything degrades gracefully: if the library is
not built, pure-Python implementations are used and `available()` returns
False.

Build with ``make -C native`` at the repo root (or set TFS_NATIVE_LIB).
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "available",
    "parse_graph_native",
    "pack_ragged",
    "unpack_ragged",
    "gather_rows",
]

_lib = None
_tried = False


def _find_lib() -> Optional[str]:
    env = os.environ.get("TFS_NATIVE_LIB")
    if env and os.path.exists(env):
        return env
    here = os.path.dirname(os.path.abspath(__file__))
    for cand in [
        os.path.join(here, "libtfs_native.so"),
        os.path.join(os.path.dirname(os.path.dirname(here)), "native", "libtfs_native.so"),
    ]:
        if os.path.exists(cand):
            return cand
    return None


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    path = _find_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.tfs_graph_parse.restype = ctypes.c_void_p
    lib.tfs_graph_parse.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.tfs_graph_free.argtypes = [ctypes.c_void_p]
    lib.tfs_graph_num_nodes.restype = ctypes.c_int64
    lib.tfs_graph_num_nodes.argtypes = [ctypes.c_void_p]
    lib.tfs_graph_producer.restype = ctypes.c_int64
    lib.tfs_graph_producer.argtypes = [ctypes.c_void_p]
    for fn in ("tfs_graph_node_name", "tfs_graph_node_op", "tfs_graph_node_device"):
        getattr(lib, fn).restype = ctypes.c_char_p
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.tfs_graph_node_num_inputs.restype = ctypes.c_int64
    lib.tfs_graph_node_num_inputs.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.tfs_graph_node_input.restype = ctypes.c_char_p
    lib.tfs_graph_node_input.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.tfs_graph_node_num_attrs.restype = ctypes.c_int64
    lib.tfs_graph_node_num_attrs.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.tfs_graph_node_attr_key.restype = ctypes.c_char_p
    lib.tfs_graph_node_attr_key.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.tfs_graph_node_attr_value.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.tfs_graph_node_attr_value.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.tfs_graph_validate.restype = ctypes.c_int
    lib.tfs_graph_validate.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.tfs_graph_placeholders.restype = ctypes.c_int64
    lib.tfs_graph_placeholders.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
    ]
    lib.tfs_pack_ragged.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.tfs_gather_rows.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_void_p,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def parse_graph_native(
    data: bytes,
) -> Optional[List[Tuple[str, str, List[str], Dict[str, bytes]]]]:
    """Parse GraphDef wire bytes with the C++ parser; validate (duplicate
    names, dangling inputs, cycles). Returns per-node
    (name, op, inputs, {attr_key: raw AttrValue bytes}), or None if the
    native library is unavailable. Raises ValueError on malformed input."""
    lib = _load()
    if lib is None:
        return None
    err = ctypes.create_string_buffer(256)
    h = lib.tfs_graph_parse(data, len(data), err, 256)
    if not h:
        raise ValueError(f"native GraphDef parse failed: {err.value.decode()}")
    try:
        if lib.tfs_graph_validate(h, err, 256) != 0:
            raise ValueError(
                f"invalid GraphDef: {err.value.decode()} (native validation)"
            )
        n = lib.tfs_graph_num_nodes(h)
        nodes = []
        for i in range(n):
            name = lib.tfs_graph_node_name(h, i).decode()
            op = lib.tfs_graph_node_op(h, i).decode()
            inputs = [
                lib.tfs_graph_node_input(h, i, j).decode()
                for j in range(lib.tfs_graph_node_num_inputs(h, i))
            ]
            attrs: Dict[str, bytes] = {}
            for j in range(lib.tfs_graph_node_num_attrs(h, i)):
                key = lib.tfs_graph_node_attr_key(h, i, j).decode()
                alen = ctypes.c_int64()
                ptr = lib.tfs_graph_node_attr_value(h, i, j, ctypes.byref(alen))
                attrs[key] = ctypes.string_at(ptr, alen.value)
            nodes.append((name, op, inputs, attrs))
        return nodes
    finally:
        lib.tfs_graph_free(h)


def pack_ragged(cells: List[np.ndarray]) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Pack rank-1 ragged cells into (padded[n, max_len], lens[n]) with the
    C++ kernel. Returns None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(cells)
    if n == 0:
        raise ValueError("pack_ragged needs at least one cell")
    dtype = cells[0].dtype
    cells = [np.ascontiguousarray(c, dtype=dtype) for c in cells]
    lens = np.array([c.size for c in cells], dtype=np.int64)
    max_len = int(lens.max())
    out = np.empty((n, max_len), dtype=dtype)
    lens_out = np.empty(n, dtype=np.int32)
    ptrs = (ctypes.c_void_p * n)(
        *[c.ctypes.data_as(ctypes.c_void_p) for c in cells]
    )
    lib.tfs_pack_ragged(
        ptrs,
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        max_len,
        dtype.itemsize,
        out.ctypes.data_as(ctypes.c_void_p),
        lens_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out, lens_out


def unpack_ragged(block: np.ndarray, lens: np.ndarray) -> Optional[List[np.ndarray]]:
    lib = _load()
    if lib is None:
        return None
    return [np.array(block[i, : lens[i]]) for i in range(len(lens))]


def gather_rows(data: np.ndarray, idx: np.ndarray) -> Optional[np.ndarray]:
    """out[i] = data[idx[i]] via the native memcpy kernel."""
    lib = _load()
    if lib is None:
        return None
    data = np.ascontiguousarray(data)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    n = len(idx)
    row_bytes = data.itemsize * int(np.prod(data.shape[1:], initial=1))
    out = np.empty((n,) + data.shape[1:], dtype=data.dtype)
    lib.tfs_gather_rows(
        data.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        row_bytes,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out
