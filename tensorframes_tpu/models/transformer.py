"""Long-context transformer LM built on the framework's parallel layer.

Demonstrates the sequence-parallel path end to end: attention runs as
`parallel.ring.ring_attention` — sequence sharded over the mesh's
``data`` axis, K/V rotating over ICI — so context length scales with the
number of chips (peak activation memory per chip is O(seq/ndev)).
Without a mesh it falls back to full attention on one device.

Kept deliberately small (pre-LN, learned positions, SGD) — it is the
framework's long-context *capability* witness, not a SOTA recipe.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.ring import _ring_shard, full_attention, ring_attention

__all__ = ["TransformerLM"]


def _layer_norm(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


class TransformerLM:
    def __init__(
        self,
        vocab: int = 128,
        d_model: int = 64,
        n_heads: int = 4,
        n_layers: int = 2,
        max_seq: int = 1024,
        seed: int = 0,
    ):
        if d_model % n_heads:
            raise ValueError("d_model must divide n_heads")
        self.vocab, self.d_model = vocab, d_model
        self.n_heads, self.n_layers = n_heads, n_layers
        self.head_dim = d_model // n_heads
        key = jax.random.PRNGKey(seed)

        def init(key, shape, scale):
            return jax.random.normal(key, shape, jnp.float32) * scale

        keys = iter(jax.random.split(key, 4 + 6 * n_layers))
        p: Dict[str, jax.Array] = {
            "embed": init(next(keys), (vocab, d_model), 0.02),
            "pos": init(next(keys), (max_seq, d_model), 0.02),
            "ln_f_g": jnp.ones((d_model,)),
            "ln_f_b": jnp.zeros((d_model,)),
        }
        s = 1.0 / np.sqrt(d_model)
        for i in range(n_layers):
            p[f"l{i}_qkv"] = init(next(keys), (d_model, 3 * d_model), s)
            p[f"l{i}_proj"] = init(next(keys), (d_model, d_model), s)
            p[f"l{i}_mlp_up"] = init(next(keys), (d_model, 4 * d_model), s)
            p[f"l{i}_mlp_down"] = init(next(keys), (4 * d_model, d_model), s)
            p[f"l{i}_ln1"] = jnp.ones((2, d_model)) * jnp.array([[1.0], [0.0]])
            p[f"l{i}_ln2"] = jnp.ones((2, d_model)) * jnp.array([[1.0], [0.0]])
        self.params = p

    # ------------------------------------------------------------------
    def _attention(self, q, k, v, mesh: Optional[Mesh]):
        """(S, H, hd) -> (S, H, hd); ring attention per head when a mesh
        is given, full attention otherwise."""
        qh = jnp.swapaxes(q, 0, 1)  # (H, S, hd)
        kh = jnp.swapaxes(k, 0, 1)
        vh = jnp.swapaxes(v, 0, 1)
        if mesh is not None:
            att = jax.vmap(
                lambda a, b, c: ring_attention(a, b, c, mesh, causal=True)
            )(qh, kh, vh)
        elif jax.default_backend() == "tpu":
            from ..ops.pallas_kernels import flash_attention

            att = jax.vmap(
                lambda a, b, c: flash_attention(a, b, c, causal=True)
            )(qh, kh, vh)
        else:
            att = jax.vmap(
                lambda a, b, c: full_attention(a, b, c, causal=True)
            )(qh, kh, vh)
        return jnp.swapaxes(att, 0, 1)

    def apply(self, params, tokens, mesh: Optional[Mesh] = None):
        """tokens: (S,) int32 -> logits (S, vocab)."""
        S = tokens.shape[0]
        h = params["embed"][tokens] + params["pos"][:S]
        for i in range(self.n_layers):
            g1, b1 = params[f"l{i}_ln1"]
            x = _layer_norm(h, g1, b1)
            qkv = x @ params[f"l{i}_qkv"]  # (S, 3*D)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            shape = (S, self.n_heads, self.head_dim)
            att = self._attention(
                q.reshape(shape), k.reshape(shape), v.reshape(shape), mesh
            )
            h = h + att.reshape(S, self.d_model) @ params[f"l{i}_proj"]
            g2, b2 = params[f"l{i}_ln2"]
            x = _layer_norm(h, g2, b2)
            h = h + jax.nn.gelu(x @ params[f"l{i}_mlp_up"]) @ params[f"l{i}_mlp_down"]
        h = _layer_norm(h, params["ln_f_g"], params["ln_f_b"])
        return h @ params["embed"].T

    def loss(self, params, tokens, mesh: Optional[Mesh] = None):
        """Next-token cross-entropy over a (S,) sequence."""
        logits = self.apply(params, tokens[:-1], mesh)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, tokens[1:, None], axis=1)
        )

    def train_step(self, params, tokens, lr=1e-2, mesh: Optional[Mesh] = None):
        loss, grads = jax.value_and_grad(self.loss)(params, tokens, mesh)
        new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new, loss

    # ------------------------------------------------------------------
    # Combined DP x SP x TP training step over a ("data","seq","model")
    # mesh: batch sharded over "data", sequence over "seq" (ring
    # attention), heads/FFN/vocab over "model" (Megatron-style column/row
    # splits with psum combines). The reference has no parallelism beyond
    # Spark data partitioning (SURVEY.md §2.5); this is the framework's
    # all-axes-at-once witness.
    # ------------------------------------------------------------------
    def _layout_table(self):
        """Single schema all three layout views derive from: rows are
        (flat param name, "rep"|"shd", layout key, to-layout shape or
        None, from-layout shape or None, PartitionSpec)."""
        from jax.sharding import PartitionSpec as P

        D, H, hd = self.d_model, self.n_heads, self.head_dim
        rows = [
            ("embed", "rep", "embed", None, None, P()),
            ("pos", "rep", "pos", None, None, P()),
            ("ln_f_g", "rep", "ln_f_g", None, None, P()),
            ("ln_f_b", "rep", "ln_f_b", None, None, P()),
        ]
        for i in range(self.n_layers):
            rows += [
                (f"l{i}_ln1", "rep", f"l{i}_ln1", None, None, P()),
                (f"l{i}_ln2", "rep", f"l{i}_ln2", None, None, P()),
                (f"l{i}_qkv", "shd", f"l{i}_qkv",
                 (D, 3, H, hd), (D, 3 * D), P(None, None, "model", None)),
                (f"l{i}_proj", "shd", f"l{i}_proj",
                 (H, hd, D), (D, D), P("model", None, None)),
                (f"l{i}_mlp_up", "shd", f"l{i}_up",
                 None, None, P(None, "model")),
                (f"l{i}_mlp_down", "shd", f"l{i}_down",
                 None, None, P("model", None)),
            ]
        return rows

    def device_layout(self, params) -> Dict[str, Dict[str, jax.Array]]:
        """Re-layout ``params`` for the 3D-sharded step: ``rep`` holds
        logically replicated tensors, ``shd`` holds model-axis-sharded
        ones (qkv/proj reshaped so the head axis is shardable)."""
        out = {"rep": {}, "shd": {}}
        for flat, kind, key, to_shape, _, _ in self._layout_table():
            v = params[flat]
            out[kind][key] = v if to_shape is None else jnp.reshape(v, to_shape)
        return out

    def merge_layout(self, layout) -> Dict[str, jax.Array]:
        """Inverse of `device_layout` (gathers back the flat param dict)."""
        p = {}
        for flat, kind, key, _, from_shape, _ in self._layout_table():
            v = layout[kind][key]
            p[flat] = v if from_shape is None else jnp.reshape(v, from_shape)
        return p

    def _layout_specs(self):
        out = {"rep": {}, "shd": {}}
        for _, kind, key, _, _, spec in self._layout_table():
            out[kind][key] = spec
        return out

    def sharded_train_step_3d(self, mesh: Mesh, lr: float = 1e-2):
        """One jitted SGD step over a ("data","seq","model") mesh.

        tokens: (batch, seq) int32, batch % data == 0, seq % seq_axis == 0;
        all `seq` positions are consumed (position t predicts t+1; the
        final global position is loss-masked). Gradient correctness under
        manual sharding: backprop is linear in cotangents, so per-shard
        partial grads sum to the true grad — replicated params psum over
        all three axes, model-sharded params over ("data","seq") only.
        The vocab axis of the tied output projection is sharded over
        "model" so no loss-path work is duplicated across TP shards.
        """
        from jax import lax, shard_map
        from jax.sharding import PartitionSpec as P

        D, H, hd, V = self.d_model, self.n_heads, self.head_dim, self.vocab
        n_seq = mesh.shape["seq"]
        mp = mesh.shape["model"]
        if H % mp or V % mp:
            raise ValueError(
                f"n_heads={H} and vocab={V} must divide model axis {mp}"
            )
        v_per = V // mp
        scale = 1.0 / np.sqrt(hd)
        ring = functools.partial(
            _ring_shard, axis_name="seq", causal=True, scale=scale
        )

        def local_loss(lp, toks):
            rep, shd = lp["rep"], lp["shd"]
            B, S = toks.shape  # local shard sizes
            if S * n_seq > rep["pos"].shape[0]:
                raise ValueError(
                    f"sequence length {S * n_seq} exceeds max_seq "
                    f"{rep['pos'].shape[0]} (dynamic_slice would silently "
                    "clamp and reuse positions)"
                )
            sidx = lax.axis_index("seq")
            midx = lax.axis_index("model")
            pos0 = sidx * S
            zero = jnp.zeros((), pos0.dtype)
            h = rep["embed"][toks] + lax.dynamic_slice(
                rep["pos"], (pos0, zero), (S, D)
            )[None]
            for i in range(self.n_layers):
                g1, b1 = rep[f"l{i}_ln1"]
                x = _layer_norm(h, g1, b1)
                qkv = jnp.einsum("bsd,dchk->cbhsk", x, shd[f"l{i}_qkv"])
                att = jax.vmap(jax.vmap(ring))(qkv[0], qkv[1], qkv[2])
                h = h + lax.psum(
                    jnp.einsum("bhsk,hkd->bsd", att, shd[f"l{i}_proj"]),
                    "model",
                )
                g2, b2 = rep[f"l{i}_ln2"]
                x = _layer_norm(h, g2, b2)
                u = jax.nn.gelu(x @ shd[f"l{i}_up"])
                h = h + lax.psum(u @ shd[f"l{i}_down"], "model")
            hf = _layer_norm(h, rep["ln_f_g"], rep["ln_f_b"])
            logits = hf @ lax.dynamic_slice(
                rep["embed"], (midx * v_per, zero), (v_per, D)
            ).T  # (B, S, V/mp)
            # next-token targets: shift left, final column comes from the
            # right ring neighbor (the global last position is masked out)
            nxt = lax.ppermute(
                toks[:, :1], "seq",
                [((j + 1) % n_seq, j) for j in range(n_seq)],
            )
            tgt = jnp.concatenate([toks[:, 1:], nxt], axis=1)
            gpos = pos0 + jnp.arange(S)
            w = (gpos < S * n_seq - 1).astype(jnp.float32)
            # cross-entropy over the vocab-sharded logits
            m = lax.pmax(
                lax.stop_gradient(jnp.max(logits, -1)), "model"
            )
            se = lax.psum(
                jnp.sum(jnp.exp(logits - m[..., None]), -1), "model"
            )
            idx = tgt - midx * v_per
            in_rng = (idx >= 0) & (idx < v_per)
            safe = jnp.clip(idx, 0, v_per - 1)
            val = jnp.take_along_axis(logits, safe[..., None], -1)[..., 0]
            tgt_logit = lax.psum(jnp.where(in_rng, val, 0.0), "model")
            ll = tgt_logit - m - jnp.log(se)  # (B, S)
            num = lax.psum(jnp.sum(ll * w[None]), ("data", "seq"))
            # the count only varies over "seq" (it comes from axis_index
            # alone); cast it varying over "data" so one psum counts every
            # (batch, position) pair
            den = lax.psum(
                lax.pcast(
                    jnp.sum(jnp.broadcast_to(w[None], ll.shape)),
                    "data", to="varying",
                ),
                ("data", "seq"),
            )
            return -num / den

        def step(lp, toks):
            # with VMA tracking on (check_vma=True), shard_map autodiff
            # accounts for replication: grads of replicated params arrive
            # already summed over all mesh axes, grads of model-sharded
            # params arrive per-shard — no manual grad psums.
            loss, g = jax.value_and_grad(local_loss)(lp, toks)
            new = jax.tree_util.tree_map(lambda p, gg: p - lr * gg, lp, g)
            return new, loss

        specs = self._layout_specs()
        return jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(specs, P("data", "seq")),
                out_specs=(specs, P()),
            )
        )
