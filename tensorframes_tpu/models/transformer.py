"""Long-context transformer LM built on the framework's parallel layer.

Demonstrates the sequence-parallel path end to end: attention runs as
`parallel.ring.ring_attention` — sequence sharded over the mesh's
``data`` axis, K/V rotating over ICI — so context length scales with the
number of chips (peak activation memory per chip is O(seq/ndev)).
Without a mesh it falls back to full attention on one device.

Kept deliberately small (pre-LN, learned positions, SGD) — it is the
framework's long-context *capability* witness, not a SOTA recipe.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.ring import full_attention, ring_attention

__all__ = ["TransformerLM"]


def _layer_norm(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


class TransformerLM:
    def __init__(
        self,
        vocab: int = 128,
        d_model: int = 64,
        n_heads: int = 4,
        n_layers: int = 2,
        max_seq: int = 1024,
        seed: int = 0,
    ):
        if d_model % n_heads:
            raise ValueError("d_model must divide n_heads")
        self.vocab, self.d_model = vocab, d_model
        self.n_heads, self.n_layers = n_heads, n_layers
        self.head_dim = d_model // n_heads
        key = jax.random.PRNGKey(seed)

        def init(key, shape, scale):
            return jax.random.normal(key, shape, jnp.float32) * scale

        keys = iter(jax.random.split(key, 4 + 6 * n_layers))
        p: Dict[str, jax.Array] = {
            "embed": init(next(keys), (vocab, d_model), 0.02),
            "pos": init(next(keys), (max_seq, d_model), 0.02),
            "ln_f_g": jnp.ones((d_model,)),
            "ln_f_b": jnp.zeros((d_model,)),
        }
        s = 1.0 / np.sqrt(d_model)
        for i in range(n_layers):
            p[f"l{i}_qkv"] = init(next(keys), (d_model, 3 * d_model), s)
            p[f"l{i}_proj"] = init(next(keys), (d_model, d_model), s)
            p[f"l{i}_mlp_up"] = init(next(keys), (d_model, 4 * d_model), s)
            p[f"l{i}_mlp_down"] = init(next(keys), (4 * d_model, d_model), s)
            p[f"l{i}_ln1"] = jnp.ones((2, d_model)) * jnp.array([[1.0], [0.0]])
            p[f"l{i}_ln2"] = jnp.ones((2, d_model)) * jnp.array([[1.0], [0.0]])
        self.params = p

    # ------------------------------------------------------------------
    def _attention(self, q, k, v, mesh: Optional[Mesh]):
        """(S, H, hd) -> (S, H, hd); ring attention per head when a mesh
        is given, full attention otherwise."""
        qh = jnp.swapaxes(q, 0, 1)  # (H, S, hd)
        kh = jnp.swapaxes(k, 0, 1)
        vh = jnp.swapaxes(v, 0, 1)
        if mesh is not None:
            att = jax.vmap(
                lambda a, b, c: ring_attention(a, b, c, mesh, causal=True)
            )(qh, kh, vh)
        elif jax.default_backend() == "tpu":
            from ..ops.pallas_kernels import flash_attention

            att = jax.vmap(
                lambda a, b, c: flash_attention(a, b, c, causal=True)
            )(qh, kh, vh)
        else:
            att = jax.vmap(
                lambda a, b, c: full_attention(a, b, c, causal=True)
            )(qh, kh, vh)
        return jnp.swapaxes(att, 0, 1)

    def apply(self, params, tokens, mesh: Optional[Mesh] = None):
        """tokens: (S,) int32 -> logits (S, vocab)."""
        S = tokens.shape[0]
        h = params["embed"][tokens] + params["pos"][:S]
        for i in range(self.n_layers):
            g1, b1 = params[f"l{i}_ln1"]
            x = _layer_norm(h, g1, b1)
            qkv = x @ params[f"l{i}_qkv"]  # (S, 3*D)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            shape = (S, self.n_heads, self.head_dim)
            att = self._attention(
                q.reshape(shape), k.reshape(shape), v.reshape(shape), mesh
            )
            h = h + att.reshape(S, self.d_model) @ params[f"l{i}_proj"]
            g2, b2 = params[f"l{i}_ln2"]
            x = _layer_norm(h, g2, b2)
            h = h + jax.nn.gelu(x @ params[f"l{i}_mlp_up"]) @ params[f"l{i}_mlp_down"]
        h = _layer_norm(h, params["ln_f_g"], params["ln_f_b"])
        return h @ params["embed"].T

    def loss(self, params, tokens, mesh: Optional[Mesh] = None):
        """Next-token cross-entropy over a (S,) sequence."""
        logits = self.apply(params, tokens[:-1], mesh)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, tokens[1:, None], axis=1)
        )

    def train_step(self, params, tokens, lr=1e-2, mesh: Optional[Mesh] = None):
        loss, grads = jax.value_and_grad(self.loss)(params, tokens, mesh)
        new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new, loss
