"""Flagship model: a dense MLP classifier, in both framework forms.

Covers BASELINE config #3 ("map_rows 3-layer MLP inference — dense matmul
per row"): the model can be *frozen* into a GraphDef-compatible scoring
graph (constants baked in, the moral equivalent of the reference's
variable freezing, `core.py:42-56`) and scored over a TensorFrame with
`map_rows`/`map_blocks`; and it is *trainable* as a pure-JAX step with
DP+TP sharding over a 2-D mesh (`parallel.mesh.mesh_2d`) for the
multi-chip path.

TPU notes: matmuls run in the MXU; training defaults to float32 params
with bfloat16 activations off (kept simple and exact for parity tests) —
flip ``compute_dtype=jnp.bfloat16`` for peak throughput.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph import builder as dsl
from ..schema import ScalarType

__all__ = ["MLP"]


class MLP:
    """Dense ``sizes[0] -> ... -> sizes[-1]`` classifier with ReLU hiddens."""

    def __init__(
        self,
        sizes: Sequence[int],
        seed: int = 0,
        param_dtype=jnp.float32,
        compute_dtype=None,
    ):
        if len(sizes) < 2:
            raise ValueError("need at least input and output sizes")
        self.sizes = list(sizes)
        self.param_dtype = param_dtype
        self.compute_dtype = compute_dtype or param_dtype
        key = jax.random.PRNGKey(seed)
        self.params: List[Tuple[jax.Array, jax.Array]] = []
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            w = jax.random.normal(k, (fan_in, fan_out), param_dtype)
            w = w * jnp.sqrt(2.0 / fan_in)
            b = jnp.zeros((fan_out,), param_dtype)
            self.params.append((w, b))

    # -- pure forward ----------------------------------------------------
    def apply(self, params, x):
        h = x.astype(self.compute_dtype)
        n = len(params)
        for i, (w, b) in enumerate(params):
            h = h @ w.astype(self.compute_dtype) + b.astype(self.compute_dtype)
            if i < n - 1:
                h = jax.nn.relu(h)
        return h  # logits

    def loss(self, params, x, y):
        logits = self.apply(params, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    def train_step(self, params, x, y, lr=1e-2):
        loss, grads = jax.value_and_grad(self.loss)(params, x, y)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    # -- DP+TP sharding over a 2-D mesh ---------------------------------
    def param_specs(self) -> List[Tuple[P, P]]:
        """Megatron-style TP: odd layers shard columns, even layers shard
        rows, so activations alternate replicated/sharded and XLA inserts
        a single psum per pair."""
        specs = []
        for i in range(len(self.sizes) - 1):
            if i % 2 == 0:
                specs.append((P(None, "model"), P("model")))
            else:
                specs.append((P("model", None), P()))
        return specs

    def shard_params(self, params, mesh: Mesh):
        return [
            (
                jax.device_put(w, NamedSharding(mesh, ws)),
                jax.device_put(b, NamedSharding(mesh, bs)),
            )
            for (w, b), (ws, bs) in zip(params, self.param_specs())
        ]

    def sharded_train_step(self, mesh: Mesh, lr=1e-2):
        """jitted training step with DP over rows + TP over features.

        Inputs: x sharded P('data', None), y sharded P('data'); params
        sharded per `param_specs`. XLA lowers the gradient psum over the
        ``data`` axis and the activation psums over ``model`` onto ICI.
        """
        pspecs = [
            (NamedSharding(mesh, ws), NamedSharding(mesh, bs))
            for ws, bs in self.param_specs()
        ]
        xspec = NamedSharding(mesh, P("data", None))
        yspec = NamedSharding(mesh, P("data"))

        def step(params, x, y):
            return self.train_step(params, x, y, lr)

        return jax.jit(
            step,
            in_shardings=(pspecs, xspec, yspec),
            out_shardings=(pspecs, NamedSharding(mesh, P())),
        )

    # -- frozen scoring graph (GraphDef interchange) ---------------------
    def scoring_graph(
        self, input_name: str = "features", block: bool = True
    ) -> dsl.Tensor:
        """Freeze params into a builder-DSL graph: Placeholder -> MatMul ->
        BiasAdd -> Relu -> ... -> Softmax, named ``probs``. Exportable to
        GraphDef wire bytes and runnable by any GraphDef consumer."""
        from ..schema import Shape

        st = ScalarType.from_np_dtype(np.dtype(self.param_dtype))
        shape = (
            Shape((None, self.sizes[0])) if block else Shape((self.sizes[0],))
        )
        x = dsl.placeholder(st, shape, name=input_name)
        h = x
        n = len(self.params)
        for i, (w, b) in enumerate(self.params):
            wc = dsl.constant(np.asarray(w), name=f"w{i}")
            bc = dsl.constant(np.asarray(b), name=f"b{i}")
            if block:
                h = dsl.matmul(h, wc)
            else:
                # per-row: x is a vector; lift to 1xN for the MXU
                h = dsl.matmul(dsl.reshape(h, [1, -1]), wc)
            h = dsl._nary("BiasAdd", [h, bc])
            if i < n - 1:
                h = dsl.relu(h)
        if not block:
            h = dsl.reshape(h, [self.sizes[-1]])
        return dsl.softmax(h).named("probs")
