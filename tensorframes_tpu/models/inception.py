"""Inception-family conv net as a frozen GraphDef scoring graph.

BASELINE config #5 is "Frozen Inception-v3 GraphDef scoring over an
image-tensor DataFrame": the reference's `read_image.py` snippet shipped
a frozen Inception GraphDef to executors and scored image rows. Here the
same shape of workload is native: `InceptionLite` builds an
Inception-v3-style network (conv/BN/relu stem, parallel-branch inception
blocks with 1x1 / stacked-3x3 / pool-projection branches, channel
concat, global average pool, softmax head) directly as TF-compatible
NodeDefs via the builder DSL, with frozen weights baked in as Const
nodes. The exported GraphDef runs through the same importer/lowering as
any TF-frozen model — every op it uses (Conv2D, FusedBatchNorm, MaxPool,
AvgPool, ConcatV2, BiasAdd, Relu, Reshape, MatMul, Softmax) is
conformance-tested against real TF in test_tf_conformance.py.

Channel widths are scaled down from the 299x299 original so tests stay
fast; the topology (branch structure, strides, padding) follows the
Inception-v3 figure-5 blocks.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..graph import builder as dsl
from ..proto.graphdef import AttrValue
from ..schema import ScalarType, Shape

__all__ = ["InceptionLite"]


class InceptionLite:
    def __init__(
        self,
        image_size: int = 32,
        channels: int = 3,
        width: int = 8,
        num_classes: int = 10,
        seed: int = 0,
    ):
        self.image_size = image_size
        self.channels = channels
        self.width = width
        self.num_classes = num_classes
        self._rng = np.random.RandomState(seed)

    # -- frozen-weight helpers ------------------------------------------
    def _conv_weights(self, kh, kw, cin, cout):
        scale = np.sqrt(2.0 / (kh * kw * cin))
        return (self._rng.randn(kh, kw, cin, cout) * scale).astype(np.float32)

    def _conv_bn_relu(self, x, kh, kw, cin, cout, stride=1, padding="SAME"):
        """Conv2D -> FusedBatchNorm (inference) -> Relu, like Inception's
        conv2d_bn building block."""
        w = dsl.constant(self._conv_weights(kh, kw, cin, cout))
        conv = dsl.Tensor(
            "Conv2D",
            [x, w],
            {
                "T": AttrValue.of_type(ScalarType.float32),
                "strides": AttrValue.of_ints([1, stride, stride, 1]),
                "padding": AttrValue.of_string(padding),
            },
            ScalarType.float32,
        )
        scale = dsl.constant(np.ones(cout, np.float32))
        offset = dsl.constant(
            (0.1 * self._rng.randn(cout)).astype(np.float32)
        )
        mean = dsl.constant(
            (0.01 * self._rng.randn(cout)).astype(np.float32)
        )
        var = dsl.constant(
            (1.0 + 0.1 * self._rng.rand(cout)).astype(np.float32)
        )
        bn = dsl.Tensor(
            "FusedBatchNorm",
            [conv, scale, offset, mean, var],
            {
                "T": AttrValue.of_type(ScalarType.float32),
                "epsilon": AttrValue("f", 1e-3),
                "is_training": AttrValue.of_bool(False),
            },
            ScalarType.float32,
        )
        return dsl.relu(bn)

    def _pool(self, x, op, ksize, stride, padding="SAME"):
        return dsl.Tensor(
            op,
            [x],
            {
                "T": AttrValue.of_type(ScalarType.float32),
                "ksize": AttrValue.of_ints([1, ksize, ksize, 1]),
                "strides": AttrValue.of_ints([1, stride, stride, 1]),
                "padding": AttrValue.of_string(padding),
            },
            ScalarType.float32,
        )

    def _inception_block(self, x, cin, b1, b3r, b3, b5r, b5, bp) -> dsl.Tensor:
        """Inception-v3 figure-5 block: four parallel branches, channel
        concat. b5 is realized as two stacked 3x3s (the v3 factorization)."""
        with dsl.scope("branch1x1"):
            br1 = self._conv_bn_relu(x, 1, 1, cin, b1)
        with dsl.scope("branch3x3"):
            t = self._conv_bn_relu(x, 1, 1, cin, b3r)
            br3 = self._conv_bn_relu(t, 3, 3, b3r, b3)
        with dsl.scope("branch5x5"):
            t = self._conv_bn_relu(x, 1, 1, cin, b5r)
            t = self._conv_bn_relu(t, 3, 3, b5r, b5)
            br5 = self._conv_bn_relu(t, 3, 3, b5, b5)
        with dsl.scope("branch_pool"):
            p = self._pool(x, "AvgPool", 3, 1)
            brp = self._conv_bn_relu(p, 1, 1, cin, bp)
        return dsl.concat([br1, br3, br5, brp], axis=3)

    # -- full scoring graph ---------------------------------------------
    def scoring_graph(self, input_name: str = "images") -> dsl.Tensor:
        """Placeholder (None, H, W, C) -> 'probs' (None, num_classes)."""
        w = self.width
        x = dsl.placeholder(
            ScalarType.float32,
            Shape((None, self.image_size, self.image_size, self.channels)),
            name=input_name,
        )
        with dsl.scope("stem"):
            h = self._conv_bn_relu(x, 3, 3, self.channels, w, stride=2,
                                   padding="VALID")
            h = self._conv_bn_relu(h, 3, 3, w, 2 * w)
            h = self._pool(h, "MaxPool", 3, 2)
        cin = 2 * w
        with dsl.scope("mixed0"):
            h = self._inception_block(h, cin, w, w, 2 * w, w // 2, w, w)
        cin = w + 2 * w + w + w
        with dsl.scope("mixed1"):
            h = self._inception_block(h, cin, w, w, 2 * w, w // 2, w, w)
        cin = w + 2 * w + w + w
        with dsl.scope("head"):
            # global average pool via Mean over spatial dims
            idx = dsl.constant(np.array([1, 2], np.int32))
            pooled = dsl.Tensor(
                "Mean",
                [h, idx],
                {
                    "T": AttrValue.of_type(ScalarType.float32),
                    "keep_dims": AttrValue.of_bool(False),
                    "Tidx": AttrValue.of_type(ScalarType.int32),
                },
                ScalarType.float32,
            )  # (None, cin)
            fc_w = dsl.constant(
                (self._rng.randn(cin, self.num_classes)
                 / np.sqrt(cin)).astype(np.float32)
            )
            fc_b = dsl.constant(np.zeros(self.num_classes, np.float32))
            logits = dsl.Tensor(
                "BiasAdd",
                [dsl.matmul(pooled, fc_w), fc_b],
                {"T": AttrValue.of_type(ScalarType.float32)},
                ScalarType.float32,
            )
        return dsl.softmax(logits).named("probs")
