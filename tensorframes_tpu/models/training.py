"""Optimizer-agnostic training steps (optax integration).

The model zoo's built-in `train_step`s use plain SGD to stay
dependency-light; real training wants momentum/Adam/weight-decay
schedules. `make_train_step` pairs any ``loss_fn(params, *batch)`` with
any `optax.GradientTransformation` into one jitted step. Under a mesh,
pass sharded params — `init_opt_state` runs `tx.init` eagerly so every
moment buffer inherits its parameter's sharding, and updates stay
device-local (DP grads still ride the mesh collectives inside
``loss_fn``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax

__all__ = ["make_train_step", "init_opt_state"]


def init_opt_state(tx, params):
    """`tx.init` EAGERLY: eager `zeros_like` preserves each parameter's
    sharding, so moment buffers land on the param's devices. (Under jit
    the auto-partitioner is free to commit the fresh zeros elsewhere.)"""
    return tx.init(params)


def make_train_step(
    loss_fn: Callable[..., Any],
    tx,
    donate: bool = True,
) -> Callable[..., Tuple[Any, Any, jax.Array]]:
    """Build ``step(params, opt_state, *batch) -> (params, opt_state, loss)``.

    ``tx`` is an `optax.GradientTransformation`; ``donate=True`` donates
    the params/opt-state buffers so updates happen in place in HBM
    (halves peak memory for large models).
    """
    import optax

    def step(params, opt_state, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)
