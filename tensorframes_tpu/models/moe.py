"""Mixture-of-Experts FFN with expert parallelism over the mesh.

Experts shard over a mesh axis (each device owns E/ndev experts); a
token's output is the gate-weighted sum of its top-k experts' FFNs, and
the cross-device combine is a single `psum` over the expert axis.

This implementation uses dense masked dispatch (every shard evaluates
its local experts over the full token set, masked by the routing
weights): numerically exact, simple, and collective-light (one psum).
The capacity-based `all_to_all` dispatch that avoids the masked compute
is the optimization path (see `parallel.ring.seq_all_to_all` for the
primitive it would build on).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["MoEFFN"]


class MoEFFN:
    """Top-k gated expert FFNs: x -> sum_k gate_k * FFN_{e_k}(x)."""

    def __init__(
        self,
        d_model: int = 32,
        d_hidden: int = 64,
        num_experts: int = 8,
        top_k: int = 2,
        seed: int = 0,
    ):
        self.d_model, self.d_hidden = d_model, d_hidden
        self.num_experts, self.top_k = num_experts, top_k
        key = jax.random.PRNGKey(seed)
        kg, k1, k2 = jax.random.split(key, 3)
        s1 = 1.0 / np.sqrt(d_model)
        s2 = 1.0 / np.sqrt(d_hidden)
        self.params = {
            "gate": jax.random.normal(kg, (d_model, num_experts), jnp.float32) * s1,
            "w1": jax.random.normal(
                k1, (num_experts, d_model, d_hidden), jnp.float32
            ) * s1,
            "w2": jax.random.normal(
                k2, (num_experts, d_hidden, d_model), jnp.float32
            ) * s2,
        }

    def _route(self, params, x):
        """Top-k softmax routing weights, (tokens, experts), rows sum to 1
        over the selected experts."""
        logits = x @ params["gate"]  # (N, E)
        topv, topi = lax.top_k(logits, self.top_k)
        gates = jax.nn.softmax(topv, axis=-1)  # (N, k)
        dense = jnp.zeros_like(logits)
        for k in range(self.top_k):
            dense = dense.at[jnp.arange(x.shape[0]), topi[:, k]].add(
                gates[:, k]
            )
        return dense  # (N, E) with <=k nonzeros per row

    @staticmethod
    def _expert_ffn(w1, w2, x):
        return jax.nn.gelu(x @ w1) @ w2

    def apply(self, params, x):
        """Single-device reference: evaluate all experts densely."""
        weights = self._route(params, x)  # (N, E)
        outs = jax.vmap(self._expert_ffn, in_axes=(0, 0, None))(
            params["w1"], params["w2"], x
        )  # (E, N, d)
        return jnp.einsum("ne,end->nd", weights, outs)

    def apply_ep(self, params, x, mesh: Mesh, axis: str = "model"):
        """Expert-parallel: experts sharded over ``axis``; one psum."""
        n_shard = mesh.shape[axis]
        if self.num_experts % n_shard:
            raise ValueError(
                f"num_experts {self.num_experts} must divide the "
                f"{axis!r} axis size {n_shard}"
            )

        def shard_body(w1, w2, gate, xs):
            weights = self._route({"gate": gate}, xs)  # (N, E) full routing
            shard = lax.axis_index(axis)
            e_per = self.num_experts // n_shard
            # this shard's slice of the routing matrix
            local_w = lax.dynamic_slice_in_dim(
                weights, shard * e_per, e_per, axis=1
            )  # (N, e_per)
            outs = jax.vmap(self._expert_ffn, in_axes=(0, 0, None))(
                w1, w2, xs
            )  # (e_per, N, d)
            local = jnp.einsum("ne,end->nd", local_w, outs)
            return lax.psum(local, axis)

        espec = P(axis)
        return shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(espec, espec, P(), P()),
            out_specs=P(),
            check_vma=False,
        )(params["w1"], params["w2"], params["gate"], x)
