"""Model zoo: framework-native models in both forms (trainable JAX +
frozen GraphDef-compatible scoring graphs)."""

from .inception import InceptionLite
from .kmeans import kmeans
from .mlp import MLP
from .moe import MoEFFN
from .training import init_opt_state, make_train_step
from .transformer import TransformerLM

__all__ = ["MLP", "kmeans", "TransformerLM", "InceptionLite", "MoEFFN", "make_train_step", "init_opt_state"]
