"""Distributed k-means over TensorFrames.

Re-design of the reference's flagship demo (`kmeans_demo.py`): per-block
assignment + `unsorted_segment_sum` partial aggregation inside a trimmed
`map_blocks`, then a block reduce — the exact same verb composition, with
the block graph compiled by XLA and the cross-block combine riding the
mesh when one is given.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .. import api
from ..frame import TensorFrame
from ..graph import builder as dsl
from ..schema import ScalarType

__all__ = ["kmeans"]


def _assignment_graph(k: int, dim: int, np_dtype, feature_col: str):
    """Trimmed map_blocks graph: block of points -> (k, dim+1) partials.

    Emits one row per centroid: [sum of assigned points, count] — the
    `unsorted_segment_sum` trick from the reference demo. Centers enter as
    a *bound placeholder*, not a constant: the reference demo rebuilds the
    graph with new centers each Lloyd iteration (`kmeans_demo.py`), which
    under XLA would recompile every step; a binding is a jit argument, so
    the executable compiles once and is reused for all iterations.
    """
    st = ScalarType.from_np_dtype(np.dtype(np_dtype))
    from ..schema import Shape

    pts = dsl.placeholder(st, Shape((None, dim)), name=feature_col)
    c = dsl.placeholder(st, Shape((k, dim)), name="centers")
    # squared distances via ||p||^2 - 2 p.c + ||c||^2 ; argmin over k
    p2 = dsl.reduce_sum(dsl.square(pts), axes=[1], keep_dims=True)  # (n,1)
    pc = dsl.matmul(pts, c, transpose_b=True)  # (n,k)
    c2 = dsl.reduce_sum(dsl.square(c), axes=[1])  # (k,)
    d = p2 - 2.0 * pc + c2  # broadcast -> (n,k)
    assign = dsl.argmin(d, axis=1)
    assign32 = dsl.cast(assign, ScalarType.int32)
    # concat [points, 1] so one segment-sum yields sums AND counts
    ones_n = dsl.reduce_sum(pts * 0.0, axes=[1], keep_dims=True) + 1.0  # (n,1)
    aug = dsl.concat([pts, ones_n], axis=1)  # (n, dim+1)
    partial = dsl.unsorted_segment_sum(aug, assign32, k).named("partial")
    return partial


def kmeans(
    frame: TensorFrame,
    feature_col: str,
    k: int,
    num_iters: int = 10,
    seed: int = 0,
    mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Lloyd iterations; returns (centers, counts)."""
    if num_iters < 1:
        raise ValueError("kmeans needs num_iters >= 1")
    col = frame.column(feature_col)
    if not col.is_dense or col.cell_shape.rank != 1:
        raise ValueError("kmeans needs a dense rank-1 feature column")
    # host copy for center bookkeeping (col.values may be a device array)
    data = np.asarray(col.values)
    n, dim = data.shape
    rng = np.random.RandomState(seed)
    centers = data[rng.choice(n, size=k, replace=False)].copy()
    counts = np.zeros(k)

    partial = _assignment_graph(k, dim, data.dtype, feature_col)
    for _ in range(num_iters):
        # trimmed map: each block contributes k partial rows; with a mesh,
        # blocks shard across devices and partials combine on host (tiny).
        part_frame = api.map_blocks(
            partial, frame, trim=True, mesh=mesh,
            bindings={"centers": centers},
        )
        parts = np.asarray(part_frame["partial"].values).reshape(-1, k, dim + 1)
        totals = parts.sum(axis=0)  # (k, dim+1)
        counts = totals[:, -1]
        sums = totals[:, :-1]
        nonempty = counts > 0
        centers = centers.copy()
        centers[nonempty] = sums[nonempty] / counts[nonempty, None]
    return centers, counts
