"""Plain-function front-end kernels + ragged bucketed execution.

The TPU-native tracer front-end: verbs accept a plain Python function
over column arrays (no GraphDef needed) — `_map_blocks_fn` /
`_map_rows_fn` are their execution kernels, and `_run_ragged_bucketed`
is the shape-bucketing plan shared by the graph and function per-row
paths (and, per shard, by `parallel.verbs._ragged_per_shard`).
Extracted from `api.py` (round-4 verdict task 7); `api.py` re-exports
every name, so `api._run_ragged_bucketed`-style references and the
public behavior are unchanged.
"""

from __future__ import annotations

import inspect
from typing import Callable, Dict, List, Optional, Tuple, Union

import jax
import numpy as np

from .frame import Column, TensorFrame

from .runtime.executor import Executor  # noqa: F401  (annotations)

# late-bound: api imports this module at its end; helper lookups
# resolve at call time through the module object
from . import api as _api


def _empty_fn_outputs(jfn, feeds: List) -> Dict[str, np.ndarray]:
    """Zero-row outputs for a function-front-end verb over an all-empty
    frame: trace the jitted fn on zero-row feeds (shape-level only). The
    lead dim is forced to 0 — a trimmed reduction traced on a zero-row
    block can still report a nonzero lead (e.g. keepdims sums)."""
    shapes = jax.eval_shape(jfn, *feeds)
    return {
        n: np.zeros((0,) + s.shape[1:], s.dtype) for n, s in shapes.items()
    }


def _fn_feed_columns(
    fn: Callable, frame: TensorFrame, bound: Optional[set] = None
) -> List[str]:
    params = [
        p.name
        for p in inspect.signature(fn).parameters.values()
        if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
    ]
    missing = [
        p for p in params if p not in frame.info and p not in (bound or ())
    ]
    if missing:
        raise ValueError(
            f"function front-end: parameters {missing} have no matching "
            f"columns (columns: {frame.columns})"
        )
    return params


def _fn_outputs_to_dict(res, what: str) -> Dict[str, "jax.Array"]:
    if isinstance(res, dict):
        if not res:
            # an empty dict would sail through the per-block loops and
            # only explode later (e.g. the mesh trim path's np.cumsum
            # over a None block size); fail at the verb with the cause
            raise ValueError(
                f"{what}: the function graph returned an empty dict; it "
                "must return at least one named output array (output "
                "names become column names)"
            )
        return res
    raise ValueError(
        f"{what}: a function graph must return a dict of named output "
        "arrays (output names become column names)"
    )


def _map_blocks_fn(
    fn: Callable,
    frame: TensorFrame,
    trim: bool,
    ex: Executor,
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
    devices=None,
) -> TensorFrame:
    bindings = {k: np.asarray(v) for k, v in (bindings or {}).items()}
    params = _fn_feed_columns(fn, frame, bound=set(bindings))
    unknown = sorted(set(bindings) - set(params))
    if unknown:
        raise ValueError(
            f"bindings {unknown} do not match any function parameter "
            f"(parameters: {params})"
        )
    _api._require_dense(frame, [p for p in params if p not in bindings], "map_blocks")
    # ex.jit, not jax.jit: under the native default this compiles
    # through the C++ PJRT host like the graph front-end does
    jfn = ex.jit(lambda *args: _fn_outputs_to_dict(fn(*args), "map_blocks"))
    # function-front-end dispatches block-schedule exactly like the
    # graph path (the native executor opts out via supports_scheduling)
    from .runtime import scheduler as _sched

    sched = _sched.schedule_for(frame, devices=devices, executor=ex)
    acc: Dict[str, List[np.ndarray]] = {}
    out_sizes: List[int] = []
    for bi in range(frame.num_blocks):
        lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
        if lo == hi:
            out_sizes.append(0)
            continue
        call = sched.bind(bi, jfn) if sched is not None else jfn
        outs = call(
            *[
                bindings[p] if p in bindings else frame.column(p).values[lo:hi]
                for p in params
            ]
        )
        bsize = None
        for name, o in outs.items():
            if o.ndim == 0:
                raise ValueError(
                    f"map_blocks: output {name!r} must have a lead (row) dim"
                    + ("" if trim else "; use trim=True for reductions")
                )
            if not trim and o.shape[0] != hi - lo:
                raise ValueError(
                    f"map_blocks: output {name!r} does not preserve the "
                    "block row count; use trim=True"
                )
            if trim:
                if bsize is None:
                    bsize = o.shape[0]
                elif o.shape[0] != bsize:
                    raise ValueError(
                        "map_blocks(trim): outputs disagree on row count"
                    )
            acc.setdefault(name, []).append(o)
        out_sizes.append(bsize if trim else hi - lo)
    if not acc:  # every block empty: zero-row outputs, names from a trace
        empties = _empty_fn_outputs(
            jfn,
            [
                bindings[p] if p in bindings else frame.column(p).values[:0]
                for p in params
            ],
        )
        acc = {n: [v] for n, v in empties.items()}
    anchor = sched.anchor_device() if sched is not None else None
    out_cols = [
        Column(n, _api._concat_parts(parts, anchor))
        for n, parts in acc.items()
    ]
    offsets = list(np.cumsum([0] + out_sizes)) if trim else frame.offsets
    return _api._output_frame(frame, out_cols, append_input=not trim, offsets=offsets)


def _run_ragged_bucketed(
    vfn,
    columns: List[Column],
    nrows: int,
    out_names_hint: Optional[List[str]] = None,
    defer: bool = False,
) -> Dict[str, List[np.ndarray]]:
    """Shape-bucketed execution for ragged rows: group rows by their joint
    cell-shape signature, run ONE vmapped XLA call per bucket, scatter the
    results back in row order.

    This is the shape-bucketing plan of SURVEY §7 "hard parts" — the ragged
    analogue of the reference's per-row variable-length support
    (`TFDataOps.scala:90-103`) without its one-session.run-per-row cost.
    Bucket sizes are padded to the next power of two (duplicating the last
    row; padded outputs discarded) so the compile count is bounded by
    O(#distinct cell shapes x log max bucket) instead of O(#rows).

    ``vfn`` is a vmapped callable returning either a tuple (graph path,
    ``out_names_hint`` gives the names) or a dict (function front-end).
    Returns name -> list of per-row output cells (row order).

    ``defer=True`` returns the raw chunk pairs (name -> [(row indices,
    DEVICE array)]) without assembling: the mesh ragged path
    (`parallel.verbs._ragged_per_shard`) runs this once per device and
    must not block on device-to-host transfer between shards — it
    collects every shard's chunks and assembles once at the end via
    `_assemble_ragged`.
    """
    cells = [c.values if c.is_dense else c.ragged for c in columns]
    buckets: Dict[Tuple, List[int]] = {}
    for i in range(nrows):
        key = tuple(cc[i].shape for cc in cells)
        buckets.setdefault(key, []).append(i)

    # (idxs, chunk) pairs per output name; assembled dense below when all
    # buckets agree on the output cell shape, else per-row (ragged result)
    chunks: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
    for idxs in buckets.values():
        nb = len(idxs)
        padded = 1 << (nb - 1).bit_length()
        take = idxs + [idxs[-1]] * (padded - nb)
        feeds = [
            cc[np.asarray(take)]
            if col.is_dense
            else np.stack([cc[i] for i in take])
            for col, cc in zip(columns, cells)
        ]
        outs = vfn(*feeds)
        if not isinstance(outs, dict):
            outs = dict(zip(out_names_hint, outs))
        idx_arr = np.asarray(idxs)
        for name, o in outs.items():
            # keep the DEVICE array (slicing is lazy): converting here
            # would block on transfer before the next bucket dispatches,
            # serializing the whole plan — with per-shard device
            # placement (parallel.verbs._ragged_per_shard) every
            # device's buckets must be in flight before any fetch
            chunks.setdefault(name, []).append((idx_arr, o[:nb]))

    if defer:
        return chunks
    return _assemble_ragged(chunks, nrows)


def _assemble_ragged(
    chunks: Dict[str, List[Tuple[np.ndarray, "jax.Array"]]], nrows: int
) -> Dict[str, Union[np.ndarray, List[np.ndarray]]]:
    """Scatter bucketed chunk outputs back into row order. Device->host
    conversion happens HERE, after every bucket (and, for the mesh path,
    every shard's device) has been dispatched."""
    per_row: Dict[str, Union[np.ndarray, List[np.ndarray]]] = {}
    for name, pairs in chunks.items():
        cell_shapes = {o.shape[1:] for _, o in pairs}
        if len(cell_shapes) == 1:  # uniform outputs: one dense scatter
            shape = next(iter(cell_shapes))
            res = np.empty((nrows,) + shape, dtype=pairs[0][1].dtype)
            for idx_arr, o in pairs:
                res[idx_arr] = np.asarray(o)
            per_row[name] = res
        else:
            rows: List[Optional[np.ndarray]] = [None] * nrows
            for idx_arr, o in pairs:
                o = np.asarray(o)
                for j, i in enumerate(idx_arr):
                    rows[i] = o[j]
            per_row[name] = rows
    return per_row


def _map_rows_fn(
    fn: Callable,
    frame: TensorFrame,
    ex: "Executor",
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
    devices=None,
) -> TensorFrame:
    """Function front-end for map_rows: fn(cell, ...) -> dict of outputs.

    jit/vmap preserve dict outputs, so output names come from the traced
    dict directly — the user function is invoked exactly once per trace.
    ``bindings`` match function PARAMETER names and are held constant
    across rows (vmap in_axes=None), like the graph front-end.
    """
    bindings = {k: np.asarray(v) for k, v in (bindings or {}).items()}
    params = _fn_feed_columns(fn, frame, bound=set(bindings))
    unknown = sorted(set(bindings) - set(params))
    if unknown:
        raise ValueError(
            f"bindings {unknown} do not match any function parameter "
            f"(parameters: {params})"
        )
    col_params = [p for p in params if p not in bindings]
    if bindings and not col_params:
        raise ValueError(
            "map_rows: every parameter is bound, so nothing varies per "
            "row; use map_blocks (or call the function directly)"
        )
    dense = all(frame.column(p).is_dense for p in col_params)
    if bindings and not dense:
        raise ValueError(
            "map_rows: bindings are not supported with ragged feed "
            "columns; densify the columns or bake the values as constants"
        )

    def wrapped(*cells):
        return _fn_outputs_to_dict(fn(*cells), "map_rows")

    def _feeds(lo, hi):
        return [
            bindings[p] if p in bindings else frame.column(p).values[lo:hi]
            for p in params
        ]

    acc: Dict[str, List[np.ndarray]] = {}
    if dense:
        in_axes = tuple(None if p in bindings else 0 for p in params)
        vfn = ex.jit(jax.vmap(wrapped, in_axes=in_axes))
        from .runtime import scheduler as _sched

        sched = _sched.schedule_for(frame, devices=devices, executor=ex)
        for bi in range(frame.num_blocks):
            lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
            if lo == hi:
                continue
            call = sched.bind(bi, vfn) if sched is not None else vfn
            outs = call(*_feeds(lo, hi))
            for n, o in outs.items():
                acc.setdefault(n, []).append(o)
        if not acc:
            empties = _empty_fn_outputs(vfn, _feeds(0, 0))
            acc = {n: [v] for n, v in empties.items()}
        anchor = sched.anchor_device() if sched is not None else None
        out_cols = [
            Column(n, _api._concat_parts(parts, anchor))
            for n, parts in acc.items()
        ]
    else:
        vfn = ex.jit(jax.vmap(wrapped))
        if frame.nrows == 0:
            # 0-row ragged columns: synthesize zero-row feeds from the
            # declared cell shapes (unknown dims collapse to 0)
            feeds = [
                np.zeros(
                    (0,)
                    + tuple(
                        0 if d is None else d
                        for d in frame.column(p).cell_shape.dims
                    ),
                    dtype=frame.column(p).dtype.np_dtype,
                )
                for p in params
            ]
            per_out = {n: v for n, v in _empty_fn_outputs(vfn, feeds).items()}
        else:
            per_out = _run_ragged_bucketed(
                vfn, [frame.column(p) for p in params], frame.nrows
            )
        out_cols = [Column(n, vals) for n, vals in per_out.items()]
    return _api._output_frame(frame, out_cols, append_input=True)


