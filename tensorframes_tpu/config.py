"""Config layer: the knobs the reference hardcoded or lacked entirely.

SURVEY.md §5 flags the reference's config story as "essentially none"
(hardcoded UDAF buffer size, graph-to-file flag) and calls for a real
layer: mesh shape, dtype policy, block sizing, compilation cache. This
module is that layer — a process-global `Config` with scoped overrides::

    tfs.config.update(matmul_precision="default")   # fast MXU bf16 passes
    with tfs.config.override(default_num_blocks=16):
        ...

Knobs:
- ``matmul_precision``: "highest" (default — numerical parity with the
  reference's fp32 TF kernels) | "default" (MXU-native bf16 passes) |
  "tensorfloat32". Consumed by the MatMul/Conv lowerings.
- ``default_num_blocks``: blocks for frames built without an explicit
  partitioning (None = single block).
- ``default_mesh``: mesh used by verbs when ``mesh=`` is omitted
  (None = single device).
- ``compilation_cache_dir``: enables JAX's persistent compilation cache
  (survives process restarts — the reference re-imported its graph into
  a fresh TF session per task, `DebugRowOps.scala:790`).
- ``aggregate_buffer_rows``: host-side group batching threshold (the
  reference's hardcoded ``bufferSize=10``, `DebugRowOps.scala:580`).

Pin tracking (the autotuner's "never fight a pin" substrate): every
knob set EXPLICITLY — through `update()`, inside an `override()` scope,
or seeded from a well-formed ``TFS_*`` env var at import — is recorded
as *pinned* (`explicit_keys()` / `is_explicit()`). The closed-loop
autotuner (`runtime.autotune`) writes knobs only through `set_tuned()`,
which refuses pinned keys, so an operator's explicit setting always
wins over a tuned one; `tuned()` reports what the tuner currently owns
and `reset_tuning()` restores those knobs to their (env-seeded)
defaults. A later `update()` of a tuned knob converts it to a pin.

Env parsing: every ``TFS_*`` scalar override reads through the
malformed-env-falls-back-to-default helpers below — a typo'd value
must never break the package import (the histogram_buckets JSON knob
established the convention); a malformed value is ignored entirely
(default value, no pin).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional

__all__ = [
    "Config",
    "get",
    "update",
    "override",
    "explicit_keys",
    "is_explicit",
    "set_tuned",
    "tuned",
    "default_value",
    "reset_tuning",
]


# fields whose env var was present AND parsed cleanly during Config
# construction — the import-time pin seed (a malformed value falls back
# to the default and pins nothing). Populated by the _env_* helpers;
# re-running a default_factory (e.g. `Config()` inside default_value)
# only re-adds the same names, so the set is stable.
_ENV_SEEDED: set = set()


def _env_bool(var: str, default: bool, field: str) -> bool:
    import os

    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    _ENV_SEEDED.add(field)
    return raw.lower() not in ("0", "false", "off")


def _env_int(var: str, default: int, field: str,
             minimum: Optional[int] = None) -> int:
    import os

    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except (TypeError, ValueError):
        return default  # malformed env never breaks the import
    _ENV_SEEDED.add(field)
    return v if minimum is None else max(minimum, v)


def _env_float(var: str, default: float, field: str,
               minimum: Optional[float] = None) -> float:
    import os

    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except (TypeError, ValueError):
        return default  # malformed env never breaks the import
    _ENV_SEEDED.add(field)
    return v if minimum is None else max(minimum, v)


def _env_str(var: str, default: str, field: str,
             mapping: Optional[dict] = None,
             choices: Optional[tuple] = None) -> str:
    import os

    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    low = raw.lower()
    val = mapping.get(low, low) if mapping is not None else raw
    if choices is not None and val not in choices:
        return default  # an out-of-vocabulary value is malformed:
        # default value, no pin — same contract as a typo'd number
    _ENV_SEEDED.add(field)
    return val


def _env_histogram_buckets():
    """Seed ``histogram_buckets`` from TFS_HISTOGRAM_BUCKETS (a JSON
    dict: family or metric name -> ascending boundary list). Malformed
    JSON must never break the package import — it reads as None (the
    built-in defaults) and the bad value is simply ignored."""
    import json
    import os

    raw = os.environ.get("TFS_HISTOGRAM_BUCKETS", "")
    if not raw:
        return None
    try:
        val = json.loads(raw)
        if isinstance(val, dict):
            _ENV_SEEDED.add("histogram_buckets")
            return val
        return None
    except Exception:
        return None


@dataclasses.dataclass
class Config:
    # Every SCALAR knob seeds from TFS_<KNOB> through the _env_*
    # helpers (tfslint TFS003 enforces the parity): a deployment tunes
    # any of them without a code change, a well-formed value pins the
    # knob against the autotuner, and a malformed value falls back to
    # the default without breaking the import.
    matmul_precision: str = dataclasses.field(
        default_factory=lambda: _env_str(
            "TFS_MATMUL_PRECISION", "highest", "matmul_precision",
            mapping={}, choices=("highest", "default", "tensorfloat32"),
        )
    )
    default_num_blocks: Optional[int] = None
    default_mesh: Optional[object] = None
    compilation_cache_dir: Optional[str] = None
    aggregate_buffer_rows: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_AGGREGATE_BUFFER_ROWS", 10, "aggregate_buffer_rows",
            minimum=1,
        )
    )
    # aggregate: above this many DISTINCT group sizes, graphs classified
    # as Reduce(rowwise(placeholder), axis=0) (api._chunk_combiners:
    # Sum/Min/Max/Prod, float Mean) switch from the exact
    # one-vmap-per-size plan to pow2 chunk decomposition with a
    # derived-monoid combine — compiles O(log max_size) instead of
    # O(#distinct sizes). Unclassifiable graphs always stay on the exact
    # plan (correct, but compile-heavy under pathological distributions).
    aggregate_exact_size_limit: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_AGGREGATE_EXACT_SIZE_LIMIT", 32,
            "aggregate_exact_size_limit", minimum=0,
        )
    )
    # aggregate: sort-free fast path for classified monoid graphs — the
    # rowwise transform runs over ALL rows in one XLA call and one
    # device segment_<op> per fetch replaces the argsort + per-size
    # plans entirely (host argsort dominated keyed aggregation at the
    # 10M-row TPU benchmark scale). Accumulation order differs from the
    # exact whole-group plan (FP reassociation). Off = exact/chunk plans.
    aggregate_segment_fast: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_AGGREGATE_SEGMENT_FAST", True, "aggregate_segment_fast"
        )
    )
    # aggregate: float Sum/Mean segment tables with at most this many
    # DISTINCT KEYS compute as a one-hot matmul on the MXU instead of
    # XLA's scatter-add lowering of segment_sum (scatter serializes on
    # TPU; a (rows x keys) @ (rows x cell) matmul does not). None =
    # auto: 256 on TPU, 0 elsewhere — on CPU/GPU scatter-add is fast
    # and the matmul's extra FLOPs only cost (measured ~28x slower on
    # CPU). Set an int to force either way.
    aggregate_onehot_keys: Optional[int] = None
    # Executor compile-cache bound (LRU): long-lived services whose
    # graphs / shapes drift would otherwise accumulate compiled
    # executables forever (the cache is never cleared implicitly).
    executor_cache_entries: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_EXECUTOR_CACHE_ENTRIES", 512, "executor_cache_entries",
            minimum=1,
        )
    )
    # Shape-bucketed block execution (`shape_policy`): pad every block
    # feed up to a geometric row-bucket ladder and mask/slice the pad
    # rows, so a workload with arbitrary drifting block sizes compiles
    # O(log max-block-rows) XLA programs per graph instead of one per
    # distinct size. Applies only to dispatches proven safe (row-local
    # map graphs; monoid-classified reduces); everything else runs the
    # exact unbucketed program regardless of this knob. Float sum/mean
    # under bucketing reduce over a padded axis, so XLA may reassociate
    # the accumulation (the same tolerance as stacking block partials);
    # turn this off when exact FP accumulation order outweighs bounded
    # compile counts. Env override TFS_SHAPE_BUCKETING ("0" disables)
    # seeds the initial value, mirroring TFS_NATIVE_EXECUTOR.
    shape_bucketing: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_SHAPE_BUCKETING", True, "shape_bucketing"
        )
    )
    # Bucket-ladder geometry: rung k holds min * growth^k rows. Growth
    # trades pad waste (worst-case (growth-1)/growth of a block) against
    # ladder length (compile count ~ log_growth(max rows)).
    shape_bucket_growth: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_SHAPE_BUCKET_GROWTH", 2.0, "shape_bucket_growth",
            # the ladder needs growth > 1 to be finite; 1.05 is the
            # autotuner's own SAFETY_BOUNDS floor
            minimum=1.05,
        )
    )
    shape_bucket_min: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_SHAPE_BUCKET_MIN", 8, "shape_bucket_min", minimum=1
        )
    )
    # Multi-device block scheduler (`runtime.scheduler`): non-mesh verbs
    # spread per-block dispatches across jax.local_devices() (size-aware
    # largest-first placement; feeds are device_put onto the assigned
    # device and jit's committed-input semantics place the execution).
    # Values:
    #   "auto" — (default) schedule when >1 local device exists
    #   "on"   — schedule onto all local devices even when there is one
    #            (forces the scheduled code path — explicit device_put,
    #            per-device ledgers)
    #   "off"  — every dispatch lands on the default device (the
    #            pre-scheduler behavior)
    #   "global" — route eligible verbs through `GlobalFrame` SPMD
    #            dispatch (`globalframe.py`): columns become single
    #            jax.Arrays sharded over a data mesh, one compiled
    #            program spans every device, classified reduces lower
    #            to in-program collectives. Ineligible dispatches
    #            (non-row-local maps, unclassified reduces, ragged/
    #            string feeds, frames below global_frame_min_rows)
    #            fall back to per-block scheduling exactly as "auto".
    # mesh= always takes precedence, and the native executor is never
    # scheduled (it owns its own PJRT host). Per-call override: the
    # devices= parameter on every non-mesh verb. Under scheduling, jit
    # specializes each program per device it touches, so compile counts
    # are bounded by ndev x the single-device count (ndev x ladder rungs
    # under shape_bucketing). Reduce combines stay bit-identical for
    # min/max and within the documented reassociation tolerance for
    # float sum/mean. Env override TFS_BLOCK_SCHEDULER seeds the initial
    # value, mirroring TFS_SHAPE_BUCKETING.
    block_scheduler: str = dataclasses.field(
        default_factory=lambda: _env_str(
            "TFS_BLOCK_SCHEDULER", "auto", "block_scheduler",
            mapping={
                "0": "off", "false": "off", "1": "on", "true": "on",
            },
        )
    )
    # Global sharded frames (`globalframe.py`): a frame routed through
    # the GlobalFrame SPMD path (block_scheduler="global", or the
    # auto-route on eligible verbs) must carry at least this many rows;
    # below it the per-shard work would be dominated by sharded
    # device_put + collective latency and the verb falls back to
    # per-block scheduling. Tunable by the closed-loop autotuner (an
    # explicit update()/override()/env set pins it like every knob).
    # Env override TFS_GLOBAL_FRAME_MIN_ROWS seeds the initial value.
    global_frame_min_rows: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_GLOBAL_FRAME_MIN_ROWS", 2048, "global_frame_min_rows",
            minimum=0,
        )
    )
    # Auto-batched per-row control flow (`graph/vectorize.py`): graphs
    # containing functionalized `_Cond`/`_While` whose branch/body
    # subgraphs are row-local classify as row-local themselves and lower
    # to masked dense programs (cond -> both-branches + select on the
    # batched predicate, while -> convergence-masked fixed point), so
    # branchy per-row graphs ride the bucket ladder, serving batcher and
    # the GlobalFrame one-dispatch SPMD path instead of falling back to
    # unbatched execution. Off = the historical conservative classifier
    # (any control-flow node disqualifies the graph) and scalar-pred-only
    # lowering. Env override TFS_ROW_VECTORIZE ("0" disables) seeds the
    # initial value.
    row_vectorize: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_ROW_VECTORIZE", True, "row_vectorize"
        )
    )
    # Pipelined ingest (`ingest.pipeline`): stream verbs and the io
    # readers run shard discovery -> parallel decode -> H2D transfer ->
    # compute as concurrently-executing stages over bounded queues.
    # Off = stage-serial: the SAME stage functions run inline on the
    # consumer thread (no overlap) — the A/B baseline
    # benchmarks/ingest_bench.py measures against, and an escape hatch
    # for single-core hosts where pipeline threads only add overhead.
    # Env override TFS_INGEST_PIPELINE ("0" disables) seeds the initial
    # value, mirroring TFS_SHAPE_BUCKETING.
    ingest_pipeline: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_INGEST_PIPELINE", True, "ingest_pipeline"
        )
    )
    # Delivery-queue bound of the ingest pipeline (was the hard-coded
    # depth=1 of `_prefetch_iter`): how many decoded chunks may sit
    # ready ahead of the consumer. Peak buffered chunks for the
    # canonical discovery -> decode(W) -> transfer chain is
    # W + 2*depth + 4 (see ingest/pipeline.py's bound derivation —
    # asserted in tests/test_ingest.py), so host memory for a stream
    # is ~that many chunks regardless of stream length. Raise it when
    # chunk decode time is bursty; lower it when chunks are huge. Env
    # override TFS_STREAM_PREFETCH_DEPTH seeds the initial value.
    stream_prefetch_depth: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_STREAM_PREFETCH_DEPTH", 1, "stream_prefetch_depth",
            minimum=1,
        )
    )
    # Durable-stream commit cadence (`runtime.checkpoint`): a streaming
    # reduce given checkpoint= without an explicit checkpoint_every=
    # atomically commits its manifest + partial table after this many
    # FOLDED chunks (empty chunks advance the watermark but do not
    # count as folds). Lower = tighter recovery point, more fsyncs;
    # the checkpoint bench asserts the default's commit overhead stays
    # <= 5% of stream wall time. Env override
    # TFS_STREAM_CHECKPOINT_EVERY seeds the initial value.
    stream_checkpoint_every: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_STREAM_CHECKPOINT_EVERY", 16, "stream_checkpoint_every",
            minimum=1,
        )
    )
    # Pipelined plan execution (`lazy.force` over the stage-graph
    # runtime from `ingest.pipeline`): block feed-prep (slice + pad +
    # device staging) for block k+1 runs on a pipeline stage while the
    # consumer thread dispatches block k, so H2D transfer overlaps
    # compute across the plan's blocks. Off = the historical
    # block-serial loop (prep and dispatch interleaved on one thread) —
    # the A/B baseline benchmarks/plan_pipeline_bench.py measures
    # against, and the single-core escape hatch. Env override
    # TFS_PLAN_PIPELINE ("0" disables) seeds the initial value.
    plan_pipeline: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_PLAN_PIPELINE", True, "plan_pipeline"
        )
    )
    # Delivery-queue bound of the plan pipeline: how many prepared
    # blocks may sit ready ahead of the dispatching consumer. The
    # prep stage holds at most depth+2 blocks' feeds beyond the
    # in-flight dispatch (the ingest pipeline's W + 2*depth + 4 queue
    # bound with W=1), so peak extra host memory is ~that many blocks.
    # Env override TFS_PLAN_PIPELINE_DEPTH seeds the initial value.
    plan_pipeline_depth: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_PLAN_PIPELINE_DEPTH", 2, "plan_pipeline_depth",
            minimum=1,
        )
    )
    # Relational plan optimizer (`graph.optimizer`): rewrite the plan
    # DAG built by filter/select/group_by/sort_by/join before
    # execution — common-subplan dedup, filter-below-map reordering,
    # predicate pushdown into the ingest scan, column pruning, and
    # map fusion across relational boundaries. Every rewrite is priced
    # against the cost ledger's residuals-corrected throughput and
    # accepted only when the modeled plan cost strictly drops; off =
    # execute the verbs exactly as written (the A/B baseline
    # benchmarks/relational_bench.py measures against). Env override
    # TFS_PLAN_OPTIMIZER ("0" disables) seeds the initial value.
    plan_optimizer: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_PLAN_OPTIMIZER", True, "plan_optimizer"
        )
    )
    # Default filter selectivity the plan optimizer assumes when a
    # `filter(...)` carries no explicit selectivity= hint: the modeled
    # fraction of rows that survive the predicate. Feeds the cost
    # estimates in tfs.explain() and the accept/reject pricing of
    # pushdown rewrites; it never affects results, only plan choice.
    # Env override TFS_PLAN_SELECTIVITY_DEFAULT seeds the initial
    # value.
    plan_selectivity_default: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_PLAN_SELECTIVITY_DEFAULT", 0.5,
            "plan_selectivity_default", minimum=0.0,
        )
    )
    # Materialization cache byte budget (`runtime.materialize`): total
    # on-disk bytes the content-keyed result cache may hold; LRU
    # entries evict to stay under it. 0 (the default) disables the
    # cache entirely — zero behavior change, no files written. Keys
    # are (data fingerprint, program fingerprint, config digest), so a
    # numerics-relevant knob change can never serve a stale result.
    # Env override TFS_MATERIALIZE_CACHE_BYTES seeds the initial value.
    materialize_cache_bytes: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_MATERIALIZE_CACHE_BYTES", 0, "materialize_cache_bytes",
            minimum=0,
        )
    )
    # Materialization cache directory: where `runtime.materialize`
    # commits its entries (atomic temp-file + os.replace, same
    # discipline as runtime.checkpoint). Empty (the default) = a
    # process-private temp directory created on first store (entries
    # die with the process); set a persistent path to share warm
    # results across processes. Env override TFS_MATERIALIZE_CACHE_DIR
    # seeds the initial value.
    materialize_cache_dir: str = dataclasses.field(
        default_factory=lambda: _env_str(
            "TFS_MATERIALIZE_CACHE_DIR", "", "materialize_cache_dir"
        )
    )
    # Decode thread-pool width for multi-file datasets
    # (`ingest.dataset.IngestStream`): 0 = auto (min(4, host cores)).
    # pyarrow releases the GIL inside Parquet/IPC decode, so workers
    # scale with real cores; each worker holds at most one chunk plus
    # the shared reorder window. Env override TFS_INGEST_DECODE_WORKERS
    # seeds the initial value.
    ingest_decode_workers: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_INGEST_DECODE_WORKERS", 0, "ingest_decode_workers"
        )
    )
    # One-time per-program warning when jit has compiled more than this
    # many distinct input shapes for a single cached program — the
    # recompile-storm signal `compile_count` (distinct lowered callables)
    # structurally cannot see. 0 disables the check.
    recompile_warn_shapes: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_RECOMPILE_WARN_SHAPES", 16, "recompile_warn_shapes",
            minimum=0,
        )
    )
    # Telemetry master switch (`utils.telemetry`): span recording,
    # histogram observation and jax TraceAnnotation mirroring for every
    # verb / plan stage / per-block dispatch / compile event. Off =
    # near-zero overhead (a span site costs one config read and a no-op
    # context); the legacy flat counters (`stats()`) stay live either
    # way. Env override TFS_TELEMETRY ("0" disables) seeds the initial
    # value, mirroring TFS_SHAPE_BUCKETING.
    telemetry: bool = dataclasses.field(
        default_factory=lambda: _env_bool("TFS_TELEMETRY", True, "telemetry")
    )
    # Span ring-buffer bound (`utils.telemetry`): a long-lived service
    # keeps the freshest N spans and counts what fell off — memory stays
    # O(N) no matter how long the process runs. Applied on
    # `telemetry.reset()` (the ring is rebuilt at the current value).
    telemetry_ring_entries: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_TELEMETRY_RING_ENTRIES", 8192, "telemetry_ring_entries",
            minimum=1,
        )
    )
    # Live telemetry endpoint (`utils.telemetry_http`): when non-zero,
    # `tfs.telemetry.serve()` (and the import-time auto-start) binds an
    # HTTP server on this port serving /metrics (Prometheus text),
    # /healthz (device-health JSON), /diagnostics (JSON) and /trace
    # (Chrome trace JSON). 0 (default) = off; `serve(port=0)` picks an
    # ephemeral port explicitly. Binds 127.0.0.1 unless
    # telemetry_host says otherwise — the endpoint exposes program
    # fingerprints and device state and has NO auth, so exposing it
    # beyond localhost is a deliberate operator decision. Env override
    # TFS_TELEMETRY_PORT seeds the initial value (set it and the
    # package import starts the server).
    telemetry_port: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_TELEMETRY_PORT", 0, "telemetry_port"
        )
    )
    telemetry_host: str = dataclasses.field(
        default_factory=lambda: _env_str(
            "TFS_TELEMETRY_HOST", "127.0.0.1", "telemetry_host"
        )
    )
    # Histogram bucket boundaries (`utils.telemetry`): override the
    # fixed per-family ladders by bucket FAMILY ("seconds" | "rows" |
    # "bytes" | "fraction") or by exact metric name ("verb_seconds" —
    # the name wins over its family). Value: ascending float list. The
    # built-in defaults are unchanged (exports stay byte-identical
    # until an operator opts in); a service whose latencies live in one
    # default bucket (ms-scale serving) sets e.g.
    # {"verb_seconds": [1e-4, 5e-4, 1e-3, ...]}. Applies to histogram
    # series CREATED after the change (existing series keep the ladder
    # they were born with — fixed buckets are what make concurrent
    # observation and merge well-defined); `telemetry.reset()` rebuilds
    # everything at the current value. Env override
    # TFS_HISTOGRAM_BUCKETS (JSON dict) seeds the initial value.
    histogram_buckets: Optional[dict] = dataclasses.field(
        default_factory=_env_histogram_buckets
    )
    # Flight-recorder master switch (`runtime.blackbox`): when True
    # (the default — the recorder is always armed), a typed fault
    # escaping the runtime (deadline, shed, eviction, OOM exhaustion,
    # checkpoint corruption, serving 5xx) captures an incident bundle.
    # Costs nothing fault-free: capture only runs on fault paths, and
    # disabling turns even those into one attribute read. Env override
    # TFS_INCIDENT_CAPTURE ("0" disables) seeds the initial value.
    incident_capture: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_INCIDENT_CAPTURE", True, "incident_capture"
        )
    )
    # Incident bundle directory (`runtime.blackbox`): where postmortem
    # bundles are committed (CheckpointStore atomic protocol). Empty
    # (the default) = a process-private temp directory created on first
    # capture (bundles die with the test/process); operators set a
    # persistent path so 3am evidence survives a restart. Env override
    # TFS_INCIDENT_DIR seeds the initial value.
    incident_dir: str = dataclasses.field(
        default_factory=lambda: _env_str(
            "TFS_INCIDENT_DIR", "", "incident_dir"
        )
    )
    # Trailing evidence window (`runtime.blackbox`), seconds: a bundle
    # keeps only span-ring events that overlap the last
    # incident_window_s before the fault, and stamps its metric deltas
    # with the age they actually cover. Env override
    # TFS_INCIDENT_WINDOW_S seeds the initial value.
    incident_window_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_INCIDENT_WINDOW_S", 60.0, "incident_window_s",
            minimum=0.0,
        )
    )
    # Incident store bundle-count budget (`runtime.blackbox`): the
    # least-recently-written bundles are pruned to keep at most this
    # many on disk. 0 = no count bound (bytes still bound the store).
    # Env override TFS_INCIDENT_MAX_BUNDLES seeds the initial value.
    incident_max_bundles: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_INCIDENT_MAX_BUNDLES", 32, "incident_max_bundles",
            minimum=0,
        )
    )
    # Incident store byte budget (`runtime.blackbox`): total on-disk
    # bundle bytes; LRU bundles prune to stay under it, and a capture
    # whose payload cannot fit at all degrades to a counted
    # incidents_suppressed{reason="store"} — 0 is a real zero-byte
    # quota (every capture suppresses; the ENOSPC degradation path),
    # not "unlimited". Env override TFS_INCIDENT_MAX_BYTES seeds the
    # initial value.
    incident_max_bytes: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_INCIDENT_MAX_BYTES", 67108864, "incident_max_bytes",
            minimum=0,
        )
    )
    # Per-fingerprint incident rate limit (`runtime.blackbox`),
    # seconds: a repeat of the same incident fingerprint (trigger x
    # program x fault class) within this window increments
    # incidents_suppressed{reason="rate_limit"} instead of writing —
    # a shed storm leaves ONE bundle plus a count. 0 disables
    # dedup (every capture writes). Env override
    # TFS_INCIDENT_RATE_LIMIT_S seeds the initial value.
    incident_rate_limit_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_INCIDENT_RATE_LIMIT_S", 30.0, "incident_rate_limit_s",
            minimum=0.0,
        )
    )
    # Cost-model accuracy warning threshold (`runtime.costmodel
    # .residuals`): a program whose span-achieved time per dispatch is
    # more than this factor away (either direction) from the cost
    # model's prediction is flagged in the diagnostics "cost-model
    # accuracy" section and in saved workload profiles. The residual is
    # RELATIVE — predictions use a per-process effective throughput
    # fitted over every attributed program, so a flag means "the model
    # misprices this program vs its peers", which is exactly what a
    # cost-based planner needs to distrust. 0 disables flagging.
    cost_residual_warn_ratio: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_COST_RESIDUAL_WARN_RATIO", 4.0,
            "cost_residual_warn_ratio", minimum=0.0,
        )
    )
    # Always-on cost/memory ledger (`runtime.costmodel`): every XLA
    # shape specialization of a cached program captures the compiler's
    # modeled flops / HBM bytes (from the lowered module's cost
    # analysis — no second XLA compile) plus exact argument/output
    # byte counts, and every dispatch counts against its shape entry,
    # so `tfs.diagnostics()` can report achieved-vs-peak fractions per
    # program fingerprint without ever re-lowering a graph. Capture
    # cost is paid only at compile events; steady-state dispatches pay
    # one dict update. Independent of `telemetry` (the ledger is on
    # even when span recording is off). Env override TFS_COST_LEDGER
    # ("0" disables) seeds the initial value.
    cost_ledger: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_COST_LEDGER", True, "cost_ledger"
        )
    )
    # Deep memory capture: additionally compile the lowered module at
    # capture time to read `memory_analysis()` (temp/scratch bytes —
    # the part of the footprint avals cannot model). DOUBLES the XLA
    # compile cost of every new program shape, so it is opt-in; with it
    # off the modeled footprint is argument + output bytes and
    # `temp_bytes` reads honest None.
    cost_ledger_memory: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_COST_LEDGER_MEMORY", False, "cost_ledger_memory"
        )
    )
    # Fault-tolerant dispatch (`runtime.faults`): every block execution
    # is a pure function of (compiled executable, block arrays) — the
    # property the reference leaned on for Spark task retry — so a
    # failed dispatch can be re-run. Errors are CLASSIFIED: only
    # ``transient`` failures (device lost/preempted, UNAVAILABLE /
    # INTERNAL / DATA_LOSS runtime statuses) consume retry attempts;
    # ``deterministic`` errors (dtype/shape bugs, check_numerics
    # FloatingPointError) surface after exactly one attempt, and
    # ``resource`` errors (RESOURCE_EXHAUSTED / OOM) trigger block
    # splitting instead (see oom_split_depth).
    #
    # block_retry_attempts: extra attempts per block dispatch for
    # transient errors (changed semantics vs the pre-classification
    # blanket retry, which burned attempts on deterministic errors too).
    block_retry_attempts: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_BLOCK_RETRY_ATTEMPTS", 3, "block_retry_attempts",
            minimum=0,
        )
    )
    # verb_retry_budget: total transient retries ONE verb call may spend
    # across all its block dispatches — bounds the worst-case stall of a
    # verb over many blocks on a flapping device.
    verb_retry_budget: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_VERB_RETRY_BUDGET", 32, "verb_retry_budget", minimum=0
        )
    )
    # Exponential backoff between transient retries: base * 2^(k-1)
    # capped at max, times a DETERMINISTIC jitter factor in
    # [1, 1+retry_jitter] seeded by (retry_seed, dispatch, attempt) —
    # reruns sleep the same schedule, so fault-injected tests reproduce.
    retry_backoff_base_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_RETRY_BACKOFF_BASE_S", 0.05, "retry_backoff_base_s",
            # a negative backoff would feed time.sleep() a ValueError
            # mid-retry — clamp, mirroring the int helpers' minimum=
            minimum=0.0,
        )
    )
    retry_backoff_max_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_RETRY_BACKOFF_MAX_S", 2.0, "retry_backoff_max_s",
            minimum=0.0,
        )
    )
    retry_jitter: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_RETRY_JITTER", 0.25, "retry_jitter", minimum=0.0
        )
    )
    retry_seed: int = dataclasses.field(
        default_factory=lambda: _env_int("TFS_RETRY_SEED", 0, "retry_seed")
    )
    # OOM graceful degradation: a resource-classified block dispatch
    # splits the block in half (down the shape-bucketing ladder) and
    # re-dispatches, up to this many recursive halvings. Row-local maps
    # concatenate the halves; monoid-classified reduces combine them
    # (size-weighted for mean); unclassifiable graphs re-raise the
    # original error exactly. 0 disables splitting.
    oom_split_depth: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_OOM_SPLIT_DEPTH", 3, "oom_split_depth", minimum=0
        )
    )
    # Device failover (`runtime.scheduler.DeviceHealth`): a transient
    # dispatch failure opens the device's circuit for this many seconds
    # (doubling on repeated failures, capped at 8x); its unissued blocks
    # re-place LPT onto healthy devices, and after the cooldown ONE
    # half-open probe dispatch re-admits it on success. Explicit
    # ``devices=`` pins opt out of failover (with a loud warning when a
    # pinned device is circuit-open).
    device_cooldown_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_DEVICE_COOLDOWN_S", 30.0, "device_cooldown_s",
            minimum=0.0,
        )
    )
    # Deadline / cancellation (`runtime.deadline`): default time budget
    # for a TOP-LEVEL verb call when no per-call timeout_s= is given
    # (0 = unbounded, the library default). The budget is an ABSOLUTE
    # deadline propagated through a contextvar, so everything a verb
    # starts (lazy force, stream chunks, combines, backoff sleeps,
    # ingest stages) shares one clock; expiry raises DeadlineExceeded
    # (classified deterministic — never burned as a retry). Env
    # override TFS_DEFAULT_VERB_TIMEOUT_S seeds the initial value.
    default_verb_timeout_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_DEFAULT_VERB_TIMEOUT_S", 0.0, "default_verb_timeout_s"
        )
    )
    # Admission control (`runtime.deadline.AdmissionController`): max
    # TOP-LEVEL verbs in flight at once (0 = unlimited). Nested verbs
    # (a stream's per-chunk reduce, a lazy terminal's force) never take
    # a second slot, so small limits cannot deadlock. Env override
    # TFS_MAX_CONCURRENT_VERBS seeds the initial value — the serving
    # lane's knob.
    max_concurrent_verbs: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_MAX_CONCURRENT_VERBS", 0, "max_concurrent_verbs"
        )
    )
    # Bounded admission wait queue: callers beyond the concurrency
    # limit queue up to this many deep; arrivals at a full queue are
    # SHED immediately with a typed OverloadError (queue depth +
    # retry-after hint from the live verb_seconds histogram). 0 = shed
    # the moment the limit is reached (no queueing). Env override
    # TFS_ADMISSION_QUEUE_LIMIT seeds the initial value — the sibling
    # of TFS_MAX_CONCURRENT_VERBS, so both admission knobs deploy
    # without code changes.
    admission_queue_limit: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_ADMISSION_QUEUE_LIMIT", 32, "admission_queue_limit"
        )
    )
    # Max seconds a queued caller waits for a slot before being shed
    # (its own deadline still applies and may fire first). 0 = wait
    # bounded only by the caller's deadline — do not combine 0 with
    # un-deadlined callers in a service, or a stuck verb strands its
    # whole queue.
    admission_wait_timeout_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_ADMISSION_WAIT_TIMEOUT_S", 30.0,
            "admission_wait_timeout_s", minimum=0.0,
        )
    )
    # Serving runtime (`serving/`): the multi-tenant front-end that
    # keeps registered endpoint programs warm and coalesces concurrent
    # small requests into one bucketed dispatch.
    #
    # serve_batch_window_ms: how long the micro-batcher holds an open
    # batch for more requests before dispatching. A batch also closes
    # EARLY the moment its row total lands exactly on a bucket-ladder
    # rung (padding waste zero — waiting longer could only push it to
    # the next rung) or reaches serve_max_batch_rows. 0 disables
    # coalescing entirely: every request dispatches alone (the A/B
    # baseline serving_bench measures against). Env override
    # TFS_SERVE_BATCH_WINDOW_MS seeds the initial value.
    serve_batch_window_ms: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_SERVE_BATCH_WINDOW_MS", 5.0, "serve_batch_window_ms"
        )
    )
    # serve_max_batch_rows: ceiling on one coalesced dispatch AND the
    # top of the bucket ladder `serving.register(warm=True)` compiles
    # at registration — requests whose batches stay under it hit only
    # warmed rungs (zero steady-state compiles, asserted by
    # serving_bench). A single oversized request still dispatches
    # (alone), paying its own compile. Env override
    # TFS_SERVE_MAX_BATCH_ROWS seeds the initial value.
    serve_max_batch_rows: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_SERVE_MAX_BATCH_ROWS", 4096, "serve_max_batch_rows"
        )
    )
    # serve_queue_limit: max requests queued per (endpoint x program)
    # batching lane; arrivals beyond it are SHED immediately with a
    # typed OverloadError (HTTP 429 + Retry-After at the server) so a
    # slow endpoint builds bounded queues, never unbounded latency.
    # 0 = unlimited (bounded only by admission control + deadlines).
    # Env override TFS_SERVE_QUEUE_LIMIT seeds the initial value so a
    # tuned deployment needs no code change.
    serve_queue_limit: int = dataclasses.field(
        default_factory=lambda: _env_int(
            "TFS_SERVE_QUEUE_LIMIT", 256, "serve_queue_limit"
        )
    )
    # serve_default_timeout_s: per-request deadline the server applies
    # when the client sends no X-TFS-Timeout-S header. Unlike
    # default_verb_timeout_s (a library-wide opt-in), a serving request
    # ALWAYS has a budget — an un-deadlined request behind a wedged
    # endpoint would strand its server thread forever. Env override
    # TFS_SERVE_DEFAULT_TIMEOUT_S seeds the initial value.
    serve_default_timeout_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_SERVE_DEFAULT_TIMEOUT_S", 30.0, "serve_default_timeout_s"
        )
    )
    # serve_warm_compile: compile every bucket-ladder rung up to
    # serve_max_batch_rows at `serving.register()` time (row-local
    # endpoints only — others cannot pad, so rung warming cannot cover
    # their request sizes). Off = first requests pay the compiles.
    serve_warm_compile: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_SERVE_WARM_COMPILE", True, "serve_warm_compile"
        )
    )
    # Device-grant watchdog (`runtime.faults.device_grant`): when > 0,
    # the scheduler's device acquisition runs under a watchdog thread
    # and falls back to the CPU backend with a loud one-time warning if
    # the accelerator backend wedges at device grant for this long
    # (the stuck-shared-TPU failure mode). 0 disables the watchdog.
    # Env override TFS_DEVICE_GRANT_TIMEOUT_S seeds the initial value.
    device_grant_timeout_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_DEVICE_GRANT_TIMEOUT_S", 0.0, "device_grant_timeout_s"
        )
    )
    # Closed-loop autotuner (`runtime.autotune`): when on, a background
    # daemon thread periodically snapshots the live workload profile
    # and nudges the UNPINNED performance knobs (bucket-ladder
    # growth/min, ingest decode workers / prefetch depth, per-endpoint
    # serving batch window, max_concurrent_verbs) toward what the
    # telemetry says the workload wants — hysteresis dead-bands + step
    # and safety bounds keep it from oscillating, and a knob set
    # explicitly (update()/override()/TFS_* env) is NEVER touched. Off
    # (the default) = zero behavior change: no thread starts and no
    # knob is ever mutated; `tfs.autotune()` stays available for
    # one-shot offline tuning either way. Env override TFS_AUTOTUNE
    # seeds the initial value.
    autotune: bool = dataclasses.field(
        default_factory=lambda: _env_bool("TFS_AUTOTUNE", False, "autotune")
    )
    # Seconds between background tuning cycles (each cycle: snapshot ->
    # recommend -> apply). Env override TFS_AUTOTUNE_INTERVAL_S.
    autotune_interval_s: float = dataclasses.field(
        default_factory=lambda: _env_float(
            "TFS_AUTOTUNE_INTERVAL_S", 30.0, "autotune_interval_s"
        )
    )
    # Debug mode: raise on NaN/Inf in any verb output (block + fetch named).
    check_numerics: bool = dataclasses.field(
        default_factory=lambda: _env_bool(
            "TFS_CHECK_NUMERICS", False, "check_numerics"
        )
    )
    # Route verbs through the C++ PJRT host (`runtime.native_executor`)
    # when no explicit executor= is passed — the SURVEY §2.4 framing:
    # the native host is the libtensorflow-equivalent spine, not an
    # opt-in. Values:
    #   "off"  — in-process JAX executor (jaxlib is itself a native
    #            runtime; this remains the safe default)
    #   "auto" — use NativeExecutor over the repo-built CPU plugin when
    #            it is present; silently fall back to in-process JAX
    #            when it is not. Mesh kinds on the single-device plugin
    #            fall back to in-process JAX per the documented
    #            NativeExecutor(jax_fallback=True) semantics (safe: the
    #            repo CPU plugin claims no shared accelerator device).
    #   "require" — like "auto" but raise if the plugin is unavailable
    #            (the CI native lane uses this so silent fallback can
    #            never mask a broken build).
    # Env override TFS_NATIVE_EXECUTOR seeds the initial value so a CI
    # lane can run the whole verb suite under the native default.
    native_executor: str = dataclasses.field(
        default_factory=lambda: _env_str(
            "TFS_NATIVE_EXECUTOR", "off", "native_executor"
        )
    )

    def lax_precision(self):
        from jax import lax

        return {
            "highest": lax.Precision.HIGHEST,
            "tensorfloat32": lax.Precision.HIGH,
            "default": lax.Precision.DEFAULT,
        }[self.matmul_precision]


_config = Config()

# ---------------------------------------------------------------------------
# pin / tuned-value bookkeeping (see the module docstring)
# ---------------------------------------------------------------------------

# one lock serializes every pin/tuned mutation (update / set_tuned /
# reset_tuning): the autotuner runs on a background thread, and the
# "pins win, always" contract needs check-then-write to be atomic —
# an operator update() racing a set_tuned() must never lose
import threading as _threading

_state_lock = _threading.Lock()

# knobs the OPERATOR set: update()/override() calls plus well-formed
# TFS_* env seeds captured while _config was constructed above. The
# autotuner must never write these.
_EXPLICIT: set = set(_ENV_SEEDED)
# knobs the AUTOTUNER currently owns -> the value it applied. Distinct
# from _EXPLICIT so diagnostics can say which values are tuned, and so
# reset_tuning() knows what to restore.
_TUNED: dict = {}

_MISSING = object()


def explicit_keys() -> frozenset:
    """Knobs pinned by the operator (update()/override()/env) — the
    set the autotuner's "never fight a pin" rule checks against."""
    return frozenset(_EXPLICIT)


def is_explicit(key: str) -> bool:
    return key in _EXPLICIT


def tuned() -> dict:
    """``{knob: value}`` currently owned by the autotuner."""
    return dict(_TUNED)


def default_value(key: str):
    """The knob's baseline: the dataclass default, env-seeded the same
    way the process's initial config was — what `reset_tuning` restores
    and what policies treat as "the static default"."""
    base = Config()
    if not hasattr(base, key):
        raise AttributeError(f"unknown config key {key!r}")
    return getattr(base, key)


def set_tuned(key: str, value) -> bool:
    """The autotuner's ONLY write path: apply ``value`` unless the knob
    is explicitly pinned. Returns False (and changes nothing) for a
    pinned knob — an operator's explicit setting always wins. The
    pin check and the write are one atomic step under the state lock,
    so a concurrent `update()` can never be overwritten."""
    if not hasattr(_config, key):
        raise AttributeError(f"unknown config key {key!r}")
    with _state_lock:
        if key in _EXPLICIT:
            return False
        setattr(_config, key, value)
        _TUNED[key] = value
    return True


def reset_tuning() -> None:
    """Restore every tuned knob to its (env-seeded) default and forget
    the tuned set — the test-isolation hook, and the operator's undo."""
    if not _TUNED:
        return
    base = Config()
    with _state_lock:
        for k in list(_TUNED):
            setattr(_config, k, getattr(base, k))
        _TUNED.clear()


def get() -> Config:
    return _config


def update(**kwargs) -> None:
    for k, v in kwargs.items():
        if not hasattr(_config, k):
            raise AttributeError(f"unknown config key {k!r}")
        with _state_lock:
            setattr(_config, k, v)
            # an explicit set PINS the knob: the autotuner may no
            # longer touch it, and any tuned value it carried is
            # superseded
            _EXPLICIT.add(k)
            _TUNED.pop(k, None)
    if "compilation_cache_dir" in kwargs and kwargs["compilation_cache_dir"]:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", kwargs["compilation_cache_dir"]
        )


@contextlib.contextmanager
def override(**kwargs):
    old = {k: getattr(_config, k) for k in kwargs}
    # pin state is scoped like the values: a knob pinned only inside an
    # override() is un-pinned again on exit (and a tuned value it
    # shadowed is restored to the tuned ledger)
    old_explicit = {k: (k in _EXPLICIT) for k in kwargs}
    old_tuned = {k: _TUNED.get(k, _MISSING) for k in kwargs}
    update(**kwargs)
    try:
        yield _config
    finally:
        update(**old)
        # the pin/ledger restore shares the state lock with
        # set_tuned(): a background tuner write interleaving here
        # would otherwise desync _TUNED from the value in force
        with _state_lock:
            for k in kwargs:
                if not old_explicit[k]:
                    _EXPLICIT.discard(k)
                if old_tuned[k] is not _MISSING:
                    _TUNED[k] = old_tuned[k]
