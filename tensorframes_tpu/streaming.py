"""Out-of-core streaming reduce (extracted from `api.py`).

`reduce_blocks_stream` folds an iterator of frames with background
prefetch and bounded-memory tree-folding — the Spark-spill analogue
that makes the BASELINE north star (1B-row vector reduce) run in
bounded host memory. `api.py` re-exports both names, so the public
surface is unchanged.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import config as _config
from .aggregate import _chunk_combiners
from .frame import TensorFrame
from .graph.analysis import analyze_graph
from .graph.ir import base_name as _base
from .runtime.executor import Executor
from .utils import telemetry as _telemetry
from .utils.profiling import count as record_count, record

# late-bound: api imports this module, so helper lookups resolve at
# call time through the module object (same pattern as parallel/verbs)
from . import api as _api

from .api import Fetches  # noqa: E402,F401  (annotations; api is mid-init
# but Fetches is defined before this module loads)


def _prefetch_iter(it, depth=None, stage=None):
    """Pull ``it`` on a daemon thread, ``depth`` items ahead (default
    ``config.stream_prefetch_depth``). The consumer (device execution)
    and the producer (chunk synthesis / host IO) then overlap — the
    streaming analogue of Spark's pipelined partition fetch.

    ``stage`` (optional) is a per-item transform run on ANOTHER
    pipeline thread between producer and consumer — the device-transfer
    stage: when it issues `jax.device_put` for chunk k+1, that H2D copy
    proceeds under chunk k's compute, double-buffering transfer against
    execution end to end. A stage failure propagates to the consumer
    like a producer failure (stamped with chunk index + stage name).

    Since ISSUE 7 this is a thin wrapper over the generic stage-graph
    runtime (`ingest.pipeline.pipelined`), which owns the shared
    buffering budget, per-stage telemetry, classified fault retries and
    cancellation; `reduce_blocks_stream` composes richer graphs
    (parallel decode of multi-file datasets) through the same runtime.
    """
    from .ingest.pipeline import PipeStage, pipelined

    stages = [] if stage is None else [PipeStage("transfer-stage", stage)]
    return pipelined(it, stages, depth=depth)


def _spill_partial_to_host(part: Dict, chunk: int) -> Dict:
    """D2H-spill one partial table to host numpy through the ONE
    accounting path every stream spill shares: a ``host_sync`` span +
    counter and the ``d2h_bytes`` histogram. The unfoldable-stream
    spill, the double-buffer stand-down and the materialization cache's
    serialize step all report through this shape, so diagnostics see
    every forced device-to-host sync the same way. Host-resident
    partials pass through untouched (and cost nothing)."""
    if all(isinstance(v, np.ndarray) for v in part.values()):
        return part
    with _telemetry.span(
        "reduce_blocks_stream.spill", kind="host_sync", chunk=chunk,
    ):
        spilled = {k: np.asarray(v) for k, v in part.items()}
    record_count("host_sync")
    if _telemetry.enabled():
        _telemetry.histogram_observe(
            "d2h_bytes",
            float(sum(v.nbytes for v in spilled.values())),
        )
    return spilled


from .runtime.deadline import deadline_entry as _deadline_entry


@_deadline_entry("reduce_blocks_stream")
def reduce_blocks_stream(
    fetches: Fetches,
    frames,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    mesh=None,
    fold_every="auto",
    devices=None,
    checkpoint=None,
    checkpoint_every: Optional[int] = None,
    resume: str = "auto",
):
    """Out-of-core reduce: fold an ITERATOR of frames (chunks too large to
    hold at once — the Spark-spill analogue). Chunk N+1 is produced by a
    background prefetch thread while chunk N reduces on device, so host
    synthesis/IO overlaps device execution; partials combine with the
    same graph.

    The partial table itself is tree-folded every ``fold_every`` chunks,
    so host memory is bounded by O(fold_every) partials no matter how
    long the stream — the streaming form is what makes the BASELINE
    north star (1B-row vector reduce_sum) run in bounded host memory
    unconditionally.

    Chunks may be `LazyFrame`s (a pending map chain over each chunk):
    the per-chunk dispatch routes through the lazy terminal, so each
    chunk's map stages and its block reduce run as ONE fused program
    per block — the combine over partials still runs the plain reduce
    graph, so fold semantics are unchanged.

    Combining partials through the same graph assumes the reduce is
    ASSOCIATIVE over blocks (sum/min/max/...) — the same contract as the
    reference's pairwise partial combine (`reducePairBlock`,
    `DebugRowOps.scala:748-757`). A non-associative graph (e.g. Mean:
    a fold result re-enters the next combine weighted as ONE chunk) is
    not exact under tree-folding, so the default ``fold_every="auto"``
    enables tree-folding (every 64 chunks) ONLY when every fetch is an
    associative monoid reduce (sum/min/max/prod) consuming its
    placeholder DIRECTLY — partials recombine through the same graph,
    so any transform between placeholder and reduce (``Sum(x*x)``)
    would be re-applied to the partials at each fold. Mean,
    transform-then-reduce, and unclassifiable graphs fall back to the
    single equally-weighted final combine at the cost of O(#chunks)
    host memory. Pass an int to force a fold cadence, or ``None`` to
    force the single final combine.

    Durable streams (``checkpoint=``, `runtime.checkpoint`): give a
    path and the stream atomically commits its progress — a versioned
    manifest (dataset/program/config fingerprints, per-fetch monoid
    kinds, the contiguous-chunk WATERMARK) plus the live partial table
    — after every ``checkpoint_every`` folded chunks (default
    ``config.stream_checkpoint_every``), on clean `DeadlineExceeded` /
    `Cancelled` exits, and at completion. A crash / SIGKILL /
    preemption then resumes in a fresh process: the committed manifest
    is validated field by field (any drift refuses loudly naming the
    field; ``resume="ignore"`` opts into a fresh start), chunks below
    the watermark are skipped at the `Dataset.tasks()` METADATA level
    (never re-decoded) for an unstarted `IngestStream`, and the fold
    is seeded with the restored partials — bit-identical to an
    uninterrupted run for exact monoids (min/max/prod/int-sum), within
    the documented reassociation tolerance for float sum/mean. Only
    classifiable monoid reduces are eligible; anything else rejects
    ``checkpoint=`` with a typed `CheckpointError`. Requires the local
    path (no ``mesh=``).
    """
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    auto_fold = fold_every == "auto"
    if auto_fold:
        fold_every = None  # resolved from the first chunk's analysis below
    if fold_every is not None:
        fold_every = max(2, int(fold_every))

    def _combine(parts: List[Dict]) -> Dict:
        # device partials stack on device (one dispatch, no host
        # round-trip between fold generations); host partials stay host.
        # Rotated-device partials converge on the schedule's anchor so
        # the stacked frame's columns share one committed device.
        anchor = sched_devs[0] if sched_devs else None
        stacked = TensorFrame.from_dict(
            {
                b: _api._stack_parts([p[b] for p in parts], anchor)
                for b in parts[0]
            }
        )
        with contextlib.ExitStack() as _gstack:
            if gmesh_off[0]:
                # the stream already proved this graph unclassifiable:
                # the combine must not re-probe (and re-count) it
                from . import globalframe as _gfm

                _gstack.enter_context(_gfm._suppress_route())
            r = _api.reduce_blocks(
                graph, stacked, None, fetch_names=fetch_list,
                executor=executor,
                # the combine honors the stream's device set (a pinned
                # stream keeps its combine on the pinned device; rotation
                # anchors it on sched_devs[0] where the stack landed)
                devices=list(sched_devs) if sched_devs else None,
            )
        return r if isinstance(r, dict) else {_base(fetch_list[0]): r}

    transfer_warned = [False]
    # global-mode sharded transfer stands down for the REST of the
    # stream once conversion fails or the consume loop's first-chunk
    # eligibility gate finds the reduce graph unclassifiable (the
    # transfer stage runs on a pipeline thread — the plain-list cell
    # is the shared off switch, and a lagging read merely shards one
    # extra chunk, which the consume loop converts back)
    gmesh_off = [False]
    gmesh_checked = [False]
    # Block-scheduled streams round-robin chunks over the device set:
    # the prefetch transfer stage targets the NEXT chunk's assigned
    # device, so each device's H2D copy double-buffers under the
    # previous chunk's compute on a DIFFERENT device, and the per-chunk
    # reduce below pins its dispatch to the same device. Both sides
    # derive the assignment from the same chunk ordinal (the stage
    # processes items in stream order on one thread), so they can never
    # disagree.
    stage_idx = [0]
    consume_idx = [0]

    def _chunk_device(counter):
        # the ordinal advances for EVERY chunk, even while rotation is
        # off (sched_devs None): if the global path stands down
        # mid-stream and rotation resumes, both the transfer stage and
        # the consume loop must map chunk i to the same device, so the
        # assignment has to key off the chunk ordinal, not off how many
        # chunks each side happened to rotate
        i = counter[0]
        counter[0] += 1
        if not sched_devs:
            return None
        return sched_devs[i % len(sched_devs)]

    def _to_device(f):
        # the transfer stage of the prefetch pipeline: issue the H2D
        # copy of chunk k+1 while chunk k computes. Only for the
        # local single-device path — the mesh path owns its own
        # sharded placement — and only for real frames (tests feed
        # plain dicts through here). Already-device columns pass
        # through untouched (to_device skips them; with a scheduled
        # target device they commit/move there). LazyFrame chunks
        # stage their BASE frame (the pending plan rides along and
        # fuses with the reduce at dispatch below).
        dev = _chunk_device(stage_idx)  # every item advances the ordinal
        from .lazy import LazyFrame

        if gmesh is not None and isinstance(f, TensorFrame):
            # global stream path: the transfer stage does the SHARDED
            # device_put (per-shard H2D copies overlap under the
            # previous chunk's compute), and the per-chunk reduce below
            # folds into the sharded accumulator as ONE SPMD dispatch.
            # Small/ineligible chunks stay plain and fall THROUGH to
            # the ordinary per-block transfer below — they keep the
            # H2D/compute overlap, the same fallback rule as the verbs.
            from . import config as _cfg
            from . import globalframe as _gf

            if (
                not gmesh_off[0]
                and f.nrows >= max(1, _cfg.get().global_frame_min_rows)
            ):
                try:
                    return _gf.GlobalFrame.from_frame(f, mesh=gmesh)
                except Exception as e:
                    gmesh_off[0] = True
                    from .utils.log import get_logger

                    get_logger("streaming").warning(
                        "global sharded transfer disabled for this "
                        "stream (%s: %s); chunks fall back to the "
                        "per-block path",
                        type(e).__name__, e,
                    )
        if isinstance(f, (LazyFrame, TensorFrame)):
            try:
                return f.to_device(device=dev)
            except Exception as e:
                # fall back to host arrays (the reduce dispatch will
                # transfer implicitly) — but say so ONCE: a silently
                # degraded stream would report serial transfer as an
                # overlap regression with no clue why
                if not transfer_warned[0]:
                    transfer_warned[0] = True
                    from .utils.log import get_logger

                    get_logger("streaming").warning(
                        "prefetch device-transfer stage disabled for "
                        "this stream (%s: %s); chunks will transfer "
                        "synchronously inside each reduce dispatch",
                        type(e).__name__, e,
                    )
                return f
        return f

    from .runtime.executor import default_executor
    from .runtime import scheduler as _rs

    # No transfer stage for the mesh path (it owns its sharded
    # placement) or a native-host executor (`.host`): device_put would
    # initialize the in-process JAX backend next to a host that may own
    # the same device.
    ex = executor if executor is not None else default_executor()
    local = mesh is None and getattr(ex, "host", None) is None
    if devices is not None and not local:
        raise ValueError(
            "reduce_blocks_stream: devices= requires the local in-process "
            "path (no mesh=, no native-host executor)"
        )
    sched_devs = (
        _rs.resolve(devices=devices, executor=ex) if local else None
    )
    if sched_devs is not None and devices is None and len(sched_devs) < 2:
        # auto-resolved to one device: plain prefetch, nothing to
        # rotate. An EXPLICIT one-device list stays: rotation over one
        # device IS the documented pin (every chunk targets it).
        sched_devs = None
    # block_scheduler="global": eligible chunks shard over ONE data
    # mesh in the transfer stage and each chunk's reduce is a single
    # SPMD dispatch — the global path owns placement, so the per-chunk
    # device rotation stands down (an explicit devices= pin keeps it).
    gmesh = None
    gmesh_rotation = None
    if local and devices is None and _rs.global_mode():
        from . import globalframe as _gf

        try:
            gmesh = _gf.resolve_global_mesh()
        except Exception:
            gmesh = None
        if gmesh is not None:
            # parked, not dropped: if the stream stands the global path
            # down (unclassifiable reduce, failed conversion), per-chunk
            # rotation resumes — ineligible streams behave as "auto"
            gmesh_rotation, sched_devs = sched_devs, None
    # Compose ONE stage graph for the whole ingest path. A plain
    # iterator of frames keeps the classic producer -> transfer shape;
    # an `IngestStream` (multi-file dataset from `stream_dataset` /
    # multi-path io readers) contributes its discovery source and
    # parallel-decode stage, so discovery, decode, H2D transfer,
    # compute and combine all overlap under one shared buffering
    # budget instead of two chained pipelines.
    from .ingest.dataset import IngestStream
    from .ingest.pipeline import PipeStage, pipelined

    composable = isinstance(frames, IngestStream) and not frames.started

    ckpt = None
    watermark = 0
    restored: List[Dict] = []
    ds_tasks = None
    if checkpoint is not None:
        from .runtime.checkpoint import CheckpointError, StreamCheckpointer

        if mesh is not None:
            raise CheckpointError(
                "checkpoint= requires the local path (no mesh= — the "
                "mesh owns its own placement and has no per-chunk "
                "watermark to commit)"
            )
        ds_fp = None
        if composable:
            # the dataset fingerprint AND the resume skip both work at
            # the task-METADATA level: materializing the task list here
            # reads only file footers, never chunk data
            ds_tasks = frames.dataset.task_list()
            ds_fp = frames.dataset.fingerprint(ds_tasks)
        ckpt = StreamCheckpointer(
            checkpoint, graph, [_base(f) for f in fetch_list],
            checkpoint_every, resume, ds_fp,
        )
        ckpt.entry_gate()
        watermark, restored = ckpt.try_resume()

    if composable:
        # resume skips committed chunks at the task level: they are
        # never decoded again (the decode-stage counter proves it)
        source, pipe_stages = frames.source_and_stages(
            tasks=ds_tasks, skip=watermark
        )
        pipe_depth = frames.depth
    else:
        # plain iterator — or an IngestStream someone already pulled
        # from, whose running pipeline must be consumed, not rebuilt
        source, pipe_stages, pipe_depth = frames, [], None
        if watermark:
            # a plain iterator has no metadata level: committed chunks
            # are pulled (the producer pays their synthesis) but never
            # transferred or dispatched
            source = iter(frames)
            for _ in range(watermark):
                try:
                    next(source)
                except StopIteration:
                    break
    if watermark:
        # device rotation continues from the committed ordinal, as if
        # the stream had never stopped
        stage_idx[0] = consume_idx[0] = watermark
    if local:
        pipe_stages.append(PipeStage("transfer-stage", _to_device))

    from .runtime.deadline import Cancelled, DeadlineExceeded

    partials: List[Dict] = list(restored)
    # Double-buffered accumulator (global streaming path): instead of
    # parking ``fold_every`` partials and tree-folding them in one
    # burst, fold chunk k's partial eagerly into one of TWO alternating
    # slots. Each slot is an independent dependency chain, so the async
    # one-SPMD-dispatch fold of chunk k (slot k%2) runs while chunk
    # k+1's sharded device_put is in flight AND chunk k+1's own fold
    # lands on the OTHER slot — the fold never serializes against the
    # H2D transfer. Device-resident partials drop from O(fold_every)
    # parked tables to O(2). Active only for streams that would
    # tree-fold anyway (same associativity contract; pairwise
    # reassociation stays within the documented float-sum tolerance;
    # min/max/prod/int-sum are exact), never for durable streams (the
    # checkpoint protocol commits the partials LIST), and gated on
    # ``config.plan_pipeline`` so the A/B benchmark can hold it still.
    dbuf: List[Optional[Dict]] = [None, None]
    dbuf_n = [0]
    dbuf_ok = [True]
    # `ordinal` counts source chunks FULLY consumed (committed ones
    # included): the candidate watermark. Empty chunks advance it —
    # they contribute the reduction identity, and a resume must not
    # re-deliver them just to skip them again.
    ordinal = watermark
    try:
        for f in pipelined(
            source, pipe_stages, depth=pipe_depth, ordinal_base=watermark
        ):
            if gmesh_off[0] and gmesh_rotation is not None:
                # the global path stood down: rotation resumes exactly
                # as under "auto" (chunks transferred before the switch
                # pay at most one implicit move onto their pinned
                # device — bounded by the prefetch depth)
                sched_devs = gmesh_rotation
                gmesh_rotation = None
            chunk_dev = _chunk_device(consume_idx)
            nrows = len(f) if _api._is_pandas(f) else getattr(f, "nrows", None)
            if nrows == 0:
                # Empty chunk (empty file partition / fully filtered
                # shard): it contributes the reduction identity, i.e.
                # nothing — skip the dispatch instead of raising "empty
                # frame" mid-stream or emitting a partial that poisons
                # the combine (reduce_min over 0 rows). Classification
                # (auto_fold) waits for the first chunk that actually
                # carries rows.
                ordinal += 1
                continue
            if gmesh is not None:
                from . import globalframe as _gfm

                if isinstance(f, _gfm.GlobalFrame) and not gmesh_off[0]:
                    if not gmesh_checked[0]:
                        # the reduce graph is fixed for the stream's
                        # lifetime: decide ONCE whether it lowers to
                        # the one-dispatch collective program, instead
                        # of paying a sharded H2D plus a local-boundary
                        # fallback re-gather on every chunk
                        gmesh_checked[0] = True
                        if not _gfm.stream_reduce_eligible(
                            graph, fetch_list, f, feed_dict, executor
                        ):
                            gmesh_off[0] = True
                            # ONE counted reason for the whole stream,
                            # not one per chunk
                            _gfm._note_fallback("unclassified-reduce")
                            from .utils.log import get_logger

                            get_logger("streaming").warning(
                                "global sharded transfer disabled for "
                                "this stream: the reduce graph has no "
                                "monoid structure to lower as an "
                                "in-program collective; chunks take "
                                "the per-block path"
                            )
                if gmesh_off[0] and isinstance(f, _gfm.GlobalFrame):
                    # sharded before the off switch flipped (in-flight
                    # prefetch, or the gate's own first chunk)
                    f = f.to_frame()
            if auto_fold or (ckpt is not None and ckpt.monoids is None):
                # classify once, on the first chunk: ONE analysis pass
                # serves both the fold class (tree-fold only graphs
                # proven associative — sum/min/max/prod monoids
                # consuming their placeholder directly; anything else
                # keeps every partial for one exact final combine) and
                # the checkpoint eligibility gate / monoid manifest
                comb_any = None
                try:
                    ov = _api._ph_overrides(
                        graph, f, feed_dict, block_level=True
                    )
                    s = analyze_graph(
                        graph, fetch_list, placeholder_shapes=ov
                    )
                    comb_any = _chunk_combiners(graph, fetch_list, s)
                    if auto_fold:
                        # require_direct: partials recombine through
                        # the same graph here, so an interposed
                        # transform (Sum(x*x)) would be re-applied at
                        # every fold
                        comb = _chunk_combiners(
                            graph, fetch_list, s, require_direct=True
                        )
                        if comb is not None and "mean" not in comb.values():
                            fold_every = 64
                except Exception:
                    pass  # conservative: no folding when classification fails
                auto_fold = False
                if ckpt is not None:
                    # rejects non-classifiable reduces (typed
                    # CheckpointError) and, on resume, refuses a
                    # drifted monoid set / fold cadence
                    ckpt.on_first_chunk(comb_any, fold_every)
            # per-chunk span/counters: stream chunks previously bypassed
            # profiling entirely (only the inner verb recorded); the chunk
            # record attributes each dispatch to the stream and carries the
            # chunk row count
            with record("reduce_blocks_stream.chunk", int(nrows or 0)), \
                    contextlib.ExitStack() as _gstack:
                if gmesh_off[0]:
                    # the stream already decided against the global
                    # path: stop the per-chunk auto-route from
                    # re-probing (and re-counting a fallback for) the
                    # same fixed graph on every chunk
                    from . import globalframe as _gfm

                    _gstack.enter_context(_gfm._suppress_route())
                r = _api.reduce_blocks(
                    graph, f, feed_dict, fetch_names=fetch_list,
                    executor=executor, mesh=mesh,
                    # pin the chunk's dispatch to the device its prefetch
                    # transfer targeted: compute lands where the data
                    # already is, and consecutive chunks run on different
                    # devices (compute/compute overlap, not just
                    # transfer/compute)
                    devices=[chunk_dev] if chunk_dev is not None else None,
                )
            part = r if isinstance(r, dict) else {_base(fetch_list[0]): r}
            use_dbuf = (
                dbuf_ok[0] and gmesh is not None and not gmesh_off[0]
                and ckpt is None and fold_every is not None
                and _config.get().plan_pipeline
            )
            if use_dbuf:
                slot = dbuf_n[0] % 2
                dbuf_n[0] += 1
                if dbuf[slot] is None:
                    dbuf[slot] = part
                else:
                    try:
                        with _telemetry.span(
                            "reduce_blocks_stream.fold", kind="stage",
                            slot=slot,
                        ):
                            dbuf[slot] = _combine([dbuf[slot], part])
                        from . import globalframe as _gfm

                        _gfm._note_stream_fold()
                    except Exception:
                        # device pressure (or anything else) mid-fold:
                        # spill both operands to host through the
                        # shared D2H accounting path and stand down to
                        # the tree-fold list for the rest of the stream
                        dbuf_ok[0] = False
                        partials.extend(
                            _spill_partial_to_host(p, ordinal)
                            for p in (dbuf[slot], part)
                        )
                        dbuf[slot] = None
                ordinal += 1
            else:
                partials.append(part)
                # advance the candidate watermark the moment the
                # chunk's contribution is IN `partials`: from here on
                # (ordinal, partials) is a committable state even if
                # the fold below is interrupted mid-combine (a fold
                # only reorganizes contributions, it never adds one)
                ordinal += 1
                if fold_every is not None and len(partials) >= fold_every:
                    with _telemetry.span(
                        "reduce_blocks_stream.fold", kind="stage"
                    ):
                        partials = [_combine(partials)]
                elif fold_every is None and len(partials) > 1:
                    # no tree-fold will ever drain this list: spill the
                    # PREVIOUS chunk's (already computed) partial to
                    # host so unfoldable streams cost O(#chunks) host
                    # RAM — the documented bound — not device HBM. The
                    # newest partial stays on device, so the current
                    # dispatch still overlaps the next chunk's
                    # production/transfer. The spill is a real D2H sync
                    # and is accounted as one (host_sync span/counter +
                    # d2h bytes) — diagnostics previously
                    # under-reported D2H traffic on long unfoldable
                    # streams.
                    partials[-2] = _spill_partial_to_host(
                        partials[-2], len(partials) - 2
                    )
            if ckpt is not None:
                # the commit point: chunk `ordinal - 1` is fully folded
                # into `partials`, so (ordinal, partials) is exactly the
                # state an uninterrupted run holds here
                ckpt.note_chunk_folded(ordinal, partials)
        # drain the double-buffer slots into the final combine (at most
        # two running folds — each already the eager reduction of its
        # half of the stream)
        partials.extend(d for d in dbuf if d is not None)
        if not partials:
            raise ValueError(
                "reduce_blocks_stream over an empty iterator (or every "
                "chunk had zero rows)"
            )
        if len(partials) == 1:
            out = partials[0]
        else:
            with _telemetry.span("reduce_blocks_stream.fold", kind="stage"):
                out = _combine(partials)
    except (DeadlineExceeded, Cancelled) as e:
        # clean cooperative exits commit the progress so far — the
        # budget bought (ordinal - watermark) folded chunks; a resume
        # picks up from the committed watermark instead of chunk zero
        if ckpt is not None:
            ckpt.on_interrupt(e, ordinal, partials)
        raise
    if ckpt is not None:
        # completion commit: watermark = every chunk, so an identical
        # re-run resumes to a no-op (restored partials combine; zero
        # chunks re-decode)
        ckpt.finalize(ordinal, partials)
    if len(fetch_list) == 1:
        return out[_base(fetch_list[0])]
    return out


