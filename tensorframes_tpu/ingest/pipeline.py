"""Stage-graph pipeline runtime: N concurrent stages, bounded queues.

Generalizes the old fixed producer -> transfer -> consumer prefetch
chain (`streaming._prefetch_iter`) into an arbitrary linear stage graph

    source -> stage_1 -> stage_2 -> ... -> consumer

where every stage runs concurrently with every other on its own
thread(s), connected by BOUNDED queues, so the pipeline's peak host
memory stays a documented constant no matter how long the stream. A
stage with ``workers > 1`` decodes items OUT OF ORDER on a small thread
pool and re-sequences them through a bounded reorder buffer, so
delivery is always IN ORDER — downstream stages (the H2D transfer
stage's device rotation, the consumer's chunk ordinals) never observe
reordering.

Memory bound (threaded mode, ``config.ingest_pipeline`` on): with
final-queue depth ``d`` (``config.stream_prefetch_depth``), the number
of simultaneously live chunks is at most

    d                  (the delivery queue)
  + 1                  (the consumer's item in hand)
  + 1 + 1              per single-worker stage (in hand + its in-queue)
  + workers + d        per pooled stage (in-flight + reorder window)
  + 1 + c0             (the producer's item in hand + the source queue,
                        c0 = d with no stages, else 1 — or the
                        declared task capacity when the first stage
                        consumes cheap task descriptors)

For the canonical chain (decode pool of W, one transfer stage) that is
``W + 2d + 4`` chunks; `tests/test_ingest.py` asserts it.

Failure semantics (the PR 6 fault classification, applied to ingest):
every stage invocation is routed through `runtime.faults` — a
``transient``-classified failure (device loss, connection reset,
injected `UNAVAILABLE:`) is retried in place with the deterministic
backoff schedule, up to ``config.block_retry_attempts`` per chunk
within one ``config.verb_retry_budget`` per stage; ``deterministic``
failures (corrupt files, schema mismatches) surface after EXACTLY one
attempt. Either way the exception reaches the consumer stamped with
``tfs_chunk_index`` / ``tfs_pipeline_stage`` (and whatever context the
stage declares — the decode stage adds ``tfs_shard_path``), and every
pipeline thread exits promptly: an error, like consumer abandonment,
cancels the whole graph and drains the bounded queues so buffered
chunks release.

Telemetry (always-live counters; gauges/spans gated on
``config.telemetry``):

- ``ingest_stage_busy_seconds{stage=}`` / ``ingest_stage_wait_seconds
  {stage=}`` — per-stage busy vs starved time (the consumer reports as
  ``stage="compute"``: its wait is exactly the time the devices sat
  starved for input).
- ``ingest_chunks{stage=}`` — items through each stage.
- ``ingest_queue_depth{stage=}`` gauge — occupancy of each stage's
  input queue at consume time (0 = that stage is starved).
- the legacy ``stream_queue_depth`` gauge on the delivery queue.

``config.ingest_pipeline`` off runs the SAME stage functions inline on
the consumer thread (stage-serial) — the A/B baseline
`benchmarks/ingest_bench.py` measures against; error stamping and
retry classification behave identically.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "PipeStage",
    "pipelined",
    "set_stage_fault_injector",
    "current_cancel_event",
]


# Per-thread handle to the owning graph's cancel event, set for every
# pipeline thread at spawn: stage code (and the hang-fault injector)
# running on WORKER threads — where the deadline contextvar does not
# flow — can wait on it and wake the moment the graph tears down
# (consumer abandon, stage error, or deadline expiry).
_CANCEL_LOCAL = threading.local()


def current_cancel_event() -> Optional[threading.Event]:
    """The cancel event of the pipeline graph owning THIS thread (None
    off pipeline threads). A long-running stage may poll/wait on it to
    exit early on teardown; `testing.faults`'s ``fault="hang"``
    injection sleeps against it so injected wedges never outlive the
    pipeline."""
    return getattr(_CANCEL_LOCAL, "event", None)


class PipeStage:
    """One pipeline stage: a per-item transform.

    ``fn(item) -> item`` runs on ``workers`` threads (out-of-order when
    ``workers > 1``; delivery re-sequences). ``context(item)`` returns
    attribute names -> values stamped onto an exception escaping this
    stage (the decode stage stamps ``tfs_shard_path``).
    ``cheap_input=True`` declares the stage's INPUT items to be small
    task descriptors rather than decoded chunks, letting the runtime
    deepen the stage's input queue without growing chunk memory."""

    __slots__ = ("name", "fn", "workers", "context", "cheap_input")

    def __init__(
        self,
        name: str,
        fn: Callable,
        workers: int = 1,
        context: Optional[Callable[[object], Dict[str, object]]] = None,
        cheap_input: bool = False,
    ):
        if workers < 1:
            raise ValueError(f"stage {name!r}: workers must be >= 1")
        self.name = name
        self.fn = fn
        self.workers = int(workers)
        self.context = context
        self.cheap_input = cheap_input


# -- fault-injection seam (testing.faults.inject_stage) ----------------------

_stage_fault_injector: Optional[Callable] = None


def set_stage_fault_injector(hook: Optional[Callable]) -> None:
    """Install/clear the stage-level chaos hook: ``hook(stage_name,
    item)`` is called before every stage-fn ATTEMPT (retries draw new
    verdicts, mirroring the executor seam's ordinal semantics) and may
    raise a classified fault."""
    global _stage_fault_injector
    _stage_fault_injector = hook


def _stamp(
    e: BaseException,
    idx: int,
    stage_name: str,
    extra: Optional[Dict[str, object]] = None,
) -> BaseException:
    """Chunk-index / stage / shard context for pipeline failures: the
    consumer sees WHICH chunk (and which pipeline stage, and — for
    decode — which shard file) died without the exception type
    changing. First stamp wins (an error forwarded through later
    stages keeps its origin)."""
    if getattr(e, "tfs_chunk_index", None) is None:
        try:
            e.tfs_chunk_index = idx
            e.tfs_pipeline_stage = stage_name
            for k, v in (extra or {}).items():
                if getattr(e, k, None) is None:
                    setattr(e, k, v)
        except Exception:
            pass  # extension exceptions without a __dict__
    return e


def _close_source(it) -> None:
    """Deterministically release the source's resources (open file
    handles in the `io` readers) instead of waiting for GC — the
    generator may live on a pipeline thread, where refcount collection
    is not prompt."""
    close = getattr(it, "close", None)
    if callable(close):
        try:
            close()
        except Exception:
            pass  # releasing a half-consumed reader must never mask errors


def _run_stage_fn(
    stage: PipeStage, scope, ordinal: int, item, parent: Optional[int] = None
):
    """One stage invocation under classified fault handling: transient
    errors retry in place (deterministic backoff, per-chunk attempt cap
    + per-stage budget from ``scope``); everything else surfaces after
    one attempt. Escaping exceptions are stamped with chunk / stage /
    stage-declared context.

    Span attribution: pipeline stages run on WORKER threads, where the
    telemetry contextvars do not flow — a naive span here would record
    an orphan root disconnected from the verb consuming the stream.
    Each successful invocation instead records an already-timed
    ``stage`` span with an EXPLICIT parent (the consumer-side span id
    captured by `pipelined` at first pull) plus a ``stage`` label, so
    the exported Chrome trace nests decode/transfer work under the
    verb with no orphan parent ids (asserted in tests)."""
    from ..utils import telemetry as _tele

    def attempt():
        hook = _stage_fault_injector
        if hook is not None:
            hook(stage.name, item)
        return stage.fn(item)

    try:
        t0 = time.perf_counter()
        out = scope.dispatch(
            attempt, what=f"ingest.{stage.name}[chunk {ordinal}]"
        )
        _tele.add_event(
            f"ingest.{stage.name}", "stage", t0, time.perf_counter(),
            parent_id=parent, stage=stage.name, chunk=ordinal,
        )
        return out
    except BaseException as e:  # noqa: BLE001 — re-raised at the consumer
        extra = None
        if stage.context is not None:
            try:
                extra = stage.context(item)
            except Exception:
                extra = None
        raise _stamp(e, ordinal, stage.name, extra)


def _note_stage(stage_name: str, busy_s: float, wait_s: float) -> None:
    from ..utils import telemetry as _tele

    _tele.counter_inc("ingest_chunks", 1.0, stage=stage_name)
    _tele.counter_inc("ingest_stage_busy_seconds", busy_s, stage=stage_name)
    _tele.counter_inc("ingest_stage_wait_seconds", wait_s, stage=stage_name)


def _fault_scope(stage_name: str):
    from ..runtime import faults as _faults

    return _faults.scope(f"ingest.{stage_name}")


# ---------------------------------------------------------------------------
# stage-serial fallback (config.ingest_pipeline = off)
# ---------------------------------------------------------------------------


class _PipelineRoot:
    """The pipeline's virtual root span: an id reserved up front (so
    stage spans on WORKER threads can name their parent before the
    parent region closes) and recorded as an already-timed ``stage``
    span when the pipeline ends — under the span that was current at
    first pull when there was one. Guarantees the exported trace never
    carries an orphan parent id, whatever thread a stage ran on."""

    __slots__ = ("sid", "parent", "t0")

    def __init__(self):
        from ..utils import telemetry as _tele

        if _tele.enabled():
            self.parent = _tele.current_span_id()
            self.sid = _tele.allocate_span_id()
            self.t0 = time.perf_counter()
        else:
            self.parent = self.sid = self.t0 = None

    def close(self, chunks: int) -> None:
        if self.sid is None:
            return
        from ..utils import telemetry as _tele

        _tele.add_event(
            "ingest.pipeline", "stage", self.t0, time.perf_counter(),
            parent_id=self.parent, span_id=self.sid, chunks=chunks,
        )


def _serial_pipeline(source, stages: Sequence[PipeStage], ordinal_base: int = 0):
    """Every stage inline on the consumer thread — no overlap, but the
    same stage functions, fault classification and error stamping as
    the threaded graph (the honest pipeline-off baseline)."""
    from ..runtime import deadline as _dl

    it = iter(source)
    scopes = [_fault_scope(s.name) for s in stages]
    root = _PipelineRoot()
    ordinal = ordinal_base
    try:
        while True:
            _dl.check("ingest.pipeline")
            try:
                item = next(it)
            except StopIteration:
                return
            except BaseException as e:  # noqa: BLE001 — stamped context
                raise _stamp(e, ordinal, "producer")
            for stage, scope in zip(stages, scopes):
                t0 = time.perf_counter()
                item = _run_stage_fn(stage, scope, ordinal, item, root.sid)
                _note_stage(stage.name, time.perf_counter() - t0, 0.0)
            ordinal += 1
            yield item
    finally:
        _close_source(it)
        root.close(ordinal - ordinal_base)


# ---------------------------------------------------------------------------
# the threaded stage graph
# ---------------------------------------------------------------------------

# queue message protocol: ("item", ordinal, payload) |
# ("end", count, None) | ("error", position, exc). `position` is the
# stream ordinal at which the stream ends/fails, so an out-of-order
# pool can re-sequence terminal messages exactly like items.
_ITEM, _END, _ERROR = "item", "end", "error"


class _Graph:
    """Shared cancellation + bounded-put plumbing for one pipeline run.

    ``scope`` (a `runtime.deadline.CancelScope`, captured from the
    CONSUMER's context at first pull) folds the verb's deadline /
    cancellation into the graph's own teardown signal: every queue
    poll checks `aborted()`, so a deadline expiry tears the stage
    graph down with exactly the consumer-abandon guarantees — threads
    exit, the source closes, bounded queues drain."""

    def __init__(self, scope=None):
        self.cancelled = threading.Event()
        self.scope = scope
        self.queues: List[queue.Queue] = []
        self.threads: List[threading.Thread] = []

    def aborted(self) -> bool:
        """Teardown signal: explicit shutdown, consumer-scope cancel,
        or consumer-deadline expiry."""
        if self.cancelled.is_set():
            return True
        if self.scope is not None and self.scope.should_abort():
            # latch: waking every poller once beats each of them
            # re-reading the clock forever
            self.cancelled.set()
            return True
        return False

    def make_queue(self, maxsize: int) -> "queue.Queue":
        q = queue.Queue(maxsize=max(1, int(maxsize)))
        self.queues.append(q)
        return q

    def put(self, q: "queue.Queue", msg) -> bool:
        """Bounded put that gives up when the consumer abandoned the
        pipeline (or its deadline expired) — a blocked put would
        otherwise pin buffered chunks (and the thread) forever."""
        while not self.aborted():
            try:
                q.put(msg, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def get(self, q: "queue.Queue"):
        """Bounded get; returns None when cancelled."""
        while not self.aborted():
            try:
                return q.get(timeout=0.1)
            except queue.Empty:
                continue
        return None

    def spawn(self, target, name: str) -> None:
        def run():
            _CANCEL_LOCAL.event = self.cancelled
            try:
                target()
            finally:
                _CANCEL_LOCAL.event = None

        t = threading.Thread(target=run, daemon=True, name=name)
        self.threads.append(t)
        t.start()

    def shutdown(self) -> None:
        self.cancelled.set()
        for q in self.queues:
            while True:  # release buffered chunks promptly
                try:
                    q.get_nowait()
                except queue.Empty:
                    break


def _start_producer(
    g: _Graph, source, q_out: "queue.Queue", ordinal_base: int = 0
) -> None:
    def producer():
        it = None
        idx = ordinal_base
        try:
            try:
                # iter() INSIDE the try: a source whose __iter__ raises
                # (non-iterable, failing open) must surface to the
                # consumer as an error message, not kill this thread
                # and leave the consumer blocked on the queue forever
                it = iter(source)
                for item in it:
                    if not g.put(q_out, (_ITEM, idx, item)):
                        return
                    idx += 1
            except BaseException as e:  # noqa: BLE001 — consumer side
                g.put(q_out, (_ERROR, idx, _stamp(e, idx, "producer")))
                return
            g.put(q_out, (_END, idx, None))
        finally:
            _close_source(source if it is None else it)

    g.spawn(producer, "tfs-ingest-producer")


def _start_serial_stage(
    g: _Graph,
    stage: PipeStage,
    q_in: "queue.Queue",
    q_out: "queue.Queue",
    parent: Optional[int] = None,
) -> None:
    """A single-worker stage: in-order by construction (one thread, one
    bounded in/out queue) — the old transfer-stage shape."""
    scope = _fault_scope(stage.name)

    def worker():
        from ..utils import telemetry as _tele

        while True:
            t0 = time.perf_counter()
            msg = g.get(q_in)
            if msg is None:
                return
            wait_s = time.perf_counter() - t0
            kind, pos, payload = msg
            if kind != _ITEM:
                g.put(q_out, msg)
                return
            if _tele.enabled():
                _tele.gauge_set(
                    "ingest_queue_depth", q_in.qsize(), stage=stage.name
                )
            t1 = time.perf_counter()
            try:
                payload = _run_stage_fn(stage, scope, pos, payload, parent)
            except BaseException as e:  # noqa: BLE001 — consumer side
                g.put(q_out, (_ERROR, pos, e))
                return
            _note_stage(stage.name, time.perf_counter() - t1, wait_s)
            if not g.put(q_out, (_ITEM, pos, payload)):
                return

    g.spawn(worker, f"tfs-ingest-{stage.name}")


class _PoolState:
    """Reorder state of one pooled stage: out-of-order workers feed
    ``buffer``; the emitter drains it in ordinal order. ``window``
    bounds how far workers may run ahead of delivery (the reorder
    buffer's chunk-memory cap)."""

    def __init__(self, window: int, base: int = 0):
        self.cond = threading.Condition()
        self.buffer: Dict[int, tuple] = {}
        self.next_emit = base
        self.end_at: Optional[int] = None
        self.done = False
        self.window = max(1, int(window))


def _start_pooled_stage(
    g: _Graph,
    stage: PipeStage,
    q_in: "queue.Queue",
    q_out: "queue.Queue",
    depth: int,
    parent: Optional[int] = None,
    ordinal_base: int = 0,
) -> None:
    """A ``workers > 1`` stage: out-of-order execution, in-order
    delivery through a bounded reorder buffer."""
    st = _PoolState(window=stage.workers + depth, base=ordinal_base)
    scope = _fault_scope(stage.name)

    def worker():
        from ..utils import telemetry as _tele

        while not st.done:
            t0 = time.perf_counter()
            msg = g.get(q_in)
            if msg is None:
                return
            wait_s = time.perf_counter() - t0
            kind, pos, payload = msg
            if kind == _END:
                with st.cond:
                    st.end_at = pos
                    st.cond.notify_all()
                return
            if kind == _ERROR:
                with st.cond:
                    st.buffer[pos] = (_ERROR, payload)
                    st.end_at = pos  # nothing follows an upstream error
                    st.cond.notify_all()
                return
            if _tele.enabled():
                _tele.gauge_set(
                    "ingest_queue_depth", q_in.qsize(), stage=stage.name
                )
            # reorder window: never run more than `window` ordinals
            # ahead of delivery — this is the decode pool's chunk
            # memory bound
            with st.cond:
                while (
                    pos - st.next_emit >= st.window
                    and not st.done
                    and not g.aborted()
                ):
                    st.cond.wait(timeout=0.1)
                if st.done or g.aborted():
                    return
            t1 = time.perf_counter()
            try:
                out = (
                    _ITEM,
                    _run_stage_fn(stage, scope, pos, payload, parent),
                )
            except BaseException as e:  # noqa: BLE001 — consumer side
                out = (_ERROR, e)
            else:
                _note_stage(stage.name, time.perf_counter() - t1, wait_s)
            with st.cond:
                st.buffer[pos] = out
                st.cond.notify_all()

    def emitter():
        while True:
            with st.cond:
                while (
                    st.next_emit not in st.buffer
                    and st.end_at != st.next_emit
                    and not g.aborted()
                ):
                    st.cond.wait(timeout=0.1)
                if g.aborted():
                    st.done = True
                    st.cond.notify_all()
                    return
                if st.next_emit in st.buffer:
                    kind, payload = st.buffer.pop(st.next_emit)
                    pos = st.next_emit
                    if kind == _ITEM:
                        st.next_emit += 1
                    else:
                        st.done = True
                    st.cond.notify_all()
                else:  # end_at == next_emit: clean end of stream
                    kind, pos, payload = _END, st.next_emit, None
                    st.done = True
                    st.cond.notify_all()
            # puts happen OUTSIDE the lock: a full downstream queue
            # must not deadlock workers waiting to buffer results
            if kind == _ITEM:
                if not g.put(q_out, (_ITEM, pos, payload)):
                    with st.cond:
                        st.done = True
                        st.cond.notify_all()
                    return
            elif kind == _END:
                g.put(q_out, (_END, pos, None))
                return
            else:
                g.put(q_out, (_ERROR, pos, payload))
                return

    for w in range(stage.workers):
        g.spawn(worker, f"tfs-ingest-{stage.name}-{w}")
    g.spawn(emitter, f"tfs-ingest-{stage.name}-emit")


def pipelined(
    source,
    stages: Sequence[PipeStage] = (),
    depth: Optional[int] = None,
    ordinal_base: int = 0,
    inline: Optional[bool] = None,
):
    """Run ``source`` through ``stages`` as a concurrently-executing
    stage graph and yield the results in order.

    ``depth`` is the delivery-queue bound (default
    ``config.stream_prefetch_depth``); the full chunk-memory bound is
    documented in the module docstring. With ``config.ingest_pipeline``
    off, runs the same stages inline on the consumer thread
    (stage-serial). ``inline`` overrides that gate for callers whose
    on/off switch is a DIFFERENT knob (the pipelined plan loop in
    `lazy.force` gates on ``config.plan_pipeline``): ``True`` forces
    the stage-serial inline path, ``False`` forces the threaded graph,
    ``None`` (default) follows ``config.ingest_pipeline``.
    ``ordinal_base`` offsets every chunk ordinal (span
    labels, ``tfs_chunk_index`` stamps): a RESUMED durable stream
    re-enters the pipeline at its committed watermark, and a failure at
    post-resume chunk 3 must name the GLOBAL ordinal, not the third
    chunk since restart. The generator owns the graph:
    closing/abandoning it cancels every stage thread and drains the
    bounded queues; an error in any stage surfaces here with
    ``tfs_chunk_index`` / ``tfs_pipeline_stage`` (+ stage context)
    stamped, after which the graph shuts down the same way."""
    from .. import config as _config
    from ..runtime import deadline as _dl
    from ..utils import telemetry as _tele

    cfg = _config.get()
    if depth is None:
        depth = getattr(cfg, "stream_prefetch_depth", 1)
    depth = max(1, int(depth))
    ordinal_base = max(0, int(ordinal_base))
    stages = list(stages)
    if inline is None:
        inline = not getattr(cfg, "ingest_pipeline", True)
    if inline:
        yield from _serial_pipeline(source, stages, ordinal_base)
        return

    # the consumer's deadline/cancel scope (this generator body first
    # runs at first pull, on the consuming verb's thread): its expiry
    # becomes the graph's teardown signal — the DEADLINE path gives the
    # same guarantees as consumer abandonment (threads exit, source
    # closes, queues drain), and the consumer loop below raises the
    # typed DeadlineExceeded instead of blocking on the queue forever
    g = _Graph(scope=_dl.current_scope())
    # cross-thread span attribution: stage spans recorded on worker
    # threads parent to the pipeline's virtual root span (contextvars
    # do not flow into pipeline threads; the root's id is reserved NOW
    # and its region recorded at shutdown, so no child ever references
    # a missing parent). The root itself parents to whatever span is
    # current at first pull — the consuming verb, when there is one.
    root = _PipelineRoot()
    parent = root.sid
    # one buffering budget for the whole graph: intermediate handoffs
    # hold a single item (cheap task descriptors may buffer a few more)
    # and the DELIVERY queue gets the full depth — adding stages must
    # not silently multiply a stream's peak chunk memory.
    if stages:
        first = stages[0]
        c0 = first.workers * 2 if first.cheap_input else 1
        q = g.make_queue(c0)
    else:
        q = g.make_queue(depth)
    _start_producer(g, source, q, ordinal_base)
    for i, stage in enumerate(stages):
        last = i == len(stages) - 1
        q_out = g.make_queue(depth if last else 1)
        if stage.workers == 1:
            _start_serial_stage(g, stage, q, q_out, parent)
        else:
            _start_pooled_stage(
                g, stage, q, q_out, depth, parent, ordinal_base
            )
        q = q_out

    delivered = 0
    try:
        while True:
            t0 = time.perf_counter()
            if _tele.enabled():
                # queue depth at each consume: how far ahead the
                # pipeline is running (0 = the consumer is starved,
                # depth = the pipeline is saturated)
                _tele.gauge_set("stream_queue_depth", q.qsize())
                _tele.gauge_set(
                    "ingest_queue_depth", q.qsize(), stage="compute"
                )
            # poll, not block: a wedged stage (slow shard, injected
            # hang) must not hold the consumer past its deadline — the
            # check raises DeadlineExceeded/Cancelled and the finally
            # below tears the graph down like an abandon
            while True:
                _dl.check("ingest.pipeline")
                if g.aborted():
                    # the scope CAPTURED at first pull died (expired,
                    # or cancel() on a retained handle from another
                    # thread) and the stage threads may already have
                    # torn down without delivering _END — the ambient
                    # check above cannot see a captured scope, so
                    # raise its typed error here instead of polling
                    # an abandoned queue forever
                    if g.scope is not None:
                        g.scope.check("ingest.pipeline")
                    raise _dl.Cancelled(
                        "ingest pipeline torn down mid-consume"
                    )
                try:
                    msg = q.get(timeout=0.1)
                    break
                except queue.Empty:
                    continue
            kind, pos, payload = msg
            wait_s = time.perf_counter() - t0
            if kind == _ERROR:
                idx = getattr(payload, "tfs_chunk_index", None)
                if idx is not None:
                    from ..utils.log import get_logger

                    get_logger("ingest").warning(
                        "ingest pipeline failed at chunk %d (%s stage%s): "
                        "%s: %s",
                        idx,
                        getattr(payload, "tfs_pipeline_stage", "?"),
                        (
                            f", shard {payload.tfs_shard_path}"
                            if getattr(payload, "tfs_shard_path", None)
                            is not None
                            else ""
                        ),
                        type(payload).__name__,
                        payload,
                    )
                raise payload
            if kind == _END:
                return
            _note_stage("compute", 0.0, wait_s)
            delivered += 1
            yield payload
    finally:
        g.shutdown()
        root.close(delivered)
