"""Multi-file shard discovery + parallel decode for the ingest engine.

A dataset is an ordered list of SHARDS (Parquet or Arrow IPC files —
a directory, a glob, an explicit list, or any mix), each shard an
ordered list of CHUNKS (groups of row groups / record batches). Shard
discovery is deterministic: user-given order is preserved, and every
directory/glob expansion is sorted lexicographically, so two runs over
the same dataset see the same chunk ordinals — which is what makes the
device rotation, fault injection and benchmark comparisons
reproducible.

Decode is per-chunk and self-contained: each `ChunkTask` re-opens its
shard, reads exactly its groups and closes the handle (try/finally, so
workers never leak descriptors), which is what makes the decode stage
embarrassingly parallel — `IngestStream` runs it on a small thread
pool (``config.ingest_decode_workers``) with in-order delivery through
the `pipeline` reorder buffer. pyarrow releases the GIL inside
Parquet/IPC decode, so the pool gives real core parallelism.

`stream_dataset` is the user entry point; `io.stream_parquet` /
`io.stream_arrow_ipc` route multi-path arguments here.
"""

from __future__ import annotations

import glob as _glob
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from .pipeline import PipeStage, pipelined

__all__ = [
    "ChunkTask",
    "Dataset",
    "IngestStream",
    "discover_shards",
    "stream_dataset",
]

PathLike = Union[str, "os.PathLike[str]"]

_PARQUET_EXTS = (".parquet", ".pq")
_IPC_EXTS = (".arrow", ".feather", ".ipc", ".arrows")
_FORMATS = ("auto", "parquet", "ipc")


def _format_of(path: str, fmt: str) -> str:
    if fmt != "auto":
        return fmt
    ext = os.path.splitext(path)[1].lower()
    if ext in _PARQUET_EXTS:
        return "parquet"
    if ext in _IPC_EXTS:
        return "ipc"
    raise ValueError(
        f"cannot infer shard format from {path!r} (extension {ext!r}); "
        "pass format='parquet' or format='ipc'"
    )


def discover_shards(
    paths: Union[PathLike, Sequence[PathLike]], format: str = "auto"
) -> List[Tuple[str, str]]:
    """Resolve ``paths`` into the dataset's deterministic shard list
    ``[(path, format), ...]``.

    Each entry may be a file, a directory (every file with a known
    Parquet/IPC extension inside, non-recursive), or a glob pattern;
    a sequence mixes freely. User-given order is preserved; every
    expansion is sorted lexicographically. Unreadable/missing inputs
    and an empty result are loud errors — a dataset that silently
    resolved to zero shards would "succeed" with the reduction of
    nothing."""
    if format not in _FORMATS:
        raise ValueError(
            f"format={format!r} is not one of 'auto' | 'parquet' | 'ipc'"
        )
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    shards: List[Tuple[str, str]] = []
    for entry in paths:
        p = os.fspath(entry)
        if os.path.isdir(p):
            exts = _PARQUET_EXTS + _IPC_EXTS
            names = sorted(
                n for n in os.listdir(p)
                if os.path.splitext(n)[1].lower() in exts
            )
            if not names:
                raise ValueError(
                    f"directory {p!r} contains no Parquet/IPC shards"
                )
            shards.extend(
                (os.path.join(p, n), _format_of(n, format)) for n in names
            )
        elif _glob.has_magic(p):
            matches = sorted(_glob.glob(p))
            if not matches:
                raise ValueError(f"glob {p!r} matched no shards")
            shards.extend((m, _format_of(m, format)) for m in matches)
        else:
            if not os.path.exists(p):
                raise FileNotFoundError(f"shard {p!r} does not exist")
            shards.append((p, _format_of(p, format)))
    if not shards:
        raise ValueError("dataset resolved to zero shards")
    return shards


@dataclass(frozen=True)
class ChunkTask:
    """One decodable unit: ``groups`` row-group / record-batch indices
    of one shard file. Cheap to hold (no data), so discovery can run
    ahead of decode through a deeper task queue."""

    shard: str
    format: str
    groups: Tuple[int, ...]
    shard_index: int
    rows: int = field(default=-1)  # from metadata; -1 = unknown


def _group_stats(md, group: int, cols: Sequence[str]):
    """(min, max) per predicate column from one parquet row group's
    footer statistics, or None when any needed column lacks stats (the
    group must then be decoded — pruning is strictly conservative)."""
    try:
        rg = md.row_group(group)
        by_name = {}
        for ci in range(rg.num_columns):
            c = rg.column(ci)
            by_name[c.path_in_schema] = c
        stats = {}
        for name in cols:
            c = by_name.get(name)
            if c is None or c.statistics is None:
                return None
            st = c.statistics
            if not st.has_min_max:
                return None
            stats[name] = (st.min, st.max)
        return stats
    except Exception:
        return None


def _chunk_context(task) -> dict:
    """Stamped onto any exception escaping the decode stage (see
    `pipeline._stamp`): failures name the shard file, not just the
    chunk ordinal."""
    return {"tfs_shard_path": getattr(task, "shard", None)}


class Dataset:
    """The resolved shard list plus the chunking policy.

    ``tasks()`` enumerates `ChunkTask`s in deterministic stream order
    (shards in discovery order, groups ascending, ``chunk_groups``
    groups per task) reading only file METADATA — the discovery stage
    of the pipeline. ``decode(task)`` turns one task into a
    `TensorFrame` — the parallel-decode stage. Shards with zero row
    groups / record batches yield no tasks (an empty shard contributes
    the reduction identity: nothing)."""

    def __init__(
        self,
        paths: Union[PathLike, Sequence[PathLike]],
        format: str = "auto",
        chunk_groups: int = 1,
    ):
        if chunk_groups < 1:
            raise ValueError("chunk_groups must be >= 1")
        self.shards = discover_shards(paths, format=format)
        self.chunk_groups = int(chunk_groups)

    # -- discovery stage -----------------------------------------------
    def _shard_groups(self, path: str, fmt: str):
        """(group count, per-group row counts or None) from file
        METADATA only — discovery must never decode data (the decode
        pool would just re-read it, and a serial full read here is
        exactly the bottleneck the pipeline exists to remove). Parquet
        footers carry row counts; the IPC footer exposes only the batch
        count cheaply, so IPC tasks report ``rows=-1`` (unknown)."""
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pf = pq.ParquetFile(path)
            try:
                md = pf.metadata
                return md.num_row_groups, [
                    md.row_group(i).num_rows
                    for i in range(md.num_row_groups)
                ]
            finally:
                pf.close()
        import pyarrow as pa

        source = pa.OSFile(path, "rb")
        try:
            reader = pa.ipc.open_file(source)
            return reader.num_record_batches, None
        finally:
            source.close()

    def tasks(self) -> Iterator[ChunkTask]:
        path = None
        try:
            for si, (path, fmt) in enumerate(self.shards):
                n_groups, group_rows = self._shard_groups(path, fmt)
                for start in range(0, n_groups, self.chunk_groups):
                    idx = tuple(
                        range(start, min(start + self.chunk_groups, n_groups))
                    )
                    yield ChunkTask(
                        shard=path,
                        format=fmt,
                        groups=idx,
                        shard_index=si,
                        rows=(
                            sum(group_rows[i] for i in idx)
                            if group_rows is not None else -1
                        ),
                    )
        except GeneratorExit:
            raise
        except Exception as e:
            # discovery failures name the shard (the producer stage has
            # no per-stage context hook — it stamps chunk index only)
            if path is not None and getattr(e, "tfs_shard_path", None) is None:
                try:
                    e.tfs_shard_path = path
                except Exception:
                    pass  # __slots__ errors refuse stamps; e still raises
            raise

    def task_list(self) -> List[ChunkTask]:
        """`tasks()` materialized — still METADATA-only (file footers,
        never chunk data). The checkpoint layer uses the list twice:
        once for the dataset fingerprint, once to skip committed
        chunks on resume without re-decoding them."""
        return list(self.tasks())

    def fingerprint(self, tasks: Optional[List[ChunkTask]] = None) -> str:
        """Deterministic digest of the dataset's METADATA identity:
        shard paths + formats + on-disk sizes, the chunking policy,
        and every task's (shard, groups, row-count) tuple. This is
        what the durable-stream manifest records — a resumed stream
        whose dataset gained/lost/resized a shard (or whose row
        groups moved) refuses loudly instead of folding drifted
        chunks onto committed partials. Same-size same-row-count
        content rewrites are beyond a metadata fingerprint; keep
        checkpoints next to immutable datasets."""
        if tasks is None:
            tasks = self.task_list()
        shards = []
        for path, fmt in self.shards:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1
            shards.append([os.path.abspath(path), fmt, size])
        blob = json.dumps(
            {
                "chunk_groups": self.chunk_groups,
                "shards": shards,
                "tasks": [
                    [t.shard, t.format, list(t.groups), t.rows]
                    for t in tasks
                ],
            },
            sort_keys=True,
        )
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    # -- decode stage --------------------------------------------------
    def decode(self, task: ChunkTask, columns=None, predicate=None):
        """One chunk -> one `TensorFrame`; opens and CLOSES the shard
        (try/finally) so a pool of decode workers never accumulates
        handles, and an abandoned stream leaks nothing.

        ``columns`` / ``predicate`` are the plan optimizer's pushdown
        surface (`graph.optimizer`): the column set narrows the
        parquet read to the selected + predicate columns, and the
        predicate prunes whole row groups from footer (min, max) stats
        BEFORE decode — skipped rows count into
        ``plan_pushdown_rows_skipped`` — then masks the survivors at
        the arrow boundary, so fewer rows are decoded, not more rows
        masked. Unknown requested columns are dropped here (the plan's
        select/map stages raise the precise schema error); every
        decoded row counts into ``ingest_rows_decoded``."""
        from ..frame import TensorFrame
        from ..utils import telemetry as _tele

        pred_cols = sorted(predicate.columns()) if predicate is not None else []
        if task.format == "parquet":
            import pyarrow.parquet as pq

            pf = pq.ParquetFile(task.shard)
            try:
                md = pf.metadata
                groups = list(task.groups)
                if predicate is not None:
                    kept, skipped_rows = [], 0
                    for g in groups:
                        stats = _group_stats(md, g, pred_cols)
                        if stats is not None and not predicate.may_match(stats):
                            skipped_rows += md.row_group(g).num_rows
                        else:
                            kept.append(g)
                    if skipped_rows:
                        from ..graph import plan as _plan

                        _plan.note_pushdown_rows(skipped_rows)
                    groups = kept
                schema_names = pf.schema_arrow.names
                read_cols = None
                if columns is not None:
                    read_cols = [
                        c
                        for c in dict.fromkeys(list(columns) + pred_cols)
                        if c in schema_names
                    ]
                if not groups:
                    table = pf.schema_arrow.empty_table()
                    if read_cols is not None:
                        table = table.select(read_cols)
                else:
                    table = pf.read_row_groups(groups, columns=read_cols)
            finally:
                pf.close()
        else:
            import pyarrow as pa

            source = pa.OSFile(task.shard, "rb")
            try:
                reader = pa.ipc.open_file(source)
                batches = [reader.get_batch(i) for i in task.groups]
                table = pa.Table.from_batches(batches, schema=reader.schema)
            finally:
                source.close()
            if columns is not None:
                keep = [
                    c
                    for c in dict.fromkeys(list(columns) + pred_cols)
                    if c in table.column_names
                ]
                table = table.select(keep)
        if predicate is not None and table.num_rows:
            import pyarrow as pa

            mask = predicate.mask(
                lambda n: table.column(n).to_numpy(zero_copy_only=False)
            )
            table = table.filter(pa.array(np.asarray(mask, dtype=bool)))
        if columns is not None:
            keep = [c for c in columns if c in table.column_names]
            table = table.select(keep)
        _tele.counter_inc("ingest_rows_decoded", float(table.num_rows))
        return TensorFrame.from_arrow(table)


def _auto_decode_workers() -> int:
    from .. import config as _config

    w = int(getattr(_config.get(), "ingest_decode_workers", 0) or 0)
    if w > 0:
        return w
    return max(1, min(4, os.cpu_count() or 1))


class IngestStream:
    """A ONE-SHOT iterator of frames backed by the stage-graph
    pipeline: discovery (producer) -> parallel decode (pool). What
    `stream_dataset` returns.

    Iterator semantics match the single-file `io.stream_*` generators
    exactly — ``next()`` works, ``close()`` releases the pipeline (and
    every open shard handle) deterministically, exhaustion is final —
    so the multi-path and single-path readers are interchangeable.
    `reduce_blocks_stream` recognizes an UNSTARTED instance and
    COMPOSES its H2D transfer stage into the same graph
    (`source_and_stages`), so discovery, decode, transfer, compute and
    combine all overlap under one shared buffering budget instead of
    two chained pipelines; a partially-consumed instance degrades to a
    plain chunk iterator."""

    def __init__(
        self,
        dataset: Dataset,
        decode_workers: Optional[int] = None,
        depth: Optional[int] = None,
    ):
        self.dataset = dataset
        self.decode_workers = (
            _auto_decode_workers() if decode_workers is None
            else max(1, int(decode_workers))
        )
        self.depth = depth
        self._active = None  # the running pipeline generator, once started

    def source_and_stages(self, tasks=None, skip: int = 0):
        """(source iterator, [decode stage]) — the pipeline prefix a
        consumer composes further stages onto. ``tasks`` reuses an
        already-materialized `task_list()`; ``skip`` drops the first N
        tasks at the METADATA level (the durable-stream resume path:
        committed chunks are never re-decoded)."""
        decode = PipeStage(
            "decode",
            self.dataset.decode,
            workers=self.decode_workers,
            context=_chunk_context,
            cheap_input=True,  # tasks are descriptors, not chunks
        )
        if skip:
            if tasks is None:
                tasks = self.dataset.task_list()
            source = iter(tasks[int(skip):])
        elif tasks is not None:
            source = iter(tasks)
        else:
            source = self.dataset.tasks()
        return source, [decode]

    @property
    def started(self) -> bool:
        return self._active is not None

    def _pipeline(self):
        if self._active is None:
            source, stages = self.source_and_stages()
            self._active = pipelined(source, stages, depth=self.depth)
        return self._active

    def __iter__(self):
        return self._pipeline()

    def __next__(self):
        return next(self._pipeline())

    def close(self) -> None:
        """Cancel the pipeline and release every buffered chunk and
        open shard handle (a no-op if never started)."""
        if self._active is not None:
            self._active.close()


def stream_dataset(
    paths: Union[PathLike, Sequence[PathLike]],
    format: str = "auto",
    chunk_groups: int = 1,
    decode_workers: Optional[int] = None,
    depth: Optional[int] = None,
) -> IngestStream:
    """Stream a multi-file dataset as frames through the pipelined
    ingest engine: deterministic shard discovery -> parallel decode
    (``decode_workers`` threads, default ``config.
    ingest_decode_workers`` or min(4, cores)) -> in-order delivery,
    all bounded by the shared buffering budget (``depth`` /
    ``config.stream_prefetch_depth``).

    ``paths`` may be a file, directory, glob, or a sequence mixing
    them; ``format`` pins 'parquet' / 'ipc' when extensions cannot
    (``auto``). ``chunk_groups`` row groups / record batches form one
    streamed frame. Feed the result to `reduce_blocks_stream` — the
    H2D transfer stage and the multi-device rotation compose into the
    same stage graph — or iterate it directly."""
    return IngestStream(
        Dataset(paths, format=format, chunk_groups=chunk_groups),
        decode_workers=decode_workers,
        depth=depth,
    )
