"""Pipelined out-of-core ingest engine.

The streaming path used to be a fixed producer -> transfer -> consumer
chain bolted onto `reduce_blocks_stream` (`streaming._prefetch_iter`),
with `io.py` decoding Parquet/IPC row groups serially on the consumer
thread and no multi-file support — devices starved whenever decode ran
slower than compute. This package is the input pipeline as a
first-class concurrent subsystem ("Extending TensorFlow's Semantics
with Pipelined Execution", PAPERS.md):

- `pipeline` — the generic stage-graph runtime: N concurrently
  executing stages over bounded queues, out-of-order parallel workers
  with in-order delivery, per-stage telemetry, classified fault
  retries, deterministic cancellation.
- `dataset` — multi-file shard discovery (directory / glob / explicit
  list of Parquet or Arrow IPC files, deterministic shard order) and
  the parallel-decode stage that turns row groups / record batches
  into frames.

`streaming.reduce_blocks_stream` and the `io.stream_*` readers are
rewired on top; `stream_dataset` is the user-facing entry point.
"""

from .pipeline import (  # noqa: F401
    PipeStage,
    pipelined,
    set_stage_fault_injector,
)
from .dataset import (  # noqa: F401
    ChunkTask,
    Dataset,
    IngestStream,
    discover_shards,
    stream_dataset,
)

__all__ = [
    "ChunkTask",
    "Dataset",
    "IngestStream",
    "PipeStage",
    "discover_shards",
    "pipelined",
    "set_stage_fault_injector",
    "stream_dataset",
]
