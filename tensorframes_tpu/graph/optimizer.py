"""Cost-based rewrite optimizer for the relational plan DAG.

Five rewrite rules run to a bounded fixpoint over `plan.PlanNode`
DAGs — dedup (common subplans collapse to one shared node), filter
reordering below maps, predicate pushdown into the ingest scan (decode
fewer rows, not mask more), column pruning end-to-end into the scan
column set, and fusion of adjacent expression-map stages (the merged
node splices into ONE XLA program at execution, across the relational
boundary the filter used to sit on).

Every structural rewrite is **priced, not assumed**: the whole-plan
cost (modeled bytes through `costmodel`'s residuals-corrected
per-op-class throughput, plus a fixed per-node dispatch overhead) is
computed for the old and the candidate root, and the rewrite is kept
only when the candidate is strictly cheaper. Rejected rewrites are
recorded too — `tfs.explain` shows the decision with both prices, and
`plan.state()["rejected"]` counts them — so a rewrite the ledger
prices as a regression (e.g. pushing a non-selective predicate into
the scan) is visibly declined rather than silently applied.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from . import plan as _plan
from .plan import PlanNode, map_feeds, map_outputs

__all__ = ["optimize", "Estimator"]

# Fixed modeled cost per plan node: dispatch/bookkeeping overhead that
# makes "fewer nodes doing the same bytes" (dedup, map fusion) strictly
# cheaper. Dwarfed by any real data movement.
_NODE_OVERHEAD_S = 5e-4
# Last-resort throughput when the ledger has no calibrated figure yet
# (fresh process): roughly host-memory bandwidth order, bytes/second.
_DEFAULT_BYTES_PER_S = 2.0e9
_COL_BYTES = 8.0  # double-precision column element (x64 default)
_UNKNOWN_ROWS = 1_000_000
_UNKNOWN_COLS = 8


class Estimator:
    """Whole-plan cost in modeled seconds.

    Rows propagate through the DAG (filter/scan predicates scale by the
    verb's selectivity hint or ``config.plan_selectivity_default``);
    bytes = rows x live columns x 8; seconds = bytes / the
    residuals-corrected throughput for the node's op class
    (`costmodel.planner_throughput`) — the measured ledger, not a
    heuristic table.
    """

    def __init__(self, executor=None):
        self._thr: Dict[str, float] = {}
        self._est: Dict[int, Tuple[float, float]] = {}
        from .. import config as _config

        self._default_sel = float(_config.get().plan_selectivity_default)

    # -- throughput -----------------------------------------------------
    def throughput(self, op_class: str) -> float:
        v = self._thr.get(op_class)
        if v is None:
            try:
                from ..runtime import costmodel as _cm

                v = _cm.planner_throughput(op_class)
            except Exception:
                v = None
            if not v or not math.isfinite(v) or v <= 0:
                v = _DEFAULT_BYTES_PER_S
            self._thr[op_class] = v
        return v

    # -- (rows, cols) propagation --------------------------------------
    def shape(self, node: PlanNode) -> Tuple[float, float]:
        got = self._est.get(id(node))
        if got is not None:
            return got
        rows, cols = self._shape(node)
        self._est[id(node)] = (rows, cols)
        return rows, cols

    def _sel(self, hint: Optional[float]) -> float:
        s = self._default_sel if hint is None else float(hint)
        return min(max(s, 0.0), 1.0)

    def _shape(self, node: PlanNode) -> Tuple[float, float]:
        p = node.payload
        if node.op == "source":
            frame = p["frame"]
            try:
                rows = float(_plan._frame_rows(frame))
                cols = float(len(frame.columns))
            except Exception:
                rows, cols = float(_UNKNOWN_ROWS), float(_UNKNOWN_COLS)
            return rows, cols
        if node.op == "scan":
            rows = self._scan_rows(node)
            cols = float(len(p["columns"])) if p.get("columns") else float(
                _UNKNOWN_COLS
            )
            pred = p.get("predicate")
            if pred is not None:
                rows *= self._sel(p.get("selectivity"))
            return rows, cols
        rows, cols = self.shape(node.inputs[0]) if node.inputs else (
            float(_UNKNOWN_ROWS), float(_UNKNOWN_COLS)
        )
        if node.op == "filter":
            return rows * self._sel(p.get("selectivity")), cols
        if node.op == "select":
            return rows, float(len(p["columns"]))
        if node.op == "map":
            return rows, cols + len(map_outputs(p))
        if node.op == "sort":
            return rows, cols
        if node.op == "groupby":
            return max(1.0, math.sqrt(rows)), float(
                len(p["keys"]) + len(p["specs"])
            )
        if node.op == "join":
            rrows, rcols = self.shape(node.inputs[1])
            return max(rows, rrows), cols + rcols - len(p["on"])
        return rows, cols

    def _scan_rows(self, node: PlanNode) -> float:
        cached = node.payload.get("_est_rows")
        if cached is not None:
            return float(cached)
        rows = 0
        known = False
        try:
            for t in node.payload["dataset"].tasks():
                if t.rows is not None and t.rows >= 0:
                    rows += int(t.rows)
                    known = True
        except Exception:
            known = False
        total = float(rows) if known else float(_UNKNOWN_ROWS)
        node.payload["_est_rows"] = total
        return total

    # -- per-node / whole-plan seconds ---------------------------------
    def node_cost(self, node: PlanNode) -> float:
        p = node.payload
        rows_out, cols_out = self.shape(node)
        if node.op == "source":
            return _NODE_OVERHEAD_S
        if node.op == "scan":
            base = self._scan_rows(node)
            pred = p.get("predicate")
            thr = self.throughput("relational")
            cost = base * cols_out * _COL_BYTES / thr  # decode
            if pred is not None:
                # decode-side predicate: evaluate over every candidate
                # row's predicate columns, then RE-materialize only the
                # survivors at the arrow boundary. Statically we do not
                # assume row-group stats will skip anything — so a
                # non-selective pushdown prices as a regression and is
                # rejected, while any sel<1 predicate wins by exactly
                # the avoided re-materialization + downstream rows.
                cost += base * len(pred.columns()) * _COL_BYTES / thr
                cost += rows_out * cols_out * _COL_BYTES / thr
                # the arrow-boundary mask+filter is one extra kernel
                # pass — billed the same fixed overhead as any plan
                # node, so absorbing a filter is never free: at sel=1
                # pushdown prices exactly even and is rejected
                cost += _NODE_OVERHEAD_S
            return cost + _NODE_OVERHEAD_S
        rows_in, cols_in = (
            self.shape(node.inputs[0]) if node.inputs else (0.0, 0.0)
        )
        if node.op == "filter":
            pred = p["pred"]
            thr = self.throughput("relational")
            return (
                rows_in * (len(pred.columns()) + cols_in) * _COL_BYTES / thr
                + _NODE_OVERHEAD_S
            )
        if node.op == "select":
            return _NODE_OVERHEAD_S
        if node.op == "map":
            touched = len(map_feeds(p)) + len(map_outputs(p))
            thr = self.throughput("map")
            return rows_in * touched * _COL_BYTES / thr + _NODE_OVERHEAD_S
        if node.op == "sort":
            thr = self.throughput("relational")
            lg = math.log2(max(rows_in, 2.0))
            return rows_in * lg * len(p["keys"]) * _COL_BYTES / thr + _NODE_OVERHEAD_S
        if node.op == "groupby":
            touched = len(p["keys"]) + len(p["specs"])
            thr = self.throughput("reduce")
            return rows_in * touched * _COL_BYTES / thr + _NODE_OVERHEAD_S
        if node.op == "join":
            rrows, rcols = self.shape(node.inputs[1])
            thr = self.throughput("relational")
            return (
                (rows_in * cols_in + rrows * rcols) * _COL_BYTES / thr
                + _NODE_OVERHEAD_S
            )
        return _NODE_OVERHEAD_S

    def plan_cost(self, root: PlanNode) -> float:
        """Sum of node costs over UNIQUE reachable nodes (a shared
        subplan executes — and is billed — once)."""
        seen: Dict[int, bool] = {}
        total = 0.0

        def rec(node: PlanNode) -> None:
            nonlocal total
            if id(node) in seen:
                return
            seen[id(node)] = True
            total += self.node_cost(node)
            for i in node.inputs:
                rec(i)

        rec(root)
        return total


# ---------------------------------------------------------------------------
# structural rewrites (cost gate applied by optimize())
# ---------------------------------------------------------------------------


def _rebuild(root: PlanNode, fn) -> Tuple[PlanNode, List[str]]:
    """Bottom-up rebuild: ``fn(node, new_inputs)`` returns a replacement
    node (pattern matched) or None (keep). Shared nodes rebuild once so
    DAG sharing survives."""
    memo: Dict[int, PlanNode] = {}
    notes: List[str] = []

    def rec(node: PlanNode) -> PlanNode:
        got = memo.get(id(node))
        if got is not None:
            return got
        new_inputs = tuple(rec(i) for i in node.inputs)
        cand = fn(node, new_inputs, notes)
        if cand is None:
            cand = (
                node
                if new_inputs == node.inputs
                else PlanNode(node.op, new_inputs, node.payload)
            )
        memo[id(node)] = cand
        return cand

    return rec(root), notes


def _rule_dedup(root: PlanNode) -> Tuple[PlanNode, List[str]]:
    """Common-subplan dedup: structurally equal nodes over the same
    leaves collapse to ONE shared node (executes once)."""
    canon: Dict[Any, PlanNode] = {}
    notes: List[str] = []
    memo: Dict[int, PlanNode] = {}

    def key(node: PlanNode, inputs: Tuple[PlanNode, ...]):
        leaf = None
        if node.op == "source":
            leaf = id(node.payload["frame"])
        elif node.op == "scan":
            leaf = id(node.payload["dataset"])
        return (node.op, node._payload_canonical(), leaf,
                tuple(id(i) for i in inputs))

    def rec(node: PlanNode) -> PlanNode:
        got = memo.get(id(node))
        if got is not None:
            return got
        new_inputs = tuple(rec(i) for i in node.inputs)
        cand = (
            node
            if new_inputs == node.inputs
            else PlanNode(node.op, new_inputs, node.payload)
        )
        k = key(cand, new_inputs)
        prior = canon.get(k)
        if prior is not None and prior is not cand:
            notes.append(f"dedup {cand.op}")
            cand = prior
        else:
            canon[k] = cand
        memo[id(node)] = cand
        return cand

    return rec(root), notes


def _rule_filter_below_map(root: PlanNode) -> Tuple[PlanNode, List[str]]:
    """filter(map(X)) -> map(filter(X)) when the predicate only reads
    columns that exist BELOW the map (not produced/shadowed by it):
    the map then touches only surviving rows."""

    def fn(node, ins, notes):
        if node.op != "filter" or not ins or ins[0].op != "map":
            return None
        m = ins[0]
        if len(m.inputs) != 1:
            return None
        pred = node.payload["pred"]
        if pred.columns() & map_outputs(m.payload):
            return None
        notes.append(f"filter ({pred.describe()}) below map")
        pushed = PlanNode("filter", (m.inputs[0],), node.payload)
        return PlanNode("map", (pushed,), m.payload)

    return _rebuild(root, fn)


def _rule_filter_into_scan(root: PlanNode) -> Tuple[PlanNode, List[str]]:
    """filter(scan(ds)) -> scan(ds, predicate): the decode pipeline
    skips whole row groups from footer stats and masks the rest at the
    arrow boundary — fewer rows DECODED, not more rows masked."""

    def fn(node, ins, notes):
        if node.op != "filter" or not ins or ins[0].op != "scan":
            return None
        s = ins[0]
        pred = node.payload["pred"]
        cols = s.payload.get("columns")
        if cols is not None and not pred.columns() <= set(cols):
            return None
        payload = dict(s.payload)
        prior = payload.get("predicate")
        payload["predicate"] = pred if prior is None else (prior & pred)
        sel = node.payload.get("selectivity")
        prior_sel = payload.get("selectivity")
        if sel is not None or prior_sel is not None:
            payload["selectivity"] = (
                (1.0 if sel is None else sel)
                * (1.0 if prior_sel is None else prior_sel)
            )
        notes.append(f"pushdown ({pred.describe()}) into scan")
        return PlanNode("scan", (), payload)

    return _rebuild(root, fn)


def _rule_prune_columns(root: PlanNode) -> Tuple[PlanNode, List[str]]:
    """Column pruning end-to-end into the scan column set. Demands
    propagate top-down (groupby demands exactly keys+agg inputs; select
    demands its list; map adds its feeds net of its outputs); a scan
    whose demanded set is narrower than what it decodes gets its
    ``columns`` payload narrowed."""
    # pass 1: accumulate per-node demand (None = all columns)
    demand: Dict[int, Optional[set]] = {}

    def merge(node: PlanNode, d: Optional[set]) -> None:
        if id(node) in demand:
            prior = demand[id(node)]
            demand[id(node)] = (
                None if prior is None or d is None else prior | d
            )
        else:
            demand[id(node)] = None if d is None else set(d)

    def walk(node: PlanNode, d: Optional[set]) -> None:
        merge(node, d)
        d = demand[id(node)]
        p = node.payload
        if node.op == "select":
            walk(node.inputs[0], set(p["columns"]))
        elif node.op == "filter":
            walk(
                node.inputs[0],
                None if d is None else d | p["pred"].columns(),
            )
        elif node.op == "sort":
            walk(node.inputs[0], None if d is None else d | set(p["keys"]))
        elif node.op == "map":
            if d is None:
                walk(node.inputs[0], None)
            else:
                walk(
                    node.inputs[0],
                    (d - map_outputs(p)) | map_feeds(p),
                )
        elif node.op == "groupby":
            need = set(p["keys"]) | {c for (_, c) in p["specs"].values()}
            walk(node.inputs[0], need)
        elif node.op == "join":
            # splitting demand per side needs schemas; stay safe
            for i in node.inputs:
                walk(i, None)
        else:
            for i in node.inputs:
                walk(i, None)

    walk(root, None)

    notes: List[str] = []

    def fn(node, ins, nts):
        if node.op != "scan":
            return None
        d = demand.get(id(node))
        if d is None:
            return None
        cur = node.payload.get("columns")
        want = tuple(sorted(d))
        if cur is not None and not (set(want) < set(cur)):
            return None  # nothing to narrow (or demand exceeds schema)
        payload = dict(node.payload)
        payload["columns"] = want
        nts.append(f"prune scan columns -> {list(want)}")
        return PlanNode("scan", (), payload)

    return _rebuild(root, fn)


def _rule_fuse_maps(root: PlanNode) -> Tuple[PlanNode, List[str]]:
    """map(map(X)) with expression stages merges into one node; at
    execution the merged stage list splices into ONE fused XLA program
    (fusion across the relational boundary the filter vacated)."""

    def fn(node, ins, notes):
        if node.op != "map" or node.payload.get("kind") == "fused":
            return None
        if not ins or ins[0].op != "map" or ins[0].payload.get("kind") == "fused":
            return None
        inner = ins[0]
        payload = {
            "kind": "exprs",
            "stages": list(inner.payload["stages"]) + list(node.payload["stages"]),
        }
        notes.append(
            f"fuse {len(inner.payload['stages'])}+{len(node.payload['stages'])}"
            " map stage(s)"
        )
        return PlanNode("map", inner.inputs, payload)

    return _rebuild(root, fn)


_RULES = (
    ("dedup", _rule_dedup),
    ("filter_below_map", _rule_filter_below_map),
    ("pushdown_into_scan", _rule_filter_into_scan),
    ("prune_columns", _rule_prune_columns),
    ("fuse_maps", _rule_fuse_maps),
)

_MAX_PASSES = 8


def optimize(root: PlanNode, executor=None) -> Tuple[PlanNode, List[Dict]]:
    """Rewrite ``root`` to a bounded fixpoint; every structural rewrite
    is kept only when the ledger-priced whole-plan cost strictly drops.
    Returns (new root, decision records) — decisions include rejected
    rewrites so `tfs.explain` can show why a plan was NOT changed.
    Runs under a ``plan.optimize`` stage span so `explain_analyze`'s
    coverage contract attributes the optimizer's own time honestly."""
    from ..utils import telemetry as _tele

    decisions: List[Dict] = []
    with _tele.span("plan.optimize", kind="stage"):
        _plan._note_optimize()
        est = Estimator(executor)
        cur = root
        for _ in range(_MAX_PASSES):
            changed = False
            for rule_name, rule in _RULES:
                cand, notes = rule(cur)
                if cand is cur or not notes:
                    continue
                before = est.plan_cost(cur)
                after = est.plan_cost(cand)
                accepted = after < before * (1.0 - 1e-9)
                decisions.append({
                    "rule": rule_name,
                    "accepted": accepted,
                    "cost_before_s": before,
                    "cost_after_s": after,
                    "detail": "; ".join(notes),
                })
                _plan.note_rewrite(rule_name, accepted)
                if accepted:
                    cur = cand
                    changed = True
            if not changed:
                break
    return cur, decisions
