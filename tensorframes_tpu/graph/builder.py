"""Builder DSL: author graphs without TensorFlow, mirroring the reference's
Scala DSL (`dsl/package.scala`, `dsl/Operation.scala`, `dsl/DslImpl.scala`).

Nodes are built lazily ("freeze" semantics, `Operation.scala:86-104`): a
`Tensor` handle records op/parents/attrs; names are assigned at `build()`
time — requested names win, anonymous nodes get TF-style ``op_N`` counters
scoped by `scope()` (the reference's `Paths`, made re-entrant and
thread-safe here via contextvars — the original is documented
thread-UNSAFE, `dsl/Paths.scala:10-12`).

The DSL emits the same TF-compatible NodeDefs as the import path, so DSL
graphs export to GraphDef wire bytes byte-for-byte comparably to graphs a
real TF would build (the reference asserts exactly this in its
`ExtractNodes` golden tests, `dsl/ExtractNodes.scala:14-77`).
"""

from __future__ import annotations

import contextvars
import weakref
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

#: build() memo: fetch-id tuple -> (weakrefs for liveness check, result)
_build_memo: Dict[tuple, tuple] = {}  # tfslint: disable=TFS004 pure memo keyed by live fetch ids (weakref-guarded) — entries die with their tensors, nothing observable leaks across tests

from ..proto.graphdef import AttrValue, TensorProto
from ..schema import ScalarType, Shape
from .ir import Graph, GraphNode

__all__ = [
    "Tensor",
    "scope",
    "placeholder",
    "constant",
    "zeros",
    "ones",
    "fill",
    "identity",
    "add",
    "sub",
    "mul",
    "div",
    "matmul",
    "square",
    "sqrt",
    "reduce_sum",
    "reduce_min",
    "reduce_max",
    "reduce_mean",
    "cast",
    "reshape",
    "expand_dims",
    "concat",
    "argmin",
    "argmax",
    "unsorted_segment_sum",
    "relu",
    "softmax",
    "sigmoid",
    "tanh",
    "build",
    "block",
    "row",
]

_scope_stack: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "tfs_dsl_scope", default=()
)


@contextmanager
def scope(name: str):
    """Name scope, like `dsl.scope` / TF name scopes (`Paths.scala:13-56`)."""
    tok = _scope_stack.set(_scope_stack.get() + (name,))
    try:
        yield
    finally:
        _scope_stack.reset(tok)


class Tensor:
    """Handle to one output of an unfrozen DSL node."""

    def __init__(
        self,
        op: str,
        parents: Sequence["Tensor"],
        attrs: Dict[str, AttrValue],
        dtype: ScalarType,
        requested_name: Optional[str] = None,
        idx: int = 0,
        source: Optional["Tensor"] = None,
    ):
        self.op = op
        self.parents = list(parents)
        self.attrs = dict(attrs)
        self.dtype = dtype
        self.requested_name = requested_name
        self.scope_path = _scope_stack.get()
        self.idx = idx
        self.source = source  # for multi-output handles: the defining node
        # (consumer Tensor, suffix): name this node "<consumer>/<suffix>"
        # at build time — how TF scopes helper constants under the op
        # that owns them (e.g. Sum's "reduction_indices")
        self.name_relative = None
        # anonymous-name counter base when it differs from the op type
        # (TF names anonymous AddV2 nodes "Add", RealDiv "div", ...)
        self.name_base = None

    # -- naming ----------------------------------------------------------
    def named(self, name: str) -> "Tensor":
        """Request an explicit node name (`Operation.named`)."""
        self.requested_name = name
        # renaming is the one post-construction mutation Tensors allow;
        # drop memoized builds so the new name is picked up
        _build_memo.clear()
        return self

    # -- operators (implicit constant conversion, dsl/Implicits.scala) ---
    def _coerce(self, other) -> "Tensor":
        if isinstance(other, Tensor):
            return other
        return constant(np.asarray(other, dtype=self.dtype.np_dtype))

    def __add__(self, other):
        return add(self, self._coerce(other))

    def __radd__(self, other):
        return add(self._coerce(other), self)

    def __sub__(self, other):
        return sub(self, self._coerce(other))

    def __rsub__(self, other):
        return sub(self._coerce(other), self)

    def __mul__(self, other):
        return mul(self, self._coerce(other))

    def __rmul__(self, other):
        return mul(self._coerce(other), self)

    def __truediv__(self, other):
        return div(self, self._coerce(other))

    def __rtruediv__(self, other):
        return div(self._coerce(other), self)

    def __neg__(self):
        return _nary("Neg", [self])

    def __repr__(self) -> str:
        nm = self.requested_name or "?"
        return f"<dsl.Tensor {self.op} {nm} {self.dtype.name}>"


# ---------------------------------------------------------------------------
# node factories
# ---------------------------------------------------------------------------


def _same_dtype(a: Tensor, b: Tensor, op: str) -> ScalarType:
    if a.dtype is not b.dtype:
        raise ValueError(
            f"{op}: dtype mismatch {a.dtype.name} vs {b.dtype.name} "
            "(TF graphs do not promote dtypes; cast explicitly)"
        )
    return a.dtype


def placeholder(
    dtype: ScalarType, shape: Shape, name: Optional[str] = None
) -> Tensor:
    attrs = {
        "dtype": AttrValue.of_type(dtype),
        "shape": AttrValue.of_shape(shape),
    }
    return Tensor("Placeholder", [], attrs, dtype, requested_name=name)


def constant(
    value, dtype: Optional[ScalarType] = None, name: Optional[str] = None
) -> Tensor:
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(dtype.np_dtype)
    elif arr.dtype == np.float64:
        pass  # keep doubles as doubles, like the Scala DSL
    st = ScalarType.from_np_dtype(arr.dtype)
    attrs = {
        "dtype": AttrValue.of_type(st),
        "value": AttrValue.of_tensor(TensorProto.from_numpy(arr)),
    }
    return Tensor("Const", [], attrs, st, requested_name=name)


def zeros(shape, dtype: ScalarType = ScalarType.float64) -> Tensor:
    t = constant(np.zeros(shape, dtype=dtype.np_dtype))
    t.name_base = "zeros"  # TF's anonymous-name base for tf.zeros
    return t


def ones(shape, dtype: ScalarType = ScalarType.float64) -> Tensor:
    t = constant(np.ones(shape, dtype=dtype.np_dtype))
    t.name_base = "ones"
    return t


def fill(shape, value, dtype: Optional[ScalarType] = None) -> Tensor:
    # A real Fill node (dims/value Const children scoped under it), the
    # wire shape TF emits — not a constant-folded Const
    dims = constant(np.asarray(shape, dtype=np.int32))
    val = constant(value, dtype=dtype)
    t = _nary(
        "Fill",
        [dims, val],
        val.dtype,
        {"index_type": AttrValue.of_type(ScalarType.int32)},
    )
    dims.name_relative = (t, "dims")
    val.name_relative = (t, "value")
    return t


def _nary(
    op: str,
    parents: List[Tensor],
    dtype: Optional[ScalarType] = None,
    extra_attrs: Optional[Dict[str, AttrValue]] = None,
    name: Optional[str] = None,
) -> Tensor:
    dt = dtype or parents[0].dtype
    attrs = {"T": AttrValue.of_type(dt)}
    attrs.update(extra_attrs or {})
    return Tensor(op, parents, attrs, dt, requested_name=name)


def identity(x: Tensor, name: Optional[str] = None) -> Tensor:
    return _nary("Identity", [x], name=name)


def add(a: Tensor, b: Tensor, name: Optional[str] = None) -> Tensor:
    # AddV2: what modern TF emits for `tf.add` — the golden structural
    # suite pins our export to the installed TF's wire format (the
    # import path still accepts legacy "Add" from reference fixtures)
    t = _nary("AddV2", [a, b], _same_dtype(a, b, "add"), name=name)
    t.name_base = "Add"  # TF's anonymous-name base for add
    return t


def sub(a: Tensor, b: Tensor, name: Optional[str] = None) -> Tensor:
    return _nary("Sub", [a, b], _same_dtype(a, b, "sub"), name=name)


def mul(a: Tensor, b: Tensor, name: Optional[str] = None) -> Tensor:
    return _nary("Mul", [a, b], _same_dtype(a, b, "mul"), name=name)


def div(a: Tensor, b: Tensor, name: Optional[str] = None) -> Tensor:
    # Modern TF's `tf.div` emits RealDiv for floats (true division) and
    # keeps integer Div truncation; match its wire format per dtype so
    # the golden structural suite holds across the dtype matrix.
    dt = _same_dtype(a, b, "div")
    op = "RealDiv" if dt.is_floating else "Div"
    t = _nary(op, [a, b], dt, name=name)
    if op == "RealDiv":
        t.name_base = "div"  # TF's anonymous-name base for tf.div
    return t


def matmul(a: Tensor, b: Tensor, transpose_a=False, transpose_b=False) -> Tensor:
    extra = {
        "transpose_a": AttrValue.of_bool(transpose_a),
        "transpose_b": AttrValue.of_bool(transpose_b),
        # modern TF stamps gradient-precision flags on every MatMul
        "grad_a": AttrValue.of_bool(False),
        "grad_b": AttrValue.of_bool(False),
    }
    return _nary("MatMul", [a, b], _same_dtype(a, b, "matmul"), extra)


def square(x: Tensor) -> Tensor:
    return _nary("Square", [x])


def sqrt(x: Tensor) -> Tensor:
    return _nary("Sqrt", [x])


def relu(x: Tensor) -> Tensor:
    return _nary("Relu", [x])


def softmax(x: Tensor) -> Tensor:
    return _nary("Softmax", [x])


def sigmoid(x: Tensor) -> Tensor:
    return _nary("Sigmoid", [x])


def tanh(x: Tensor) -> Tensor:
    return _nary("Tanh", [x])


def cast(x: Tensor, dtype: ScalarType) -> Tensor:
    attrs = {
        "SrcT": AttrValue.of_type(x.dtype),
        "DstT": AttrValue.of_type(dtype),
    }
    return Tensor("Cast", [x], attrs, dtype)


def reshape(x: Tensor, shape: Sequence[int]) -> Tensor:
    shp = constant(np.asarray(shape, dtype=np.int32))
    t = _nary(
        "Reshape", [x, shp],
        extra_attrs={"Tshape": AttrValue.of_type(ScalarType.int32)},
    )
    shp.name_relative = (t, "shape")
    return t


def expand_dims(x: Tensor, axis: int) -> Tensor:
    dim = constant(np.int32(axis))
    t = _nary(
        "ExpandDims", [x, dim],
        extra_attrs={"Tdim": AttrValue.of_type(ScalarType.int32)},
    )
    dim.name_relative = (t, "dim")
    return t


def concat(xs: Sequence[Tensor], axis: int) -> Tensor:
    dt = xs[0].dtype
    for x in xs[1:]:
        if x.dtype is not dt:
            raise ValueError(
                f"concat: inputs disagree on dtype ({dt.name} vs "
                f"{x.dtype.name}); cast first"
            )
    ax = constant(np.int32(axis))
    t = _nary(
        "ConcatV2", list(xs) + [ax], dt,
        {
            "N": AttrValue.of_int(len(xs)),
            "Tidx": AttrValue.of_type(ScalarType.int32),
        },
    )
    t.name_base = "concat"  # TF's anonymous-name base for tf.concat
    ax.name_relative = (t, "axis")
    return t


def _reducer(
    op: str, x: Tensor, axes: Optional[Sequence[int]], keep_dims: bool
) -> Tensor:
    """Reduction with a `reduction_indices` Const child, matching
    `DslImpl.build_reducer` (`DslImpl.scala:175-188`)."""
    if axes is None:
        axes = []
    idx = constant(np.asarray(list(axes), dtype=np.int32))
    extra = {
        "keep_dims": AttrValue.of_bool(keep_dims),
        "Tidx": AttrValue.of_type(ScalarType.int32),
    }
    t = _nary(op, [x, idx], x.dtype, extra)
    # TF scopes the axis constant under the reduce node's (final) name
    idx.name_relative = (t, "reduction_indices")
    return t


def reduce_sum(x: Tensor, axes=None, keep_dims=False, name=None) -> Tensor:
    return _reducer("Sum", x, axes, keep_dims).named(name) if name else _reducer(
        "Sum", x, axes, keep_dims
    )


def reduce_min(x: Tensor, axes=None, keep_dims=False) -> Tensor:
    return _reducer("Min", x, axes, keep_dims)


def reduce_max(x: Tensor, axes=None, keep_dims=False) -> Tensor:
    return _reducer("Max", x, axes, keep_dims)


def reduce_mean(x: Tensor, axes=None, keep_dims=False) -> Tensor:
    return _reducer("Mean", x, axes, keep_dims)


def _arg_reducer(op: str, x: Tensor, axis: int) -> Tensor:
    """ArgMin/ArgMax with TF's `dimension` const child + index attrs."""
    dim = constant(np.int32(axis))
    t = _nary(
        op, [x, dim], x.dtype,
        {
            "Tidx": AttrValue.of_type(ScalarType.int32),
            "output_type": AttrValue.of_type(ScalarType.int64),
        },
    )
    t.dtype = ScalarType.int64
    dim.name_relative = (t, "dimension")
    return t


def argmin(x: Tensor, axis: int = 0) -> Tensor:
    return _arg_reducer("ArgMin", x, axis)


def argmax(x: Tensor, axis: int = 0) -> Tensor:
    return _arg_reducer("ArgMax", x, axis)


def unsorted_segment_sum(data: Tensor, ids: Tensor, num_segments: int) -> Tensor:
    n = constant(np.int32(num_segments))
    return _nary(
        "UnsortedSegmentSum", [data, ids, n], data.dtype,
        {"Tindices": AttrValue.of_type(ids.dtype)},
    )


# ---------------------------------------------------------------------------
# frame integration (dsl.block / dsl.row, `dsl/package.scala:92-112`)
# ---------------------------------------------------------------------------


def block(frame, col_name: str, tf_name: Optional[str] = None) -> Tensor:
    """Placeholder matching a column's *block* (unknown lead dim), named
    after the column (`extractPlaceholder`, `DslImpl.scala:90-107`)."""
    info = frame.info[col_name]
    return placeholder(
        info.dtype, info.block_shape, name=tf_name or col_name
    )


def row(frame, col_name: str, tf_name: Optional[str] = None) -> Tensor:
    """Placeholder matching a single row's cell of a column."""
    info = frame.info[col_name]
    return placeholder(info.dtype, info.cell_shape, name=tf_name or col_name)


# ---------------------------------------------------------------------------
# freeze: Tensor closure -> Graph
# ---------------------------------------------------------------------------


def build(fetches: Union[Tensor, Sequence[Tensor]]) -> (Graph, List[str]):
    """Freeze the transitive closure of ``fetches`` into a `Graph`.

    Returns (graph, fetch_names). Name assignment: requested names win;
    anonymous nodes get ``<scope>/<op_lower>_<k>`` counters
    (`Paths.scala:40-55`, `DslImpl.buildGraph`).
    """
    if isinstance(fetches, Tensor):
        fetches = [fetches]
    # Memoize per fetch-tuple identity: verbs rebuild the graph on every
    # call otherwise (re-serializing it dominated chained-verb dispatch).
    # Tensors are immutable once created, so identity is a sound key.
    memo_key = tuple(id(f) for f in fetches)
    cached = _build_memo.get(memo_key)
    if cached is not None and all(
        a() is b for a, b in zip(cached[0], fetches)
    ):
        return cached[1]
    order: List[Tensor] = []
    seen: Dict[int, bool] = {}

    def visit(t: Tensor):
        root = t.source or t
        if id(root) in seen:
            return
        seen[id(root)] = True
        for p in root.parents:
            visit(p)
        order.append(root)

    for f in fetches:
        visit(f)

    counters: Dict[str, int] = {}
    names: Dict[int, str] = {}
    used = set()
    for t in order:
        if t.name_relative is not None:
            continue  # named after its consumer in the second pass
        if t.requested_name:
            name = "/".join(t.scope_path + (t.requested_name,))
        else:
            base = "/".join(t.scope_path + (t.name_base or t.op,))
            k = counters.get(base, 0)
            name = base if k == 0 else f"{base}_{k}"
            counters[base] = k + 1
            while name in used:
                k = counters[base]
                name = f"{base}_{k}"
                counters[base] = k + 1
        if name in used:
            raise ValueError(f"duplicate node name {name!r} in DSL graph")
        used.add(name)
        names[id(t)] = name
    for t in order:
        if t.name_relative is None:
            continue
        consumer, suffix = t.name_relative
        root = consumer.source or consumer
        name = f"{names[id(root)]}/{suffix}"
        if name in used:
            raise ValueError(f"duplicate node name {name!r} in DSL graph")
        used.add(name)
        names[id(t)] = name

    g = Graph()
    for t in order:
        edges = []
        for p in t.parents:
            root = p.source or p
            e = names[id(root)]
            if p.idx:
                e = f"{e}:{p.idx}"
            edges.append(e)
        g.add(GraphNode(names[id(t)], t.op, edges, dict(t.attrs)))

    fetch_names = []
    for f in fetches:
        root = f.source or f
        n = names[id(root)]
        fetch_names.append(f"{n}:{f.idx}" if f.idx else n)
    if len(_build_memo) > 256:  # bound the memo
        _build_memo.clear()
    _build_memo[memo_key] = (
        [weakref.ref(f) for f in fetches],
        (g, fetch_names),
    )
    return g, fetch_names
