"""Relational plan DAG: first-class filter/select/sort/join/groupby nodes.

The five paper verbs are map/reduce-shaped linear chains (`lazy.py`
fuses them into ONE graph via `fuse.splice`); real traffic is
filter/join/groupby-shaped. This module generalizes the linear fused
chain into a **plan DAG** whose nodes are either relational verbs or
opaque "map" nodes wrapping the existing fused-chain machinery — the
HiFrames observation (arxiv 1704.02341): compiling frame operators into
the same parallel IR as the numeric code, instead of executing them as
library calls, is worth integer factors.

Three layers live here:

* `Col` / `Pred` — a tiny predicate expression tree (`col("x") > 0.5`,
  `&`/`|`/`~`) that evaluates as a numpy *or* jax mask, prices itself,
  prunes parquet row groups from footer statistics (`may_match`), and
  fingerprints **canonically** (commutative `&`/`|` operands sort), so
  semantically equal predicates key the same cached plan.
* `PlanNode` — immutable DAG node (`source`/`scan`/`map`/`filter`/
  `select`/`sort`/`groupby`/`join`) with structural and data
  fingerprints; `graph.optimizer` rewrites these.
* `execute` — lowers an (optimized) DAG onto the existing executors:
  map nodes replay through `LazyFrame` (one fused XLA program per
  chain, the global SPMD route included), filters on a `GlobalFrame`
  go through `globalframe.filter_global` (mask dispatch + compact),
  groupby-agg through the segment-aggregate recipe, and everything a
  sharded primitive cannot express falls back LOUDLY to the local
  block path with a counted ``plan_fallbacks{reason=}`` — never a
  silent wrong answer.

Process-global accounting (rewrites / fallbacks / pushdown rows) lives
behind `_LOCK` with the standard `state()` / `reset_state()` pair; the
conftest autouse fixture resets it between tests.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Col",
    "Pred",
    "col",
    "PlanNode",
    "execute",
    "render",
    "plan_fingerprint",
    "data_fingerprint",
    "map_outputs",
    "map_feeds",
    "note_fallback",
    "note_rewrite",
    "note_pushdown_rows",
    "note_cache_hit",
    "state",
    "reset_state",
]

AGG_OPS = ("sum", "mean", "min", "max")

# ---------------------------------------------------------------------------
# accounting (module-global; lock-guarded; reset via conftest autouse)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()


def _new_acct() -> Dict[str, Any]:
    return {
        "optimize_runs": 0,
        "rewrites": {},  # rule -> accepted count
        "rejected": {},  # rule -> cost-rejected count
        "fallbacks": {},  # reason -> count
        "pushdown_rows_skipped": 0,
        "executed_nodes": 0,
        "forces": 0,
        "cache_hits": 0,
    }


_ACCT = _new_acct()


def note_rewrite(rule: str, accepted: bool) -> None:
    """Record one optimizer decision; only ACCEPTED rewrites hit the
    `plan_rewrites{rule=}` counter (rejections stay visible in
    `state()["rejected"]` and in `tfs.explain`)."""
    with _LOCK:
        key = "rewrites" if accepted else "rejected"
        _ACCT[key][rule] = _ACCT[key].get(rule, 0) + 1
    if accepted:
        from ..utils import telemetry as _tele

        _tele.counter_inc("plan_rewrites", 1, rule=rule)


def note_fallback(reason: str) -> None:
    with _LOCK:
        _ACCT["fallbacks"][reason] = _ACCT["fallbacks"].get(reason, 0) + 1
    from ..utils import telemetry as _tele

    _tele.counter_inc("plan_fallbacks", 1, reason=reason)


def note_pushdown_rows(n: int) -> None:
    """Rows the scan pushdown PROVABLY skipped decoding (parquet
    row-group stats pruning) — the honest counter behind the "decode
    fewer rows, not mask more" claim."""
    if n <= 0:
        return
    with _LOCK:
        _ACCT["pushdown_rows_skipped"] += int(n)
    from ..utils import telemetry as _tele

    _tele.counter_inc("plan_pushdown_rows_skipped", int(n))


def note_cache_hit() -> None:
    with _LOCK:
        _ACCT["cache_hits"] += 1


def _note_optimize() -> None:
    with _LOCK:
        _ACCT["optimize_runs"] += 1


def _note_force() -> None:
    with _LOCK:
        _ACCT["forces"] += 1


def state() -> Dict[str, Any]:
    """Snapshot of the plan/optimizer ledger (diagnostics section)."""
    with _LOCK:
        return {
            "optimize_runs": _ACCT["optimize_runs"],
            "forces": _ACCT["forces"],
            "executed_nodes": _ACCT["executed_nodes"],
            "cache_hits": _ACCT["cache_hits"],
            "pushdown_rows_skipped": _ACCT["pushdown_rows_skipped"],
            "rewrites": dict(_ACCT["rewrites"]),
            "rejected": dict(_ACCT["rejected"]),
            "fallbacks": dict(_ACCT["fallbacks"]),
        }


def reset_state() -> None:
    global _ACCT
    with _LOCK:
        _ACCT = _new_acct()


# ---------------------------------------------------------------------------
# predicate expression tree
# ---------------------------------------------------------------------------


class Col:
    """A column reference inside a predicate: ``col("x") > 0.5``."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def _cmp(self, op: str, other) -> "Pred":
        return Pred("cmp", op=op, left=self, right=other)

    def __gt__(self, other):
        return self._cmp("gt", other)

    def __ge__(self, other):
        return self._cmp("ge", other)

    def __lt__(self, other):
        return self._cmp("lt", other)

    def __le__(self, other):
        return self._cmp("le", other)

    def __eq__(self, other):  # type: ignore[override]
        return self._cmp("eq", other)

    def __ne__(self, other):  # type: ignore[override]
        return self._cmp("ne", other)

    def __hash__(self):
        return hash(("Col", self.name))

    def __repr__(self):
        return f"col({self.name!r})"


def col(name: str) -> Col:
    """Predicate column reference (the relational DSL entry point)."""
    return Col(name)


_CMP_FNS: Dict[str, Callable] = {
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
}
_CMP_TEXT = {"gt": ">", "ge": ">=", "lt": "<", "le": "<=", "eq": "==", "ne": "!="}


class Pred:
    """Predicate tree node: comparison (`cmp`) or `and`/`or`/`not`.

    Evaluates against any column lookup (numpy on host, jax inside a
    jitted mask program); `may_match` consults (min, max) column stats
    conservatively so parquet row groups can be skipped *before*
    decode; `fingerprint()` is canonical under commutativity (the
    operands of `&`, `|`, `==`, `!=` sort), which is what lets
    reordered-but-equal plans share one materialization-cache key.
    """

    __slots__ = ("kind", "op", "left", "right", "children")

    def __init__(self, kind, op=None, left=None, right=None, children=()):
        self.kind = kind
        self.op = op
        self.left = left
        self.right = right
        self.children = tuple(children)

    # -- combinators ----------------------------------------------------
    def __and__(self, other: "Pred") -> "Pred":
        return Pred("and", children=(self, other))

    def __or__(self, other: "Pred") -> "Pred":
        return Pred("or", children=(self, other))

    def __invert__(self) -> "Pred":
        return Pred("not", children=(self,))

    def __bool__(self):
        raise TypeError(
            "Pred is not a python boolean; combine predicates with "
            "`&` / `|` / `~`, not `and` / `or` / `not`"
        )

    # -- introspection --------------------------------------------------
    def columns(self) -> set:
        if self.kind == "cmp":
            cols = {self.left.name}
            if isinstance(self.right, Col):
                cols.add(self.right.name)
            return cols
        out: set = set()
        for c in self.children:
            out |= c.columns()
        return out

    def mask(self, getcol: Callable[[str], Any]):
        """Boolean mask over rows; works for numpy and jax arrays."""
        if self.kind == "cmp":
            lhs = getcol(self.left.name)
            rhs = (
                getcol(self.right.name)
                if isinstance(self.right, Col)
                else self.right
            )
            return _CMP_FNS[self.op](lhs, rhs)
        masks = [c.mask(getcol) for c in self.children]
        if self.kind == "and":
            out = masks[0]
            for m in masks[1:]:
                out = out & m
            return out
        if self.kind == "or":
            out = masks[0]
            for m in masks[1:]:
                out = out | m
            return out
        return ~masks[0]  # not

    def may_match(self, stats: Dict[str, Tuple[Any, Any]]) -> bool:
        """Conservative row-group test from (min, max) column stats:
        False ONLY when the group provably contains no matching row —
        missing stats or inexpressible shapes always keep the group."""
        if self.kind == "cmp":
            if isinstance(self.right, Col):
                return True  # col-vs-col: stats cannot decide
            st = stats.get(self.left.name)
            if st is None:
                return True
            mn, mx = st
            if mn is None or mx is None:
                return True
            try:
                v = self.right
                if self.op == "gt":
                    return mx > v
                if self.op == "ge":
                    return mx >= v
                if self.op == "lt":
                    return mn < v
                if self.op == "le":
                    return mn <= v
                if self.op == "eq":
                    return mn <= v <= mx
                if self.op == "ne":
                    return not (mn == mx == v)
            except TypeError:
                return True
            return True
        if self.kind == "and":
            return all(c.may_match(stats) for c in self.children)
        if self.kind == "or":
            return any(c.may_match(stats) for c in self.children)
        return True  # not: negating range logic is not conservative

    # -- identity -------------------------------------------------------
    def fingerprint(self) -> str:
        return _short(self._canonical())

    def _canonical(self) -> str:
        if self.kind == "cmp":
            lhs = f"c:{self.left.name}"
            rhs = (
                f"c:{self.right.name}"
                if isinstance(self.right, Col)
                else f"v:{self.right!r}"
            )
            if self.op in ("eq", "ne") and rhs < lhs:
                lhs, rhs = rhs, lhs  # commutative comparison
            return f"({self.op} {lhs} {rhs})"
        parts = [c._canonical() for c in self.children]
        if self.kind in ("and", "or"):
            parts.sort()  # commutative + associative at this arity
        return f"({self.kind} {' '.join(parts)})"

    def describe(self) -> str:
        if self.kind == "cmp":
            rhs = (
                self.right.name if isinstance(self.right, Col) else repr(self.right)
            )
            return f"{self.left.name} {_CMP_TEXT[self.op]} {rhs}"
        if self.kind == "not":
            return f"~({self.children[0].describe()})"
        joiner = " & " if self.kind == "and" else " | "
        return "(" + joiner.join(c.describe() for c in self.children) + ")"

    def __repr__(self):
        return f"Pred<{self.describe()}>"


def _short(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# plan DAG nodes
# ---------------------------------------------------------------------------


class PlanNode:
    """One immutable relational plan node.

    ops: ``source`` (in-memory TensorFrame/GlobalFrame leaf), ``scan``
    (ingest Dataset leaf; payload carries the pushed-down column set +
    predicate), ``map`` (opaque fused-chain or expr stages), ``filter``,
    ``select``, ``sort``, ``groupby``, ``join``.
    """

    __slots__ = ("op", "inputs", "payload", "_plan_fp", "_data_fp")

    def __init__(self, op: str, inputs: Sequence["PlanNode"] = (),
                 payload: Optional[Dict[str, Any]] = None):
        self.op = op
        self.inputs = tuple(inputs)
        self.payload = dict(payload or {})
        self._plan_fp: Optional[str] = None
        self._data_fp: Optional[Tuple[bool, Optional[str]]] = None

    # -- payload digests ------------------------------------------------
    def _payload_canonical(self) -> str:
        p = self.payload
        if self.op == "source":
            return "source"
        if self.op == "scan":
            cols = ",".join(p.get("columns") or ())
            pred = p.get("predicate")
            ptxt = pred._canonical() if pred is not None else ""
            return f"scan cols=[{cols}] pred={ptxt}"
        if self.op == "map":
            if p.get("kind") == "fused":
                from . import fuse as _fuse

                return "map fused " + _fuse.chain_fingerprint(
                    p["graph"], p["feed_map"], sorted(p["sources"])
                )
            parts = []
            for st in p["stages"]:
                fd = st.get("feed_dict") or {}
                parts.append(
                    st["graph"].fingerprint()
                    + "|"
                    + ",".join(f"{k}={v}" for k, v in sorted(fd.items()))
                    + "|"
                    + ",".join(st["fetch_list"])
                )
            return "map exprs " + ";".join(parts)
        if self.op == "filter":
            sel = p.get("selectivity")
            return f"filter {p['pred']._canonical()} sel={sel}"
        if self.op == "select":
            return "select " + ",".join(p["columns"])
        if self.op == "sort":
            return (
                "sort " + ",".join(p["keys"])
                + (" desc" if p.get("descending") else "")
            )
        if self.op == "groupby":
            specs = ",".join(
                f"{out}={op_}({c})"
                for out, (op_, c) in sorted(p["specs"].items())
            )
            return "groupby " + ",".join(p["keys"]) + " agg " + specs
        if self.op == "join":
            return f"join on={','.join(p['on'])} how={p.get('how', 'inner')}"
        raise ValueError(f"unknown plan op {self.op!r}")

    def describe(self) -> str:
        """One explain line (payload summary, no fingerprints)."""
        p = self.payload
        if self.op == "source":
            frame = p["frame"]
            kind = type(frame).__name__
            return f"source[{kind}] rows={_frame_rows(frame)}"
        if self.op == "scan":
            cols = p.get("columns")
            pred = p.get("predicate")
            bits = [f"columns={list(cols)}" if cols else "columns=*"]
            if pred is not None:
                bits.append(f"predicate=({pred.describe()})")
            return "scan " + " ".join(bits)
        if self.op == "map":
            kind = p.get("kind")
            outs = sorted(map_outputs(self.payload))
            if kind == "fused":
                return f"map[fused chain] -> {outs}"
            return f"map[{len(p['stages'])} stage(s)] -> {outs}"
        if self.op == "filter":
            sel = p.get("selectivity")
            hint = f" sel~{sel}" if sel is not None else ""
            return f"filter ({p['pred'].describe()}){hint}"
        if self.op == "select":
            return f"select {list(p['columns'])}"
        if self.op == "sort":
            d = " descending" if p.get("descending") else ""
            return f"sort_by {list(p['keys'])}{d}"
        if self.op == "groupby":
            specs = {
                out: f"{op_}({c})" for out, (op_, c) in sorted(p["specs"].items())
            }
            return f"group_by {list(p['keys'])} agg {specs}"
        if self.op == "join":
            return f"join on={list(p['on'])} how={p.get('how', 'inner')}"
        return self.op


def map_outputs(payload: Dict[str, Any]) -> set:
    """Column names a map node PRODUCES (shadowing passthroughs)."""
    if payload.get("kind") == "fused":
        return set(payload["sources"])
    out: set = set()
    for st in payload["stages"]:
        out |= {f.split(":")[0] for f in st["fetch_list"]}
    return out


def map_feeds(payload: Dict[str, Any]) -> set:
    """Column names a map node READS from its INPUT frame. For a
    multi-stage expression chain, later stages reading an earlier
    stage's output are internal — the reverse walk nets them out so
    column pruning never demands a column that only exists inside the
    chain."""
    if payload.get("kind") == "fused":
        return set(payload["feed_map"].values())
    need: set = set()
    for st in reversed(payload["stages"]):
        outs = {f.split(":")[0] for f in st["fetch_list"]}
        need = (need - outs) | set(st.get("feeds") or ())
    return need


def _frame_rows(frame) -> int:
    if hasattr(frame, "nrows"):
        return int(frame.nrows)
    names = frame.columns
    return len(frame.column(names[0])) if names else 0


# ---------------------------------------------------------------------------
# fingerprints (structural plan key + leaf data key)
# ---------------------------------------------------------------------------


def plan_fingerprint(root: PlanNode) -> str:
    """Canonical structural fingerprint of the DAG: payloads digest
    canonically (predicates sort commutative operands), leaves
    contribute only their ordinal — two semantically equal plans over
    the same-shaped inputs share this key regardless of how they were
    authored. Combined with `data_fingerprint` it keys the
    materialization cache."""
    memo: Dict[int, str] = {}

    def rec(node: PlanNode) -> str:
        fp = memo.get(id(node))
        if fp is None:
            if node._plan_fp is not None:
                fp = node._plan_fp
            else:
                kids = ",".join(rec(i) for i in node.inputs)
                fp = _short(f"{node._payload_canonical()}[{kids}]")
                node._plan_fp = fp
            memo[id(node)] = fp
        return fp

    return rec(root)


def data_fingerprint(root: PlanNode) -> Optional[str]:
    """Digest of every leaf's DATA (frame fingerprint / dataset
    fingerprint) in DFS order, or None when any leaf is not
    fingerprintable (device-resident frame, unknown dataset) — the
    caller then skips the materialization cache entirely."""
    h = hashlib.sha256()
    seen: Dict[int, bool] = {}

    def rec(node: PlanNode) -> bool:
        cached = seen.get(id(node))
        if cached is not None:
            return cached
        ok = True
        if node.op == "source":
            fp = _source_data_fp(node)
            if fp is None:
                ok = False
            else:
                h.update(fp.encode())
        elif node.op == "scan":
            try:
                h.update(node.payload["dataset"].fingerprint().encode())
            except Exception:
                ok = False
        else:
            for i in node.inputs:
                if not rec(i):
                    ok = False
                    break
        seen[id(node)] = ok
        return ok

    return h.hexdigest() if rec(root) else None


def _source_data_fp(node: PlanNode) -> Optional[str]:
    cached = node._data_fp
    if cached is not None:
        return cached[1]
    from ..runtime import materialize as _mat

    try:
        fp = _mat.frame_fingerprint(node.payload["frame"])
    except Exception:
        fp = None
    node._data_fp = (True, fp)
    return fp


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------


def execute(root: PlanNode, executor=None):
    """Run the (optimized) DAG bottom-up. Shared subplans execute once
    (the structural-dedup rewrite makes equal subplans the SAME node,
    so an id-keyed memo suffices); every node runs under a
    ``plan.<op>`` stage span so `explain_analyze` attributes it."""
    memo: Dict[int, Any] = {}

    def run(node: PlanNode):
        if id(node) in memo:
            return memo[id(node)]
        ins = [run(i) for i in node.inputs]
        out = _EXEC[node.op](node, ins, executor)
        memo[id(node)] = out
        with _LOCK:
            _ACCT["executed_nodes"] += 1
        return out

    return run(root)


def _is_global(frame) -> bool:
    from .. import globalframe as _gfm

    return isinstance(frame, _gfm.GlobalFrame)


def _localize(frame, reason: str):
    """Loud, counted crossing from the SPMD path to the local block
    path for constructs the sharded primitives cannot express."""
    if _is_global(frame):
        note_fallback(reason)
        return frame.to_frame()
    return frame


def _exec_source(node, ins, executor):
    return node.payload["frame"]


def _exec_scan(node, ins, executor):
    from ..frame import TensorFrame
    from ..utils import telemetry as _tele

    ds = node.payload["dataset"]
    cols = node.payload.get("columns")
    pred = node.payload.get("predicate")
    with _tele.span(
        "plan.scan", kind="stage",
        predicate=pred.describe() if pred is not None else None,
        columns=",".join(cols) if cols else None,
    ):
        frames = [
            ds.decode(t, columns=list(cols) if cols else None, predicate=pred)
            for t in ds.tasks()
        ]
        if len(frames) == 1:
            return frames[0]
        names = list(cols) if cols else frames[0].columns
        data = {
            n: np.concatenate([np.asarray(f.host_values(n)) for f in frames])
            for n in names
        }
        total = len(next(iter(data.values()))) if names else 0
        nb = max(1, min(len(frames), total or 1))
        return TensorFrame.from_dict(data, num_blocks=nb)


def _exec_map(node, ins, executor):
    from ..lazy import LazyFrame

    frame = ins[0]
    p = node.payload
    if p.get("kind") == "fused":
        lf = LazyFrame(
            frame,
            graph=p["graph"],
            sources=dict(p["sources"]),
            feed_map=dict(p["feed_map"]),
            stages=list(p["stages"]),
        )
        return lf.force(executor=executor)
    lf = frame.lazy()
    for st in p["stages"]:
        lf = lf.map_blocks(
            st["graph"],
            feed_dict=dict(st["feed_dict"]) if st.get("feed_dict") else None,
            fetch_names=list(st["fetch_list"]),
        )
    return lf.force(executor=executor)


def _exec_filter(node, ins, executor):
    from ..frame import TensorFrame
    from ..utils import telemetry as _tele

    frame = ins[0]
    pred = node.payload["pred"]
    if _is_global(frame):
        from .. import globalframe as _gfm

        out = _gfm.filter_global(pred, frame, executor)
        if out is not None:
            return out
        frame = _localize(frame, "filter-ineligible")
    with _tele.span(
        "plan.filter", kind="stage", predicate=pred.describe(),
        rows=_frame_rows(frame),
    ):
        mask = np.asarray(pred.mask(frame.host_values), dtype=bool)
        take = np.flatnonzero(mask)
        data = {n: frame.host_values(n)[take] for n in frame.columns}
        nb = max(1, min(frame.num_blocks, len(take) or 1))
        return TensorFrame.from_dict(data, num_blocks=nb)


def _exec_select(node, ins, executor):
    return ins[0].select(list(node.payload["columns"]))


def _exec_sort(node, ins, executor):
    from ..frame import TensorFrame
    from ..utils import telemetry as _tele

    frame = _localize(ins[0], "sort-global")
    keys = node.payload["keys"]
    with _tele.span(
        "plan.sort", kind="stage", keys=",".join(keys),
        rows=_frame_rows(frame),
    ):
        arrays = [np.asarray(frame.host_values(k)) for k in keys]
        order = np.lexsort(tuple(reversed(arrays)))
        if node.payload.get("descending"):
            order = order[::-1]
        data = {n: frame.host_values(n)[order] for n in frame.columns}
        return TensorFrame.from_dict(
            data, num_blocks=max(1, frame.num_blocks)
        )


def _exec_groupby(node, ins, executor):
    from .. import api as _api
    from ..utils import telemetry as _tele

    frame = ins[0]
    keys = list(node.payload["keys"])
    specs = node.payload["specs"]
    with _tele.span(
        "plan.groupby", kind="stage", keys=",".join(keys),
        aggs=len(specs),
    ):
        # GroupedFrame handles the GlobalFrame crossing itself; the
        # segment-aggregate recipe then runs ONE whole-frame dispatch
        # (sum/mean/min/max all classify as segment combiners)
        grouped = _api.GroupedFrame(frame, keys)
        fetches, feed = _api._agg_spec_exprs(grouped.frame, specs)
        return _api.aggregate(
            fetches, grouped, feed_dict=feed, executor=executor
        )


def _exec_join(node, ins, executor):
    from ..frame import TensorFrame
    from ..utils import telemetry as _tele

    left = _localize(ins[0], "join-global")
    right = _localize(ins[1], "join-global")
    on = list(node.payload["on"])
    with _tele.span(
        "plan.join", kind="stage", on=",".join(on),
        left_rows=_frame_rows(left), right_rows=_frame_rows(right),
    ):
        import pandas as pd

        ldf = pd.DataFrame({k: np.asarray(left.host_values(k)) for k in on})
        ldf["__tfs_li"] = np.arange(len(ldf), dtype=np.int64)
        rdf = pd.DataFrame({k: np.asarray(right.host_values(k)) for k in on})
        rdf["__tfs_ri"] = np.arange(len(rdf), dtype=np.int64)
        merged = pd.merge(ldf, rdf, on=on, how="inner")
        li = merged["__tfs_li"].to_numpy()
        ri = merged["__tfs_ri"].to_numpy()
        data: Dict[str, np.ndarray] = {}
        for n in left.columns:
            data[n] = np.asarray(left.host_values(n))[li]
        for n in right.columns:
            if n in on:
                continue
            out_name = n if n not in data else f"{n}_right"
            data[out_name] = np.asarray(right.host_values(n))[ri]
        return TensorFrame.from_dict(data, num_blocks=1)


_EXEC = {
    "source": _exec_source,
    "scan": _exec_scan,
    "map": _exec_map,
    "filter": _exec_filter,
    "select": _exec_select,
    "sort": _exec_sort,
    "groupby": _exec_groupby,
    "join": _exec_join,
}


# ---------------------------------------------------------------------------
# rendering (tfs.explain — never executes)
# ---------------------------------------------------------------------------


def render(root: PlanNode, annotate: Optional[Callable[[PlanNode], str]] = None) -> str:
    """Indented DAG text. Shared subplans print once and are referenced
    by their node number afterwards."""
    lines: List[str] = []
    numbered: Dict[int, int] = {}

    def rec(node: PlanNode, depth: int) -> None:
        pad = "  " * depth
        if id(node) in numbered:
            lines.append(f"{pad}#{numbered[id(node)]} (shared, see above)")
            return
        num = len(numbered) + 1
        numbered[id(node)] = num
        extra = f"  [{annotate(node)}]" if annotate is not None else ""
        lines.append(f"{pad}#{num} {node.describe()}{extra}")
        for i in node.inputs:
            rec(i, depth + 1)

    rec(root, 0)
    return "\n".join(lines)
