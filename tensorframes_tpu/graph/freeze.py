"""Variable freezing: stateful imported graphs become constant graphs.

The reference ships only stateless graphs: its Python front-end calls
``tf.graph_util.convert_variables_to_constants`` on every user graph
before serialization (`core.py:42-56`), running a throwaway session to
read each variable's value. This framework has no session state at all,
so the equivalent transform evaluates each variable's *initializer
subgraph* through the normal JAX lowering and splices the result in as a
``Const`` node. Two wire patterns are handled:

- **Reference-era ref variables** (TF 1.x protos, e.g. the frozen graphs
  the reference loads from disk, `PythonInterface.scala:115-118`):
  ``Variable``/``VariableV2`` nodes initialized by ``Assign(var, value)``.
- **Resource variables** (graphs exported by modern TF, which is what the
  conformance suite's TF emits): ``VarHandleOp`` handles, initialized by
  ``AssignVariableOp(handle, value)`` and read via ``ReadVariableOp``.

Initializers may depend on *other* variables (``b = Variable(f(a))``);
freezing iterates until a fixpoint, evaluating whichever initializers
have become computable. Initializer/bookkeeping machinery (assigns,
``VarIsInitializedOp``, the ``init`` NoOp from
``global_variables_initializer``) is pruned, and control edges into
pruned nodes are dropped from surviving nodes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..proto.graphdef import AttrValue, TensorProto
from ..schema import ScalarType
from .ir import Graph, GraphNode, parse_edge

__all__ = ["freeze_variables", "has_variables"]

# Ops that *are* a variable's stored value.
_REF_VARIABLE_OPS = ("Variable", "VariableV2")
# Ops that only exist to initialize/inspect variables; never part of the
# frozen compute graph.
_BOOKKEEPING_OPS = (
    "Assign",
    "AssignAdd",
    "AssignSub",
    "AssignVariableOp",
    "AssignAddVariableOp",
    "AssignSubVariableOp",
    "VarIsInitializedOp",
    "IsVariableInitialized",
    "VarHandleOp",
)


def has_variables(graph: Graph) -> bool:
    return any(
        n.op in _REF_VARIABLE_OPS or n.op == "VarHandleOp" for n in graph
    )


def _const_node(name: str, arr: np.ndarray) -> GraphNode:
    st = ScalarType.from_np_dtype(arr.dtype)
    return GraphNode(
        name,
        "Const",
        [],
        {
            "dtype": AttrValue.of_type(st),
            "value": AttrValue.of_tensor(TensorProto.from_numpy(arr)),
        },
    )


def _find_initializers(graph: Graph) -> Dict[str, str]:
    """var/handle node name -> initial-value input edge.

    A graph may contain several assigns to the same variable (the
    startup initializer plus compute-time ``tf.assign`` updates). TF
    names the initializer assign ``<var>/Assign`` — prefer that node; for
    anything else, first in definition order wins. The value edge is the
    SECOND data input (control edges may precede data inputs in a legal
    GraphDef, so raw ``inputs[1]`` is not usable)."""
    inits: Dict[str, str] = {}
    preferred: Dict[str, bool] = {}
    for n in graph:
        if n.op in ("Assign", "AssignVariableOp"):
            data = n.data_inputs()
            if len(data) < 2:
                continue
            target, _ = data[0]
            name, idx = data[1]
            edge = f"{name}:{idx}" if idx else name
            is_init = n.name == f"{target}/Assign"
            if target not in inits or (is_init and not preferred[target]):
                inits[target] = edge
                preferred[target] = is_init
    return inits


def _reaches_unfrozen(graph: Graph, edge: str, unfrozen: set) -> bool:
    """Cheap reachability: does the subgraph under ``edge`` read a
    variable that has not been frozen yet? (Avoids attempting — and
    failing — a lowering per pending variable per round.)"""
    stack = [parse_edge(edge)[0]]
    seen: set = set()
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        node = graph[name]
        if node.op in _REF_VARIABLE_OPS or node.op == "VarHandleOp":
            if name in unfrozen:
                return True
        for dep, _, ctrl in map(parse_edge, node.inputs):
            if not ctrl:
                stack.append(dep)
    return False


def freeze_variables(graph: Graph) -> Graph:
    """Return an equivalent stateless graph with every variable replaced
    by a ``Const`` holding its initializer's value. No-op (same object)
    for graphs without variables."""
    if not has_variables(graph):
        return graph

    inits = _find_initializers(graph)
    ref_vars = [n.name for n in graph if n.op in _REF_VARIABLE_OPS]
    handles = [n.name for n in graph if n.op == "VarHandleOp"]
    missing = [v for v in ref_vars + handles if v not in inits]
    if missing:
        raise ValueError(
            f"cannot freeze graph: variables {missing!r} have no "
            "Assign/AssignVariableOp initializer (the reference requires "
            "initializable variables too: it session-runs the initializer "
            "before convert_variables_to_constants, core.py:42-56)"
        )

    # Working copy we rewrite round by round.
    work = Graph([GraphNode(n.name, n.op, list(n.inputs), dict(n.attrs))
                  for n in graph])
    from ..ops.lowering import build_callable

    frozen: Dict[str, np.ndarray] = {}
    pending = set(ref_vars) | set(handles)
    while pending:
        # One batched evaluation per fixpoint round: every initializer
        # whose subgraph no longer reads an unfrozen variable is fetched
        # through a single lowering (rounds = dependency depth, not #vars).
        ready = [
            v for v in sorted(pending)
            if not _reaches_unfrozen(work, inits[v], pending)
        ]
        if not ready:
            raise ValueError(
                "cannot freeze graph: circular or non-constant variable "
                f"initializers for {sorted(pending)!r}"
            )
        values = build_callable(work, [inits[v] for v in ready], [])()
        for var, value in zip(ready, values):
            value = np.asarray(value)
            frozen[var] = value
            # Splice the value in: ref variables become the Const
            # themselves (their readers use the node directly); resource
            # handles stay put while every ReadVariableOp on them becomes
            # the Const.
            for i, n in enumerate(work.nodes):
                if n.name == var and n.op in _REF_VARIABLE_OPS:
                    work.nodes[i] = _const_node(var, value)
                    work._by_name[var] = work.nodes[i]
                elif (
                    n.op == "ReadVariableOp"
                    and n.data_inputs()
                    and n.data_inputs()[0][0] == var
                ):
                    work.nodes[i] = _const_node(n.name, value)
                    work._by_name[n.name] = work.nodes[i]
            # direct node splices bypass Graph.add's cache invalidation
            work._fingerprint = None
        pending -= set(frozen)

    # Prune bookkeeping nodes and anything data-dependent on them.
    # GraphDef node order is NOT guaranteed topological, so propagate the
    # drop set to a fixpoint rather than in one forward pass.
    dropped: set = {n.name for n in work if n.op in _BOOKKEEPING_OPS}
    changed = True
    while changed:
        changed = False
        for n in work:
            if n.name in dropped:
                continue
            if any(
                dep in dropped
                for dep, _, ctrl in map(parse_edge, n.inputs)
                if not ctrl
            ):
                dropped.add(n.name)
                changed = True
    # NoOp init barriers whose only purpose was ordering the assigns.
    for n in work:
        if n.op == "NoOp" and n.inputs and all(
            parse_edge(e)[0] in dropped for e in n.inputs
        ):
            dropped.add(n.name)

    out = Graph()
    for n in work:
        if n.name in dropped:
            continue
        kept_inputs: List[str] = []
        for e in n.inputs:
            dep, _, ctrl = parse_edge(e)
            if ctrl and dep in dropped:
                continue  # ordering edge into pruned init machinery
            kept_inputs.append(e)
        out.add(GraphNode(n.name, n.op, kept_inputs, dict(n.attrs)))
    # control-flow side tables survive freezing
    out.library = graph.library
    out._library_proto = graph._library_proto
    out.subgraphs = dict(graph.subgraphs)
    return out
