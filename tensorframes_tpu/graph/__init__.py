"""Graph layer: IR, GraphDef import/export, analysis, builder DSL."""

from .analysis import GraphSummary, NodeSummary, ShapeHints, analyze_graph
from .freeze import freeze_variables, has_variables
from .ir import Graph, GraphNode, parse_edge

__all__ = [
    "Graph",
    "GraphNode",
    "parse_edge",
    "freeze_variables",
    "has_variables",
    "GraphSummary",
    "NodeSummary",
    "ShapeHints",
    "analyze_graph",
]
