"""Graph layer: IR, GraphDef import/export, analysis, builder DSL."""

from .analysis import GraphSummary, NodeSummary, ShapeHints, analyze_graph
from .ir import Graph, GraphNode, parse_edge

__all__ = [
    "Graph",
    "GraphNode",
    "parse_edge",
    "GraphSummary",
    "NodeSummary",
    "ShapeHints",
    "analyze_graph",
]
