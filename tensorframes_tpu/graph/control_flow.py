"""TF control flow -> functional JAX control flow.

The reference executed ANY GraphDef because libtensorflow interpreted
dataflow control flow at runtime (`TensorFlowOps.scala:76-95`,
`Build.scala:56-57`). XLA compiles static programs, so imported control
flow must be FUNCTIONALIZED before lowering:

- v2 functional ops (`If`/`StatelessIf`, `While`/`StatelessWhile`)
  map directly: their branch/loop FunctionDefs become `Subgraph`s and
  the node becomes a `_Cond`/`_While` pseudo-node, lowered to
  `lax.cond` / `lax.while_loop` by `ops.control`.
- v1 dataflow control flow is structurally recovered: while frames via
  their `Enter`/`Merge`/`Switch`/`NextIteration`/`Exit` rings (the
  shape TF 1.x sessions emitted — the graphs the reference ingested),
  cond diamonds via branch labeling from `Switch` ports to the joining
  `Merge`s.
- `PartitionedCall`/`StatefulPartitionedCall` (and direct
  function-name-as-op calls) are inlined at their call sites from the
  GraphDef's `FunctionDefLibrary`.

Documented bounds (inherent to compiling, not incidental):

- loop carries must keep static shape/dtype across iterations
  (`lax.while_loop`'s contract; TF itself requires an invariant loop
  signature);
- both cond branches must produce matching output shapes (`lax.cond`
  traces both branches);
- `Merge` value_index outputs (``:1``) and unstructured Switch/Merge
  patterns raise `GraphLoweringError` with the offending node named;
- FunctionDef edge syntax ``node:out_arg:index`` resolves named out_args
  to flat output offsets via the op's output-arg signature
  (`_OP_OUTPUT_ARGS`: TopK, Unique*, FusedBatchNorm*, ...); ops without
  a table entry are single-output-arg, where positional resolution is
  exact. A tabled op with an unknown out_arg raises `GraphLoweringError`
  instead of silently aliasing output 0;
- loop/cond interiors consumed from OUTSIDE the extracted construct
  (anything but an `Exit`/`Merge` output) raise `GraphLoweringError`
  naming the leaking node, instead of a bare `KeyError` later.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..proto.graphdef import AttrValue, FunctionDef
from .ir import Graph, GraphNode, Subgraph, parse_edge

__all__ = ["has_control_flow", "functionalize"]


_V1_OPS = {
    "Switch", "RefSwitch", "Merge", "RefMerge", "Enter", "RefEnter",
    "Exit", "RefExit", "NextIteration", "RefNextIteration", "LoopCond",
}
_V2_OPS = {"If", "StatelessIf", "While", "StatelessWhile"}
_CALL_OPS = {"PartitionedCall", "StatefulPartitionedCall"}


class GraphLoweringError(ValueError):
    # a lowering failure is a property of the graph, not of the device:
    # re-running the identical dispatch fails identically
    tfs_fault_class = "deterministic"


def has_control_flow(g: Graph) -> bool:
    return any(
        n.op in _V1_OPS or n.op in _V2_OPS or n.op in _CALL_OPS
        or n.op in g.library
        for n in g.nodes
    )


def functionalize(g: Graph, fetches: List[str]) -> Tuple[Graph, List[str]]:
    """Return an equivalent (graph, fetches) with all control flow in
    `_Cond`/`_While` pseudo-node form and all function calls inlined.
    No-op (same objects) when the graph has no control flow."""
    if not has_control_flow(g):
        return g, fetches
    g, fetches = _inline_calls(g, fetches)
    g = _convert_functional_ops(g)
    g, fetches = _functionalize_v1(g, fetches)
    g = _prune(g, fetches)
    return g, fetches


# ---------------------------------------------------------------------------
# shared plumbing
# ---------------------------------------------------------------------------


def _apply_repl(
    g: Graph, fetches: List[str], repl: Dict[Tuple[str, int], str]
) -> Tuple[Graph, List[str]]:
    """Rewrite every node input + fetch through ``repl`` (chains
    resolved). Control edges retarget to the replacement's base node."""

    def resolve(key: Tuple[str, int]) -> Optional[str]:
        tgt = repl.get(key)
        for _ in range(64):
            if tgt is None:
                return None
            name, idx, _ = parse_edge(tgt)
            nxt = repl.get((name, idx))
            if nxt is None:
                return tgt
            tgt = nxt
        raise GraphLoweringError("edge replacement chain did not converge")

    def rw(e: str) -> str:
        name, idx, ctrl = parse_edge(e)
        tgt = resolve((name, idx))
        if tgt is None:
            return e
        if ctrl:
            return "^" + parse_edge(tgt)[0]
        return tgt

    out = Graph()
    out.library = g.library
    out._library_proto = g._library_proto
    out.subgraphs = dict(g.subgraphs)
    for n in g.nodes:
        out.add(GraphNode(n.name, n.op, [rw(e) for e in n.inputs], n.attrs))
    return out, [rw(f) for f in fetches]


def _sub_key(kind: str, sub: Subgraph) -> str:
    """Content-hashed key: the owning graph's byte fingerprint (which
    includes this key string in the pseudo-node attrs) then
    distinguishes different bodies."""
    h = hashlib.sha256()
    h.update(sub.graph.to_bytes())
    h.update("|".join(sub.feeds).encode())
    h.update("|".join(sub.fetches).encode())
    return f"{kind}_{h.hexdigest()[:12]}"


def _attach_sub(g: Graph, kind: str, sub: Subgraph) -> str:
    sub.graph.library = g.library
    key = _sub_key(kind, sub)
    g.subgraphs[key] = sub
    return key


def _placeholder(name: str, dtype=None) -> GraphNode:
    attrs = {}
    # only attach dtypes this schema models: a DT_VARIANT Enter (a
    # TensorList carried through a Keras RNN loop) parses as raw bytes,
    # which must not be wrapped in a type attr (the subgraph would no
    # longer serialize for its content-hash key)
    if dtype is not None and hasattr(dtype, "tf_datatype"):
        attrs["dtype"] = AttrValue.of_type(dtype)
    return GraphNode(name, "Placeholder", [], attrs)


def _unique_name(g: Graph, base: str) -> str:
    if base not in g:
        return base
    i = 1
    while f"{base}_{i}" in g:
        i += 1
    return f"{base}_{i}"


def _prune(g: Graph, fetches: Sequence[str]) -> Graph:
    """Drop nodes unreachable from the fetches (the leftover interiors
    of extracted loops/conds), preserving definition order. Placeholders
    are kept when CONSUMED by any kept node (feed_dict may rename them)
    — but not when fully dangling: `convert_variables_to_constants`
    leaves zero-consumer `unused_control_flow_input*` placeholders
    behind in frozen RNN graphs, and shape analysis must not demand
    shapes for those."""
    keep: Set[str] = set()

    def visit(name: str):
        if name in keep:
            return
        keep.add(name)
        for e in g[name].inputs:
            visit(parse_edge(e)[0])

    for f in fetches:
        visit(parse_edge(f)[0])
    consumed = {
        parse_edge(e)[0]
        for n in g.nodes
        if n.name in keep
        for e in n.inputs
    }
    for n in g.nodes:
        if n.op in ("Placeholder", "PlaceholderV2") and n.name in consumed:
            visit(n.name)
    out = Graph()
    out.library = g.library
    out._library_proto = g._library_proto
    for n in g.nodes:
        if n.name in keep:
            out.add(n)
    # only the subgraphs still referenced
    for n in out.nodes:
        for akey in ("cond_then", "cond_else", "while_cond", "while_body"):
            key = n.attr(akey)
            if key is not None:
                key = key.decode() if isinstance(key, bytes) else key
                out.subgraphs[key] = g.subgraphs[key]
    return out


def _copy_nested_subgraphs(src: Graph, dst: Graph) -> None:
    """When cloning pseudo-nodes into a subgraph, bring the subgraph
    entries they reference along."""
    for n in dst.nodes:
        for akey in ("cond_then", "cond_else", "while_cond", "while_body"):
            key = n.attr(akey)
            if key is not None:
                key = key.decode() if isinstance(key, bytes) else key
                dst.subgraphs[key] = src.subgraphs[key]


def _clone_closure(
    g: Graph,
    src_edges: Sequence[str],
    edge_map: Dict[Tuple[str, int], str],
    forbidden: Optional[Dict[str, str]] = None,
    allowed: Optional[Set[str]] = None,
) -> Tuple[List[GraphNode], List[str], Set[str]]:
    """Clone the backward closure of ``src_edges`` up to the boundary
    ``edge_map`` (edge -> placeholder name). Control edges are dropped
    (this IR lowers them as ordering-only no-ops anyway). Returns
    (cloned nodes in original graph order, mapped fetch edges, visited
    source names).

    ``forbidden`` maps ring-node names to a reason; reaching one means
    the structure is not the canonical TF shape — raise, never
    mis-compile. ``allowed`` (if given) restricts which nodes may be
    entered (cond branch labeling)."""
    forbidden = forbidden or {}
    visited: Set[str] = set()
    order: Dict[str, int] = {n.name: i for i, n in enumerate(g.nodes)}

    def visit(name: str):
        if name in visited:
            return
        if name in forbidden:
            raise GraphLoweringError(
                f"unsupported control-flow structure: reached {name!r} "
                f"({forbidden[name]}) outside its canonical position"
            )
        if allowed is not None and name not in allowed:
            raise GraphLoweringError(
                f"unsupported control-flow structure: node {name!r} is "
                "referenced from a branch it does not belong to"
            )
        visited.add(name)
        for e in g[name].inputs:
            dep, idx, ctrl = parse_edge(e)
            if ctrl:
                continue
            if (dep, idx) in edge_map:
                continue
            visit(dep)

    fetch_edges: List[str] = []
    for e in src_edges:
        dep, idx, ctrl = parse_edge(e)
        if (dep, idx) in edge_map:
            fetch_edges.append(edge_map[(dep, idx)])
        else:
            visit(dep)
            fetch_edges.append(e)

    def rw_inputs(node: GraphNode) -> List[str]:
        out = []
        for e in node.inputs:
            dep, idx, ctrl = parse_edge(e)
            if ctrl:
                continue
            mapped = edge_map.get((dep, idx))
            out.append(mapped if mapped is not None else e)
        return out

    cloned = [
        GraphNode(n.name, n.op, rw_inputs(n), n.attrs)
        for n in g.nodes
        if n.name in visited
    ]
    cloned.sort(key=lambda n: order[n.name])
    return cloned, fetch_edges, visited


# ---------------------------------------------------------------------------
# function library: call inlining + FunctionDef -> Subgraph
# ---------------------------------------------------------------------------


# Flat output-arg layout of the multi-output ops this framework lowers.
# FunctionDef edges use ``node:out_arg:idx`` syntax where ``out_arg``
# NAMES an output arg of the node's op; the flat output offset is the
# arg's position in the op's output signature (every arg below is a
# single tensor, so position + idx is exact). Single-output ops need no
# entry: their one out_arg sits at offset 0 and ``idx`` is already flat.
_FBN_OUTS = (
    "y", "batch_mean", "batch_variance", "reserve_space_1", "reserve_space_2",
)
_OP_OUTPUT_ARGS: Dict[str, Tuple[str, ...]] = {
    "TopK": ("values", "indices"),
    "TopKV2": ("values", "indices"),
    "Unique": ("y", "idx"),
    "UniqueV2": ("y", "idx"),
    "UniqueWithCounts": ("y", "idx", "count"),
    "FusedBatchNorm": _FBN_OUTS,
    "FusedBatchNormV2": _FBN_OUTS,
    "FusedBatchNormV3": _FBN_OUTS + ("reserve_space_3",),
    "MaxPoolWithArgmax": ("output", "argmax"),
    "Switch": ("output_false", "output_true"),
    "RefSwitch": ("output_false", "output_true"),
    "Merge": ("output", "value_index"),
    "RefMerge": ("output", "value_index"),
}


def _flat_output_index(op: Optional[str], out_arg: str, idx: int, edge: str) -> int:
    """Resolve a named ``out_arg`` to its flat output offset via the
    op's output-arg signature. Ops without a table entry are treated as
    single-output-arg (offset == idx) — correct for every other op this
    framework lowers; a tabled op with an unrecognized out_arg raises
    rather than silently resolving to the wrong output."""
    sig = _OP_OUTPUT_ARGS.get(op or "")
    if sig is None:
        return idx
    if out_arg not in sig:
        raise GraphLoweringError(
            f"function body edge {edge!r}: op {op!r} has no output arg "
            f"{out_arg!r} (outputs: {list(sig)})"
        )
    if idx != 0:
        # every tabled output arg is a single tensor; a nonzero
        # within-arg index would need list-arg sizing we cannot do here
        raise GraphLoweringError(
            f"function body edge {edge!r}: output arg {out_arg!r} of "
            f"{op!r} is a single tensor but the edge indexes element {idx}"
        )
    return sig.index(out_arg)


def _fdef_edge(
    e: str,
    argmap: Dict[str, str],
    bodynames: Set[str],
    prefix: str = "",
    body_ops: Optional[Dict[str, str]] = None,
) -> str:
    """Translate FunctionDef edge syntax (``arg``, ``node:out_arg:idx``)
    into plain graph edge syntax: args splice to ``argmap`` targets,
    body nodes get ``prefix`` (the call-site name when inlining, empty
    when building a standalone Subgraph). Classification happens BEFORE
    prefixing, so a body node shadowing a caller node name cannot
    double-prefix. Named out_args resolve to flat output offsets via the
    op's output signature (``body_ops``: body node name -> op), so e.g.
    ``bn:batch_mean:0`` becomes output 1 of a FusedBatchNorm instead of
    silently aliasing output 0."""
    ctrl = e.startswith("^")
    if ctrl:
        e = e[1:]
    parts = e.split(":")
    base = parts[0]
    if base in argmap:
        tgt = argmap[base]
        return ("^" + parse_edge(tgt)[0]) if ctrl else tgt
    if base in bodynames:
        if ctrl:
            return f"^{prefix}{base}"
        op = (body_ops or {}).get(base)
        if len(parts) == 3:
            if not parts[2].isdigit():
                raise GraphLoweringError(
                    f"malformed function body edge {e!r}"
                )
            k = _flat_output_index(op, parts[1], int(parts[2]), e)
            return f"{prefix}{base}:{k}"
        if len(parts) == 2 and parts[1].isdigit():
            return f"{prefix}{base}:{parts[1]}"
        if len(parts) == 2:
            k = _flat_output_index(op, parts[1], 0, e)
            return f"{prefix}{base}:{k}"
        return f"{prefix}{base}"
    raise GraphLoweringError(
        f"function body edge {e!r} references neither an argument "
        f"({sorted(argmap)}) nor a body node"
    )


def _call_site_argmap(
    fdef: FunctionDef, call: GraphNode
) -> Dict[str, str]:
    data_in = [e for e in call.inputs if not e.startswith("^")]
    if len(data_in) != len(fdef.input_args):
        raise GraphLoweringError(
            f"call {call.name!r} feeds {len(data_in)} args but function "
            f"{fdef.name!r} declares {len(fdef.input_args)}"
        )
    return {a.name: data_in[i] for i, a in enumerate(fdef.input_args)}


def _inline_calls(g: Graph, fetches: List[str]) -> Tuple[Graph, List[str]]:
    lib = g.library
    if not lib:
        return g, fetches
    for _ in range(64):
        calls = [
            n for n in g.nodes if n.op in _CALL_OPS or n.op in lib
        ]
        if not calls:
            return g, fetches
        callset = {n.name for n in calls}
        out = Graph()
        out.library = g.library
        out._library_proto = g._library_proto
        out.subgraphs = dict(g.subgraphs)
        repl: Dict[Tuple[str, int], str] = {}
        for node in g.nodes:
            if node.name not in callset:
                out.add(node)
                continue
            if node.op in _CALL_OPS:
                fav = node.attrs.get("f")
                if fav is None or fav.kind != "func":
                    raise GraphLoweringError(
                        f"call node {node.name!r} has no function attr"
                    )
                fname = fav.value.name
                if fname not in lib:
                    raise GraphLoweringError(
                        f"call node {node.name!r} references unknown "
                        f"function {fname!r}"
                    )
                fdef = lib[fname]
            else:
                fdef = lib[node.op]
            argmap = _call_site_argmap(fdef, node)
            prefix = node.name + "/"
            bodynames = {bn.name for bn in fdef.nodes}
            body_ops = {bn.name: bn.op for bn in fdef.nodes}

            def tr(
                e: str,
                argmap=argmap,
                bodynames=bodynames,
                prefix=prefix,
                body_ops=body_ops,
            ):
                return _fdef_edge(e, argmap, bodynames, prefix, body_ops)

            for bn in fdef.nodes:
                out.add(
                    GraphNode(
                        prefix + bn.name, bn.op,
                        [tr(e) for e in bn.inputs], dict(bn.attrs),
                    )
                )
            for k, oarg in enumerate(fdef.output_args):
                ret_edge = fdef.ret.get(oarg.name)
                if ret_edge is None:
                    raise GraphLoweringError(
                        f"function {fdef.name!r} has no ret entry for "
                        f"output {oarg.name!r}"
                    )
                repl[(node.name, k)] = tr(ret_edge)
        g, fetches = _apply_repl(out, fetches, repl)
    raise GraphLoweringError(
        "function inlining did not converge after 64 rounds "
        "(recursive function library?)"
    )


def _fdef_to_subgraph(fdef: FunctionDef) -> Subgraph:
    sub = Graph()
    argmap = {a.name: a.name for a in fdef.input_args}
    bodynames = {bn.name for bn in fdef.nodes}
    body_ops = {bn.name: bn.op for bn in fdef.nodes}
    for a in fdef.input_args:
        sub.add(_placeholder(a.name, a.type))
    for bn in fdef.nodes:
        inputs = []
        for e in bn.inputs:
            te = _fdef_edge(e, argmap, bodynames, body_ops=body_ops)
            if not te.startswith("^"):
                inputs.append(te)
        sub.add(GraphNode(bn.name, bn.op, inputs, dict(bn.attrs)))
    fetches = []
    for oarg in fdef.output_args:
        ret_edge = fdef.ret.get(oarg.name)
        if ret_edge is None:
            raise GraphLoweringError(
                f"function {fdef.name!r} has no ret entry for output "
                f"{oarg.name!r}"
            )
        fetches.append(_fdef_edge(ret_edge, argmap, bodynames, body_ops=body_ops))
    return Subgraph(sub, [a.name for a in fdef.input_args], fetches)


def _convert_functional_ops(g: Graph) -> Graph:
    """`If`/`While` (v2 functional control flow) -> `_Cond`/`_While`."""
    if not any(n.op in _V2_OPS for n in g.nodes):
        return g
    out = Graph()
    out.library = g.library
    out._library_proto = g._library_proto
    out.subgraphs = dict(g.subgraphs)
    for node in g.nodes:
        if node.op in ("If", "StatelessIf"):
            tname = node.attrs["then_branch"].value.name
            ename = node.attrs["else_branch"].value.name
            tsub = _subgraph_from_lib(g, tname)
            esub = _subgraph_from_lib(g, ename)
            n_out = len(tsub.fetches)
            out.add(
                GraphNode(
                    node.name, "_Cond", list(node.inputs),
                    {
                        "cond_then": AttrValue.of_string(
                            _attach_sub(out, "cond_then", tsub)
                        ),
                        "cond_else": AttrValue.of_string(
                            _attach_sub(out, "cond_else", esub)
                        ),
                        "n_out": AttrValue.of_int(n_out),
                    },
                )
            )
        elif node.op in ("While", "StatelessWhile"):
            csub = _subgraph_from_lib(g, node.attrs["cond"].value.name)
            bsub = _subgraph_from_lib(g, node.attrs["body"].value.name)
            n_vars = len([e for e in node.inputs if not e.startswith("^")])
            out.add(
                GraphNode(
                    node.name, "_While", list(node.inputs),
                    {
                        "while_cond": AttrValue.of_string(
                            _attach_sub(out, "while_cond", csub)
                        ),
                        "while_body": AttrValue.of_string(
                            _attach_sub(out, "while_body", bsub)
                        ),
                        "n_vars": AttrValue.of_int(n_vars),
                    },
                )
            )
        else:
            out.add(node)
    return out


def _subgraph_from_lib(g: Graph, fname: str) -> Subgraph:
    if fname not in g.library:
        raise GraphLoweringError(f"unknown library function {fname!r}")
    sub = _fdef_to_subgraph(g.library[fname])
    sub.graph.library = g.library
    # the body may itself contain calls / functional ops / v1 rings
    sg, sf = functionalize(sub.graph, list(sub.fetches))
    return Subgraph(sg, sub.feeds, sf)


# ---------------------------------------------------------------------------
# v1 dataflow control flow
# ---------------------------------------------------------------------------


def _functionalize_v1(
    g: Graph, fetches: List[str]
) -> Tuple[Graph, List[str]]:
    for _ in range(64):
        frames = _frames(g)
        if frames:
            g, fetches = _extract_while(g, fetches, frames[0])
            # drop control-only satellites of the extracted construct
            # (e.g. an inner cond's pred Switch/switch_t identities that
            # only carried ^control edges) before the next pass trips
            # over their dangling inputs
            g = _prune(g, fetches)
            continue
        group = _next_cond_group(g)
        if group is not None:
            g, fetches = _extract_cond(g, fetches, *group)
            g = _prune(g, fetches)
            continue
        leftovers = [n for n in g.nodes if n.op in _V1_OPS]
        if leftovers:
            raise GraphLoweringError(
                "unstructured v1 control flow: leftover "
                f"{[(n.op, n.name) for n in leftovers[:4]]}"
            )
        return g, fetches
    raise GraphLoweringError("v1 functionalization did not converge")


def _frames(g: Graph) -> List[str]:
    seen: List[str] = []
    for n in g.nodes:
        if n.op in ("Enter", "RefEnter"):
            f = n.attr("frame_name")
            f = f.decode() if isinstance(f, bytes) else f
            if f not in seen:
                seen.append(f)
    return seen


def _extract_while(
    g: Graph, fetches: List[str], frame: str
) -> Tuple[Graph, List[str]]:
    """Recover one while frame into a `_While` pseudo-node.

    The canonical v1 ring per loop variable i (what `tf.while_loop`
    emitted): Merge_i(Enter_i, NextIteration_i) -> [cond] -> LoopCond ->
    Switch_i(Merge_i, LoopCond); Switch_i:1 -> [body] ->
    NextIteration_i; Switch_i:0 -> Exit_i. Loop invariants enter via
    Enter(is_constant=True) and become extra carries returned unchanged.
    """

    def fattr(n: GraphNode) -> Optional[str]:
        f = n.attr("frame_name")
        return f.decode() if isinstance(f, bytes) else f

    enters = [
        n for n in g.nodes if n.op in ("Enter", "RefEnter")
        and fattr(n) == frame
    ]
    loop_enters = [n for n in enters if not n.attr("is_constant")]
    const_enters = [n for n in enters if n.attr("is_constant")]
    enter_names = {n.name for n in loop_enters}

    merges = [
        n for n in g.nodes
        if n.op in ("Merge", "RefMerge")
        and any(parse_edge(e)[0] in enter_names for e in n.inputs)
    ]
    if not merges:
        raise GraphLoweringError(
            f"while frame {frame!r} has Enter nodes but no Merge ring"
        )

    class Var:
        __slots__ = ("enter", "merge", "next", "switch", "exit")

    nvars: List[Var] = []
    merge_names = {m.name for m in merges}
    switches = {
        parse_edge(n.inputs[0])[0]: n
        for n in g.nodes
        if n.op in ("Switch", "RefSwitch")
        and parse_edge(n.inputs[0])[0] in merge_names
    }
    exits = {}
    switch_names = {s.name for s in switches.values()}
    for n in g.nodes:
        if n.op in ("Exit", "RefExit"):
            b = parse_edge(n.inputs[0])[0]
            if b in switch_names:
                exits[b] = n

    lc_name = None
    for m in merges:
        v = Var()
        v.merge = m
        ins = [parse_edge(e)[0] for e in m.inputs]
        v.enter = next(g[i] for i in ins if i in enter_names)
        v.next = next(
            (g[i] for i in ins
             if g[i].op in ("NextIteration", "RefNextIteration")),
            None,
        )
        if v.next is None:
            raise GraphLoweringError(
                f"merge {m.name!r} in while frame {frame!r} has no "
                "NextIteration back edge"
            )
        v.switch = switches.get(m.name)
        v.exit = exits.get(v.switch.name) if v.switch is not None else None
        if v.switch is not None:
            cand = parse_edge(v.switch.inputs[1])[0]
            if g[cand].op != "LoopCond":
                raise GraphLoweringError(
                    f"switch {v.switch.name!r} predicate is "
                    f"{g[cand].op!r}, expected LoopCond"
                )
            if lc_name is None:
                lc_name = cand
            elif lc_name != cand:
                raise GraphLoweringError(
                    f"while frame {frame!r} has two LoopConds "
                    f"({lc_name!r}, {cand!r}) — nested frames sharing a "
                    "name are unsupported"
                )
        nvars.append(v)
    if lc_name is None:
        raise GraphLoweringError(
            f"while frame {frame!r} has no Switch/LoopCond"
        )
    lc = g[lc_name]

    edge_map: Dict[Tuple[str, int], str] = {}
    body_map: Dict[Tuple[str, int], str] = {}
    feeds: List[str] = []
    for i, v in enumerate(nvars):
        ph = f"__var{i}"
        feeds.append(ph)
        edge_map[(v.merge.name, 0)] = ph
        if v.switch is not None:
            body_map[(v.switch.name, 1)] = ph
    caps: List[str] = []
    for j, ce in enumerate(const_enters):
        ph = f"__cap{j}"
        feeds.append(ph)
        caps.append(ph)
        edge_map[(ce.name, 0)] = ph
        body_map[(ce.name, 0)] = ph

    ring_reason = {
        n.name: f"{n.op} of while frame {frame!r}"
        for n in (
            enters + merges + [lc]
            + [v.switch for v in nvars if v.switch is not None]
            + [v.next for v in nvars]
            + [v.exit for v in nvars if v.exit is not None]
        )
    }

    # cond: closure from the LoopCond input, stopping at merges/caps
    ring_for_cond = {
        k: r for k, r in ring_reason.items()
        if k not in {m.name for m in merges}
        and k not in {ce.name for ce in const_enters}
    }
    cond_nodes, cond_fetch, cond_visited = _clone_closure(
        g, [lc.inputs[0]], edge_map, forbidden=ring_for_cond
    )
    # body: closure from every NextIteration input, stopping at
    # switch:1 / caps; merges may be reached via nothing (forbidden)
    ring_for_body = {
        k: r for k, r in ring_reason.items()
        if k not in {v.switch.name for v in nvars if v.switch is not None}
        and k not in {ce.name for ce in const_enters}
    }
    # invariant captures return unchanged: fetch the const-Enter edges,
    # which the boundary map rewrites to the __cap placeholders
    body_srcs = [v.next.inputs[0] for v in nvars] + [
        ce.name for ce in const_enters
    ]
    body_nodes, body_fetch, body_visited = _clone_closure(
        g, body_srcs, body_map, forbidden=ring_for_body
    )

    def build_sub(nodes: List[GraphNode], fetch: List[str]) -> Subgraph:
        sub = Graph()
        for i, v in enumerate(nvars):
            sub.add(_placeholder(f"__var{i}", v.enter.attr("T")))
        for j, ce in enumerate(const_enters):
            sub.add(_placeholder(f"__cap{j}", ce.attr("T")))
        for n in nodes:
            sub.add(n)
        _copy_nested_subgraphs(g, sub)
        sub.library = g.library
        # the body may contain NESTED control flow (tf.cond inside the
        # loop body, an inner while frame): functionalize recursively
        sg, sf = functionalize(sub, list(fetch))
        return Subgraph(sg, list(feeds), sf)

    cond_sub = build_sub(cond_nodes, cond_fetch[:1])
    body_sub = build_sub(body_nodes, body_fetch)

    out = Graph()
    out.library = g.library
    out._library_proto = g._library_proto
    out.subgraphs = dict(g.subgraphs)
    wname = _unique_name(g, frame.split("/")[0] + "/_functional_while")
    interior = (
        set(ring_reason) | cond_visited | body_visited
        | {ce.name for ce in const_enters}
    )
    for n in g.nodes:
        if n.name in interior:
            continue
        out.add(n)
    out.add(
        GraphNode(
            wname, "_While",
            [v.enter.inputs[0] for v in nvars]
            + [ce.inputs[0] for ce in const_enters],
            {
                "while_cond": AttrValue.of_string(
                    _attach_sub(out, "while_cond", cond_sub)
                ),
                "while_body": AttrValue.of_string(
                    _attach_sub(out, "while_body", body_sub)
                ),
                "n_vars": AttrValue.of_int(len(nvars)),
            },
        )
    )
    repl = {
        (v.exit.name, 0): f"{wname}:{i}"
        for i, v in enumerate(nvars)
        if v.exit is not None
    }
    _check_interior_leaks(
        out, fetches, repl, interior, f"while frame {frame!r}"
    )
    return _apply_repl(out, fetches, repl)


def _check_interior_leaks(
    out: Graph,
    fetches: Sequence[str],
    repl: Dict[Tuple[str, int], str],
    dropped: Set[str],
    what: str,
) -> None:
    """Before an extracted construct's interior nodes vanish, verify no
    surviving node (or fetch) REACHABLE from the fetches consumes an
    interior output that is not re-exported through ``repl`` (Exit /
    Merge outputs). Raising here names the leaking edge and its
    consumer; without the check the dangling reference surfaces later as
    a bare `KeyError` deep in toposort. Unreachable consumers are
    ignored — `_prune` removes them right after extraction, exactly as
    before."""

    def leak(consumer: str, edge: str) -> None:
        dep, idx, _ = parse_edge(edge)
        raise GraphLoweringError(
            f"{consumer} consumes {dep}:{idx}, an interior node of the "
            f"extracted {what}; only its functional outputs are visible "
            "outside — unstructured control flow"
        )

    seen: Set[str] = set()

    def visit(name: str):
        if name in seen or name not in out:
            return
        seen.add(name)
        for e in out[name].inputs:
            dep, idx, _ = parse_edge(e)
            if dep in dropped and (dep, idx) not in repl:
                leak(f"node {out[name].name!r}", e)
            if dep not in dropped:
                visit(dep)

    for f in fetches:
        dep, idx, _ = parse_edge(f)
        if dep in dropped and (dep, idx) not in repl:
            leak(f"fetch {f!r}", f)
        visit(dep)


def _resolve_pred(g: Graph, edge: str) -> Tuple[str, int]:
    name, idx, _ = parse_edge(edge)
    for _ in range(64):
        node = g[name]
        if node.op == "Identity" and len(node.data_inputs()) == 1:
            name, idx = node.data_inputs()[0]
        else:
            return name, idx
    return name, idx


def _next_cond_group(g: Graph):
    """Pick one cond diamond: all Switches sharing a resolved predicate.
    Returns (pred_edge, switch list) or None."""
    groups: Dict[Tuple[str, int], List[GraphNode]] = {}
    first_edge: Dict[Tuple[str, int], str] = {}
    for n in g.nodes:
        if n.op in ("Switch", "RefSwitch"):
            origin = _resolve_pred(g, n.inputs[1])
            groups.setdefault(origin, []).append(n)
            first_edge.setdefault(origin, n.inputs[1])
    if not groups:
        return None
    origin = next(iter(groups))
    return first_edge[origin], groups[origin]


def _extract_cond(
    g: Graph, fetches: List[str], pred_edge: str, switches: List[GraphNode]
) -> Tuple[Graph, List[str]]:
    """Recover one cond diamond into a `_Cond` pseudo-node.

    Branch membership by label propagation from Switch ports (port 1 =
    true) through data AND control edges (v1 pins branch constants with
    a control edge to the switch identities) until the joining Merges.
    """
    switch_names = {s.name for s in switches}
    labels: Dict[str, str] = {}
    joins: List[GraphNode] = []
    join_set: Set[str] = set()

    changed = True
    while changed:
        changed = False
        for node in g.nodes:
            if node.name in switch_names or node.name in join_set:
                continue
            got: Set[str] = set()
            for e in node.inputs:
                dep, idx, _ = parse_edge(e)
                if dep in switch_names:
                    got.add("T" if idx == 1 else "F")
                elif dep in labels:
                    got.add(labels[dep])
            if len(got) == 2:
                if node.op in ("Merge", "RefMerge"):
                    joins.append(node)
                    join_set.add(node.name)
                    labels.pop(node.name, None)
                    changed = True
                    continue
                raise GraphLoweringError(
                    f"node {node.name!r} ({node.op}) consumes both cond "
                    "branches without a Merge — unstructured control flow"
                )
            if len(got) == 1 and node.name not in labels:
                labels[node.name] = got.pop()
                changed = True

    if not joins:
        raise GraphLoweringError(
            f"cond Switches {sorted(switch_names)[:3]} have no joining "
            "Merge — unstructured control flow"
        )

    # captures: external data edges consumed inside either branch.
    # Iterate the dict (insertion-ordered), NOT a set: cap order decides
    # the _Cond input order and the content-hashed subgraph keys, which
    # must be deterministic across processes (hash randomization).
    interior = set(labels)
    cap_edges: List[Tuple[str, int]] = []
    for name in labels:
        for e in g[name].inputs:
            dep, idx, ctrl = parse_edge(e)
            if ctrl or dep in interior or dep in switch_names:
                continue
            if (dep, idx) not in cap_edges:
                cap_edges.append((dep, idx))

    edge_map_t: Dict[Tuple[str, int], str] = {}
    edge_map_f: Dict[Tuple[str, int], str] = {}
    feeds: List[str] = []
    for k, s in enumerate(switches):
        ph = f"__sw{k}"
        feeds.append(ph)
        edge_map_t[(s.name, 1)] = ph
        edge_map_f[(s.name, 0)] = ph
        # a branch may read the "wrong" port only through its own
        # label; canonical graphs never do, and _clone_closure's
        # boundary check will surface it if one does
    for j, (dep, idx) in enumerate(cap_edges):
        ph = f"__cap{j}"
        feeds.append(ph)
        edge_map_t[(dep, idx)] = ph
        edge_map_f[(dep, idx)] = ph

    def branch(lab: str, emap) -> Tuple[Subgraph, Set[str]]:
        srcs = []
        for m in joins:
            side = None
            for e in m.inputs:
                dep, idx, _ = parse_edge(e)
                l = (
                    ("T" if idx == 1 else "F")
                    if dep in switch_names
                    else labels.get(dep)
                )
                if l == lab:
                    side = e
            if side is None:
                raise GraphLoweringError(
                    f"merge {m.name!r} has no {lab}-branch input"
                )
            srcs.append(side)
        allowed = {n for n, l in labels.items() if l == lab}
        nodes, fetch, visited = _clone_closure(
            g, srcs, emap, allowed=allowed | {parse_edge(s)[0] for s in srcs}
        )
        sub = Graph()
        for ph in feeds:
            sub.add(_placeholder(ph))
        for n in nodes:
            sub.add(n)
        _copy_nested_subgraphs(g, sub)
        sub.library = g.library
        # nested conds/loops inside the branch functionalize recursively
        sg, sf = functionalize(sub, list(fetch))
        return Subgraph(sg, list(feeds), sf), visited

    then_sub, _ = branch("T", edge_map_t)
    else_sub, _ = branch("F", edge_map_f)

    # Merge value_index (:1) consumers are unsupported
    join_names = {m.name for m in joins}
    for n in g.nodes:
        if n.name in interior or n.name in join_names:
            continue
        for e in n.inputs:
            dep, idx, _ = parse_edge(e)
            if dep in join_names and idx != 0:
                raise GraphLoweringError(
                    f"node {n.name!r} consumes Merge value_index "
                    f"({dep}:{idx}) — unsupported"
                )

    out = Graph()
    out.library = g.library
    out._library_proto = g._library_proto
    out.subgraphs = dict(g.subgraphs)
    cname = _unique_name(g, joins[0].name + "/_functional_cond")
    drop = interior | switch_names | join_names
    for n in g.nodes:
        if n.name in drop:
            continue
        out.add(n)
    out.add(
        GraphNode(
            cname, "_Cond",
            [pred_edge]
            + [s.inputs[0] for s in switches]
            + [dep if idx == 0 else f"{dep}:{idx}" for dep, idx in cap_edges],
            {
                "cond_then": AttrValue.of_string(
                    _attach_sub(out, "cond_then", then_sub)
                ),
                "cond_else": AttrValue.of_string(
                    _attach_sub(out, "cond_else", else_sub)
                ),
                "n_out": AttrValue.of_int(len(joins)),
            },
        )
    )
    repl = {(m.name, 0): f"{cname}:{j}" for j, m in enumerate(joins)}
    _check_interior_leaks(
        out, fetches, repl, drop,
        f"cond diamond at {joins[0].name!r}",
    )
    return _apply_repl(out, fetches, repl)
