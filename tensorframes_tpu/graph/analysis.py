"""Graph analysis: input/output classification + shape & dtype inference.

TPU-native counterpart of `TensorFlowOps.analyzeGraphTF`
(`TensorFlowOps.scala:101-141`): where the reference imported the graph into
a native TF runtime and read back each op's static shape, we lower the
graph with JAX and run `jax.eval_shape` — an abstract interpretation that
never touches a device — under two different *probe* substitutions for the
unknown dims. Dims that stay constant across probes are known; dims that
track the probe are unknown. This recovers TF's partial static shapes
without a hand-written symbolic shape-inference engine.

`ShapeHints` mirrors `ShapeDescription` (`ShapeDescription.scala:12-19`):
per-call output-shape hints (which override pruned/unknown inferred dims,
`TensorFlowOps.scala:123-133`), the requested fetches, and the
placeholder->column feed map.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..ops.lowering import build_callable
from ..schema import ScalarType, Shape
from .ir import Graph, GraphNode, parse_edge

__all__ = ["ShapeHints", "NodeSummary", "GraphSummary", "analyze_graph"]

# Probe sizes for unknown dims: distinct, small, unlikely to collide with
# real fixed dims in tandem (a dim must equal BOTH probes to be mistaken
# for unknown, which is impossible since they differ).
_PROBES = (3, 5)


@dataclass
class ShapeHints:
    """Per-call side-channel (`ShapeDescription.scala:12-19`)."""

    out_shapes: Dict[str, Shape] = field(default_factory=dict)
    requested_fetches: List[str] = field(default_factory=list)
    feed_map: Dict[str, str] = field(default_factory=dict)  # placeholder -> column


@dataclass
class NodeSummary:
    """`GraphNodeSummary` (`TensorFlowOps.scala:163-169`)."""

    name: str
    is_input: bool
    is_output: bool
    dtype: ScalarType
    shape: Shape  # may contain unknown dims


@dataclass
class GraphSummary:
    inputs: Dict[str, NodeSummary]
    outputs: Dict[str, NodeSummary]


def _placeholder_spec(
    node: GraphNode, overrides: Dict[str, Shape]
) -> (ScalarType, Shape):
    dtype = node.dtype_attr
    if dtype is None:
        raise ValueError(f"placeholder {node.name!r} has no dtype attr")
    shape = overrides.get(node.name, node.shape_attr)
    if shape is None:
        raise ValueError(
            f"placeholder {node.name!r} has no shape (attr or hint); "
            "the reference requires placeholder shapes too "
            "(core.py:72-92 records them for every op)"
        )
    return dtype, shape


def _concretize(shape: Shape, probe: int) -> tuple:
    return tuple(probe if d is None else d for d in shape.dims)


_analysis_cache: Dict[tuple, GraphSummary] = {}  # tfslint: disable=TFS004 pure memo keyed by (fingerprint, fetches, overrides, hints) — re-derivation is bit-identical, nothing observable leaks across tests


def analyze_graph(
    graph: Graph,
    fetches: Sequence[str],
    hints: Optional[ShapeHints] = None,
    placeholder_shapes: Optional[Dict[str, Shape]] = None,
) -> GraphSummary:
    """Classify inputs/outputs and infer dtypes + partial shapes.

    ``placeholder_shapes`` overrides placeholder shape attrs (used by the
    verbs to inject column block shapes before validation).

    Results are memoized on (graph fingerprint, fetches, overrides,
    hints): analysis is pure, and re-deriving it per verb call would
    dominate small-block dispatch (two abstract traces per call).
    """
    cache_key = (
        graph.fingerprint(),
        tuple(fetches),
        tuple(sorted(
            (k, v.dims) for k, v in (placeholder_shapes or {}).items()
        )),
        tuple(sorted(
            (k, v.dims) for k, v in (hints.out_shapes if hints else {}).items()
        )),
    )
    cached = _analysis_cache.get(cache_key)
    if cached is not None:
        return cached
    hints = hints or ShapeHints()
    overrides = dict(placeholder_shapes or {})
    phs = graph.placeholders()
    inputs: Dict[str, NodeSummary] = {}
    for ph in phs:
        dtype, shape = _placeholder_spec(ph, overrides)
        inputs[ph.name] = NodeSummary(ph.name, True, False, dtype, shape)

    fetch_list = list(fetches)
    feed_names = [ph.name for ph in phs]
    fn = build_callable(graph, fetch_list, feed_names)

    per_probe: List[List] = []
    for probe in _PROBES:
        structs = [
            jax.ShapeDtypeStruct(
                _concretize(inputs[name].shape, probe),
                inputs[name].dtype.np_dtype,
            )
            for name in feed_names
        ]
        outs = jax.eval_shape(fn, *structs)
        per_probe.append(list(outs))

    outputs: Dict[str, NodeSummary] = {}
    for i, f in enumerate(fetch_list):
        base = parse_edge(f)[0]
        a, b = per_probe[0][i], per_probe[1][i]
        merged = Shape(a.shape).merge(Shape(b.shape))
        if merged is None:
            # rank varied with the probe — fully dynamic; fall back to hint
            merged = hints.out_shapes.get(base)
            if merged is None:
                raise ValueError(
                    f"fetch {f!r}: output rank depends on the block size and "
                    "no shape hint was provided"
                )
        hint = hints.out_shapes.get(base)
        if hint is not None and hint.rank == merged.rank:
            # Hints override unknown inferred dims (TensorFlowOps.scala:123-133).
            merged = Shape(
                m if m is not None else h
                for m, h in zip(merged.dims, hint.dims)
            )
        dtype = ScalarType.from_np_dtype(np.dtype(a.dtype))
        outputs[base] = NodeSummary(base, False, True, dtype, merged)

    summary = GraphSummary(inputs=inputs, outputs=outputs)
    if len(_analysis_cache) > 1024:  # bound the cache
        _analysis_cache.clear()
    _analysis_cache[cache_key] = summary
    return summary
