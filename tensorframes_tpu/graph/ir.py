"""Graph IR: the framework's internal representation of a computation graph.

A `Graph` is a list of `GraphNode`s in definition order, each holding an op
name, typed attrs, and input edges. This is the layer the reference kept in
protoc-generated `GraphDef` Java objects and fed to libtensorflow
(`TensorFlowOps.scala:64-74`); here it is a first-class IR that can be

- imported from / exported to TF `GraphDef` wire bytes (compat path),
- built by the tracer / builder DSL front-ends, and
- lowered to a JAX callable (-> XLA) by `ops.lowering`.

Edges use TF's input syntax: ``name``, ``name:k`` (k-th output), and
``^name`` (control edge — order-only; this IR is purely functional, so
control edges are parsed and dropped at lowering).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..proto.graphdef import AttrValue, FunctionDef, GraphDef, NodeDef
from ..schema import ScalarType, Shape

__all__ = ["GraphNode", "Graph", "Subgraph", "parse_edge"]


@dataclass
class Subgraph:
    """An extracted control-flow body: a Graph plus its feed (placeholder
    name) order and fetch edges. `_Cond`/`_While` lowering rules build a
    callable from this exactly like a top-level graph."""

    graph: "Graph"
    feeds: List[str]
    fetches: List[str]


def parse_edge(edge: str) -> Tuple[str, int, bool]:
    """Split a TF input edge into (node_name, output_index, is_control)."""
    if edge.startswith("^"):
        return edge[1:], 0, True
    if ":" in edge:
        name, _, idx = edge.rpartition(":")
        if idx.isdigit():
            return name, int(idx), False
    return edge, 0, False


def base_name(edge: str) -> str:
    """Node name of an edge (strips ``:k`` / ``^``) — the shared `_base`
    helper every verb/planner module aliases."""
    return parse_edge(edge)[0]


@dataclass
class GraphNode:
    name: str
    op: str
    inputs: List[str] = field(default_factory=list)
    attrs: Dict[str, AttrValue] = field(default_factory=dict)

    # -- attr accessors --------------------------------------------------
    def attr(self, key: str, default=None):
        av = self.attrs.get(key)
        return default if av is None else av.value

    @property
    def dtype_attr(self) -> Optional[ScalarType]:
        for key in ("dtype", "T", "DstT"):
            av = self.attrs.get(key)
            if av is not None and av.kind == "type":
                return av.value
        return None

    @property
    def shape_attr(self) -> Optional[Shape]:
        av = self.attrs.get("shape")
        if av is not None and av.kind == "shape":
            return av.value
        return None

    def data_inputs(self) -> List[Tuple[str, int]]:
        out = []
        for e in self.inputs:
            name, idx, ctrl = parse_edge(e)
            if not ctrl:
                out.append((name, idx))
        return out

    def to_node_def(self) -> NodeDef:
        return NodeDef(self.name, self.op, list(self.inputs), dict(self.attrs))

    @classmethod
    def from_node_def(cls, nd: NodeDef) -> "GraphNode":
        return cls(nd.name, nd.op, list(nd.inputs), dict(nd.attrs))


class Graph:
    """An ordered, named DAG of `GraphNode`s.

    Two side tables ride along for control flow:

    - ``library``: FunctionDefs from the GraphDef's FunctionDefLibrary
      (name -> FunctionDef), consumed by `graph.control_flow` to inline
      `PartitionedCall` sites and lower `If`/`While` branches.
    - ``subgraphs``: extracted loop/branch bodies (key -> Subgraph),
      referenced by name from `_Cond`/`_While` pseudo-node attrs after
      functionalization. Keys embed a content hash, so the main graph's
      byte fingerprint still distinguishes different bodies.
    """

    def __init__(self, nodes: Optional[List[GraphNode]] = None):
        self.nodes: List[GraphNode] = []
        self._by_name: Dict[str, GraphNode] = {}
        self._fingerprint: Optional[str] = None
        self.library: Dict[str, "FunctionDef"] = {}
        self.subgraphs: Dict[str, "Subgraph"] = {}
        for n in nodes or []:
            self.add(n)

    def add(self, node: GraphNode) -> GraphNode:
        if node.name in self._by_name:
            raise ValueError(f"duplicate node name {node.name!r}")
        self.nodes.append(node)
        self._by_name[node.name] = node
        self._fingerprint = None
        return node

    def __getitem__(self, name: str) -> GraphNode:
        # Accept "name:0" fetch syntax like TF session fetches.
        base, _, _ = parse_edge(name)
        if base not in self._by_name:
            raise KeyError(
                f"no node {base!r} in graph; nodes: {[n.name for n in self.nodes]}"
            )
        return self._by_name[base]

    def __contains__(self, name: str) -> bool:
        base, _, _ = parse_edge(name)
        return base in self._by_name

    def __iter__(self):
        return iter(self.nodes)

    def __len__(self) -> int:
        return len(self.nodes)

    # -- structure -------------------------------------------------------
    def placeholders(self) -> List[GraphNode]:
        """Graph inputs: zero-input Placeholder ops (the same classification
        as `TensorFlowOps.analyzeGraphTF`, `TensorFlowOps.scala:106-108`)."""
        return [
            n
            for n in self.nodes
            if n.op in ("Placeholder", "PlaceholderV2") and not n.data_inputs()
        ]

    def toposort(self, fetches: Optional[List[str]] = None) -> List[GraphNode]:
        """Topological order of the transitive closure of ``fetches``
        (all nodes if None). Mirrors `DslImpl.getClosure`."""
        if fetches is None:
            wanted = [n.name for n in self.nodes]
        else:
            wanted = [parse_edge(f)[0] for f in fetches]
        order: List[GraphNode] = []
        seen: Dict[str, int] = {}  # 0 = visiting, 1 = done

        def visit(name: str, stack: List[str]):
            state = seen.get(name)
            if state == 1:
                return
            if state == 0:
                raise ValueError(f"cycle through {name!r}: {stack}")
            seen[name] = 0
            node = self[name]
            for dep, _, _ in map(parse_edge, node.inputs):
                visit(dep, stack + [name])
            seen[name] = 1
            order.append(node)

        for w in wanted:
            visit(w, [])
        return order

    # -- GraphDef interchange -------------------------------------------
    def to_graph_def(self) -> GraphDef:
        gd = GraphDef([n.to_node_def() for n in self.nodes])
        gd.library = self._library_proto
        return gd

    _library_proto = None  # raw FunctionDefLibrary for re-serialization

    @classmethod
    def from_graph_def(cls, gd: GraphDef) -> "Graph":
        g = cls([GraphNode.from_node_def(n) for n in gd.nodes])
        if gd.library is not None:
            g.library = gd.library.by_name()
            g._library_proto = gd.library
        return g

    @classmethod
    def from_bytes(cls, data: bytes) -> "Graph":
        """Parse GraphDef wire bytes. Uses the native C++ parser
        (`native/graphdef.cc` — parse + validate + cycle check in one pass)
        when built, with the pure-Python wire codec as fallback."""
        from ..native import parse_graph_native
        from ..proto.graphdef import AttrValue

        native = None
        try:
            native = parse_graph_native(data)
        except ValueError:
            raise  # malformed/invalid graph: surface the native error
        if native is not None:
            g = cls()
            for name, op, inputs, raw_attrs in native:
                attrs = {
                    k: AttrValue.from_bytes(v) for k, v in raw_attrs.items()
                }
                g.add(GraphNode(name, op, inputs, attrs))
            # the native parser returns nodes only: scan field 2 (the
            # FunctionDefLibrary) with the Python codec so If/While
            # branches and PartitionedCall bodies are not dropped
            from ..proto import wire
            from ..proto.graphdef import FunctionDefLibrary

            for f, _, v in wire.iter_fields(data):
                if f == 2:
                    lib = FunctionDefLibrary.from_bytes(v)
                    g.library = lib.by_name()
                    g._library_proto = lib
            return g
        return cls.from_graph_def(GraphDef.from_bytes(data))

    @classmethod
    def from_file(cls, path: str) -> "Graph":
        return cls.from_graph_def(GraphDef.from_file(path))

    def to_bytes(self) -> bytes:
        return self.to_graph_def().to_bytes()

    def clone(self) -> "Graph":
        """Structural copy: fresh `GraphNode`s (input lists and attr
        dicts copied one level deep) sharing the library / subgraph side
        tables. The splice machinery (`graph.fuse`) builds fused graphs
        on top of a clone so the producer plan is never mutated —
        LazyFrames stay immutable and can branch like frames do."""
        g = Graph(
            [
                GraphNode(n.name, n.op, list(n.inputs), dict(n.attrs))
                for n in self.nodes
            ]
        )
        g.library = dict(self.library)
        g._library_proto = self._library_proto
        g.subgraphs = dict(self.subgraphs)
        return g

    def fingerprint(self) -> str:
        """Stable content hash; the compile-cache key component that replaces
        the reference's per-task graph re-import (`DebugRowOps.scala:790`).
        Cached after first use (serializing the graph dominated verb
        dispatch otherwise); `add` invalidates."""
        if self._fingerprint is None:
            self._fingerprint = hashlib.sha256(self.to_bytes()).hexdigest()[:16]
        return self._fingerprint

    def __repr__(self) -> str:
        return f"Graph({len(self.nodes)} nodes)"
