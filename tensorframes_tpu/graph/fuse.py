"""Graph splicing: fuse a consumer graph onto a producer graph.

The mechanical core of lazy verb fusion (`tensorframes_tpu.lazy`). A
chained ``map_blocks -> map_blocks -> reduce_blocks`` pipeline is, at
the graph level, a sequence of graphs where each stage's placeholders
read the previous stage's outputs by column name. `splice` turns that
chain into ONE graph: consumer placeholders bound to a producer output
are deleted and their consumers rewired to the producer edge, every
other consumer node is copied in with its name uniquified against the
producer's namespace, and the function library / extracted control-flow
subgraphs of both sides merge.

The result is an ordinary `Graph`, so everything downstream — analysis,
`build_callable` lowering, the executor compile cache keyed on
`Graph.fingerprint()` — works unchanged: XLA sees the entire chain as
one program and keeps intermediates in registers/HBM-local instead of
materializing a device buffer per verb (the HiFrames observation,
arxiv 1704.02341: operator fusion is the dominant win for dataframe
pipelines).

Placeholder<->output *matching policy* (name conventions, dtype/shape
validation) lives with the caller (`lazy.LazyFrame`); this module only
performs the validated rewiring.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Tuple

from .ir import Graph, GraphNode, parse_edge

__all__ = ["splice", "chain_fingerprint"]


def chain_fingerprint(graph: Graph, feed_map: Dict[str, str],
                      outputs) -> str:
    """Canonical digest of one fused verb chain: the spliced graph's
    content fingerprint plus the placeholder->column bindings and the
    (sorted) output column set. This is the identity a fused chain
    contributes to a relational plan fingerprint (`graph.plan`) — two
    chains that fused to the same program over the same bindings key
    identically no matter how many verb calls produced them."""
    h = hashlib.sha256(graph.fingerprint().encode())
    for ph, colname in sorted(feed_map.items()):
        h.update(f"|{ph}={colname}".encode())
    for out in sorted(outputs):
        h.update(f"|>{out}".encode())
    return h.hexdigest()[:16]


def _rewired_edge(edge: str, target: str) -> str:
    """Rewire ``edge`` (which pointed at a bound placeholder) to the
    producer edge ``target``. Placeholders have exactly one output, so
    the consumer-side output index is always 0 and the target edge is
    used verbatim; control edges retarget to the target's base node."""
    _, _, ctrl = parse_edge(edge)
    if ctrl:
        return "^" + parse_edge(target)[0]
    return target


def splice(
    producer: Graph,
    consumer: Graph,
    bindings: Dict[str, str],
    fetches: List[str],
) -> Tuple[Graph, List[str], Dict[str, str]]:
    """Splice ``consumer`` onto ``producer``.

    ``bindings`` maps consumer placeholder names to producer edges
    (``node`` / ``node:k``): those placeholders are dropped and their
    consumers rewired to the producer edge. Every other consumer node is
    added to the fused graph, renamed only on collision with a producer
    node name (suffix ``__f<k>``), so single-stage plans keep their
    original names and fingerprints stay content-deterministic.

    Returns ``(fused graph, fetches rewritten into fused edges,
    rename map: consumer node name -> fused node name)``. The producer's
    own node names and edges are preserved verbatim, so any producer
    fetch edge remains valid in the fused graph.
    """
    fused = producer.clone()
    # side tables: extracted control-flow bodies merge freely (subgraph
    # keys are content-hashed, so a same-key collision means an
    # identical body); function libraries are keyed by NAME, and two
    # stages traced in different processes can carry the same function
    # name with different bodies (TF's name counter is per-process) —
    # silently letting one win would make call sites execute the wrong
    # body, so a same-name different-bytes collision refuses to fuse
    for fname, fdef in consumer.library.items():
        prev = producer.library.get(fname)
        if prev is not None and prev is not fdef and (
            prev.to_bytes() != fdef.to_bytes()
        ):
            raise ValueError(
                f"splice: function library collision on {fname!r} with "
                "different bodies between stages; force() between them"
            )
    fused.library = {**producer.library, **consumer.library}
    fused.subgraphs = {**producer.subgraphs, **consumer.subgraphs}
    if fused.library:
        from ..proto.graphdef import FunctionDefLibrary

        # rebuilt (raw=b"") library: serializes from .functions, so the
        # fused fingerprint still covers merged function bodies
        fused._library_proto = FunctionDefLibrary(list(fused.library.values()))

    dropped = {
        n.name
        for n in consumer.placeholders()
        if n.name in bindings
    }
    unknown = sorted(set(bindings) - dropped)
    if unknown:
        raise ValueError(
            f"splice: bindings {unknown} do not name consumer placeholders "
            f"(placeholders: {sorted(p.name for p in consumer.placeholders())})"
        )

    rename: Dict[str, str] = {}
    for n in consumer.nodes:
        if n.name in dropped:
            continue
        name = n.name
        if name in fused:
            k = 1
            while f"{name}__f{k}" in fused or f"{name}__f{k}" in rename.values():
                k += 1
            name = f"{name}__f{k}"
        rename[n.name] = name
        fused.add(GraphNode(name, n.op, [], dict(n.attrs)))  # inputs below

    def rw(edge: str) -> str:
        base, idx, ctrl = parse_edge(edge)
        if base in dropped:
            return _rewired_edge(edge, bindings[base])
        if base not in rename:
            raise ValueError(
                f"splice: consumer edge {edge!r} references {base!r}, "
                "which is neither a consumer node nor a bound placeholder"
            )
        new = rename[base]
        if ctrl:
            return "^" + new
        return f"{new}:{idx}" if idx else new

    for n in consumer.nodes:
        if n.name in dropped:
            continue
        fused[rename[n.name]].inputs.extend(rw(e) for e in n.inputs)

    return fused, [rw(f) for f in fetches], rename
