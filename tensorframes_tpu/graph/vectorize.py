"""Auto-batching pass for functionalized per-row control flow.

The reference ran arbitrary GraphDefs because libtensorflow interpreted
dataflow control flow per row; our XLA port functionalizes
`tf.cond`/`tf.while_loop` into `_Cond`/`_While` pseudo-nodes
(`graph.control_flow`), and the conservative row-local classifier
(`aggregate._rowwise_transform`) used to disqualify any graph containing
them — branchy per-row workloads lost the bucket ladder, OOM splitting,
serving batching, and the GlobalFrame one-dispatch SPMD path.

This module closes that gap with the lowering "Auto-Vectorizing
TensorFlow Graphs" describes (PAPERS.md):

* `_Cond` whose branch subgraphs are row-local lowers to
  both-branches-evaluated + a select on the batched predicate
  (`select_cond`). Legal because `freeze_variables` already guarantees
  branch bodies are side-effect-free pure functions.
* `_While` lowers to a convergence-masked fixed point (`masked_while`):
  one `lax.while_loop` iterates until EVERY row's predicate is false;
  rows that converged early are carried through later iterations
  unchanged by a per-row boolean mask folded into the carry. The trip
  count is bounded by the same static-shape contract scalar loops obey.

`subgraphs_row_local` is the classification hook `_rowwise_transform`
calls for control-flow nodes: a `_Cond`/`_While` counts as row-local
exactly when every branch/cond/body subgraph passes the SAME row-local
walk at the enclosing graph's lead rank (subgraph feeds are slices of
the outer row axis, so they inherit it). That one predicate threads the
fast path through every consumer of `shape_policy.rowwise_fetches`:
`api.map_blocks` bucketing, `api.map_rows` bucketed vmapped dispatch,
`lazy` fusion, `globalframe` SPMD routing, and the serving batchability
probe.

Everything is gated behind ``config.row_vectorize`` (env
``TFS_ROW_VECTORIZE``, default on). Graphs whose branches or carries are
not row-local fall back to the historical unbatched path; every decision
is counted by reason in the module ledger (`state()` /
`tfs.diagnostics()`) and in the always-live Prometheus counters
``row_vectorize_lowered{kind=}`` / ``row_vectorize_fallbacks{reason=}``.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# Control-flow pseudo-nodes this pass can vectorize, mapped to their
# subgraph attr keys and the fallback-reason label each subgraph gets
# when it fails the row-local walk.
_SUB_ATTRS = {
    "_Cond": (("cond_then", "cond-branch"), ("cond_else", "cond-branch")),
    "_While": (("while_cond", "while-cond"), ("while_body", "while-body")),
}

#: Node ops `aggregate._rowwise_transform` defers to `subgraphs_row_local`
#: instead of rejecting outright.
CONTROL_OPS = frozenset(_SUB_ATTRS)

_state_lock = threading.Lock()
_stats: Dict[str, Dict[str, int]] = {"lowered": {}, "fallbacks": {}}


def enabled() -> bool:
    from .. import config

    return bool(config.get().row_vectorize)


def note_lowered(kind: str) -> None:
    """One masked dense lowering traced (kind: ``cond`` | ``while``).

    Fires at trace time — once per compiled specialization, not per
    dispatch — which is what "how many programs went through the
    vectorizer" means."""
    from ..utils import telemetry as _tele

    with _state_lock:
        _stats["lowered"][kind] = _stats["lowered"].get(kind, 0) + 1
    _tele.counter_inc("row_vectorize_lowered", 1.0, kind=kind)


def note_fallback(reason: str) -> None:
    """One graph kept OFF the vectorized fast path, by reason. Counts
    classification events (a graph probed by several consumers counts
    once per probe), mirroring `global_fallbacks` semantics."""
    from ..utils import telemetry as _tele

    with _state_lock:
        _stats["fallbacks"][reason] = _stats["fallbacks"].get(reason, 0) + 1
    _tele.counter_inc("row_vectorize_fallbacks", 1.0, reason=reason)


def state() -> Dict:
    """Snapshot for `tfs.diagnostics()`: lowerings by kind, fallbacks by
    reason."""
    with _state_lock:
        return {
            "lowered": dict(_stats["lowered"]),
            "fallbacks": dict(_stats["fallbacks"]),
        }


def reset_state() -> None:
    with _state_lock:
        _stats["lowered"] = {}
        _stats["fallbacks"] = {}


def lift_to_block_level(graph):
    """Stamp a leading unknown row axis onto every placeholder's
    declared shape, in place, and return the graph.

    TensorFlow cannot author per-row control flow at block level —
    `tf.cond`/`tf.while_loop` demand a SCALAR predicate — so a
    block-level branchy program is authored per row (cell-level
    placeholders, scalar predicates) and lifted: after the lift the
    predicates carry the block's row axis and the masked dense
    lowerings in this module take over. This is how branchy serving
    endpoints and block-level branchy maps are built (tests and
    `benchmarks/autobatch_bench.py` use it)."""
    from ..proto.graphdef import AttrValue
    from ..schema import Shape

    for ph in graph.placeholders():
        cell = ph.shape_attr
        dims = (None,) + tuple(cell.dims) if cell is not None else (None,)
        ph.attrs["shape"] = AttrValue.of_shape(Shape(dims))
    return graph


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def subgraphs_row_local(graph, node, lead_rank: int) -> bool:
    """True when every subgraph of control-flow ``node`` is row-local at
    the enclosing graph's ``lead_rank``.

    Subgraph placeholders (``__sw{k}``/``__var{i}``/``__cap{j}``) carry
    slices of the outer graph's row axis, so each one is checked at the
    OUTER lead rank; nested control flow recurses through the same walk.
    Counts a fallback reason on every rejection so branchy graphs that
    stay off the fast path are visible in diagnostics."""
    if not enabled():
        note_fallback("disabled")
        return False
    from ..aggregate import _rowwise_transform

    for attr_key, label in _SUB_ATTRS[node.op]:
        key = node.attr(attr_key)
        key = key.decode() if isinstance(key, bytes) else key
        sub = getattr(graph, "subgraphs", {}).get(key)
        if sub is None:
            note_fallback(f"{label}-missing")
            return False
        if not _rowwise_transform(
            sub.graph, list(sub.fetches), lambda _name: lead_rank
        ):
            note_fallback(f"{label}-not-row-local")
            return False
    return True


# ---------------------------------------------------------------------------
# masked dense lowerings (called from ops/control.py when the traced
# predicate is batched — i.e. the per-row graph is executing at block
# level, where the predicate carries the block's row axis)
# ---------------------------------------------------------------------------


def _lowering_error(msg: str):
    from ..ops.registry import GraphLoweringError

    return GraphLoweringError(msg)


def _pred_rows(node, shape) -> int:
    """Row count of a batched predicate (shape-only: works on avals,
    tracers, and concrete arrays alike)."""
    shape = tuple(shape)
    if len(shape) < 1 or math.prod(shape) != shape[0]:
        raise _lowering_error(
            f"{node.op} (node {node.name!r}) predicate has shape "
            f"{shape}; a vectorized predicate must carry exactly one "
            "value per row (lead axis only, unit trailing dims)"
        )
    return int(shape[0])


def _flat_rows(node, pred) -> Tuple[int, jnp.ndarray]:
    """Interpret a batched predicate as one boolean per row."""
    p = jnp.asarray(pred)
    n = _pred_rows(node, p.shape)
    return n, p.reshape((n,)).astype(bool)


def select_cond(node, pred, then_outs, else_outs) -> Tuple:
    """Both-branches-evaluated + per-output select on the batched
    predicate. Branch outputs may sit below the lead rank (a per-row
    scalar/vector the branch computed identically for every row); they
    broadcast against the row-axis mask like any sub-lead constant in a
    row-local graph."""
    n, mask = _flat_rows(node, pred)
    outs = []
    for i, (t, e) in enumerate(zip(then_outs, else_outs)):
        t, e = jnp.asarray(t), jnp.asarray(e)
        if t.dtype != e.dtype:
            raise _lowering_error(
                f"_Cond (node {node.name!r}) output {i}: then-branch "
                f"dtype {t.dtype} != else-branch dtype {e.dtype}; both "
                "branches of a cond must produce the same dtype"
            )
        rank = max(t.ndim, e.ndim, 1)
        m = mask.reshape((n,) + (1,) * (rank - 1))
        try:
            jnp.broadcast_shapes(m.shape, t.shape, e.shape)
        except ValueError:
            raise _lowering_error(
                f"_Cond (node {node.name!r}) output {i}: then-branch "
                f"shape {t.shape} and else-branch shape {e.shape} do not "
                f"broadcast against the {n}-row predicate; both branches "
                "must produce per-row-compatible shapes"
            ) from None
        outs.append(jnp.where(m, t, e))
    note_lowered("cond")
    return tuple(outs)


def check_branch_avals(node, tfn, efn, operands) -> None:
    """Scalar-predicate pre-check: `lax.cond` demands identical output
    avals from both branches; diagnose the mismatch by output index and
    shape/dtype instead of surfacing XLA's raw trace error."""
    touts = jax.eval_shape(lambda *o: tuple(tfn(*o)), *operands)
    eouts = jax.eval_shape(lambda *o: tuple(efn(*o)), *operands)
    for i, (t, e) in enumerate(zip(touts, eouts)):
        if t.shape != e.shape or t.dtype != e.dtype:
            raise _lowering_error(
                f"_Cond (node {node.name!r}) output {i}: then-branch "
                f"produces {t.dtype}{list(t.shape)} but else-branch "
                f"produces {e.dtype}{list(e.shape)}; both branches of a "
                "cond must produce the same shape and dtype"
            )


def check_while_carry(node, body_fn, carry, n_vars: int) -> None:
    """Scalar-path pre-check: `lax.while_loop` demands the body preserve
    every carry aval exactly; name the offending carry (loop var vs
    invariant capture, original input edge, shapes/dtypes) instead of
    surfacing XLA's raw trace error."""
    outs = jax.eval_shape(lambda *c: tuple(body_fn(*c)), *carry)
    for i, (c, o) in enumerate(zip(carry, outs)):
        if o.shape != c.shape or o.dtype != c.dtype:
            raise _lowering_error(_carry_drift_msg(node, i, n_vars, c, o))


def _carry_drift_msg(node, i, n_vars, c, o) -> str:
    kind = "loop var" if i < n_vars else "invariant capture"
    edge = node.inputs[i] if i < len(node.inputs) else "<missing>"
    return (
        f"_While (node {node.name!r}) carry {i} ({kind}, input "
        f"{edge!r}) drifts from {jnp.dtype(c.dtype)}{list(c.shape)} to "
        f"{jnp.dtype(o.dtype)}{list(o.shape)} across iterations; loop "
        "carries must keep a fixed shape and dtype"
    )


def masked_while(node, carry, n_vars: int, cond_fn, body_fn, pred0) -> Tuple:
    """Lower a `_While` with a batched predicate to ONE dense
    `lax.while_loop` over the whole block.

    Semantics: every carry broadcasts to the row axis (rows evolve
    independently); the loop iterates while ANY row's predicate holds;
    a per-row convergence mask in the carry freezes rows whose predicate
    went false, so ragged per-row trip counts execute in
    max-trips-over-rows dense iterations. Pad rows (shape bucketing
    replicates the last valid row) converge exactly when their source
    row does, so the bucket ladder stays sound."""
    n = _pred_rows(node, pred0.shape)
    carry = tuple(_broadcast_lead(c, n) for c in carry)

    # loud-naming pre-check (same contract as the scalar path, relaxed
    # to broadcast-compatibility: a body output may sit sub-lead and be
    # spread across rows by the mask select)
    outs = jax.eval_shape(lambda *c: tuple(body_fn(*c)), *carry)
    for i, (c, o) in enumerate(zip(carry, outs)):
        ok = o.dtype == c.dtype
        if ok:
            try:
                ok = jnp.broadcast_shapes(o.shape, c.shape) == c.shape
            except ValueError:
                ok = False
        if not ok:
            raise _lowering_error(_carry_drift_msg(node, i, n_vars, c, o))

    def _pred(c) -> jnp.ndarray:
        p = jnp.asarray(cond_fn(*c)[0]).astype(bool)
        if p.size == 1:
            return jnp.broadcast_to(p.reshape(()), (n,))
        return _flat_rows(node, p)[1]

    def _step(state):
        active, c = state
        new = tuple(jnp.asarray(v) for v in body_fn(*c))
        sel = tuple(
            jnp.where(
                active.reshape((n,) + (1,) * (old.ndim - 1)), nv, old
            )
            for nv, old in zip(new, c)
        )
        return (jnp.logical_and(active, _pred(sel)), sel)

    _, final = lax.while_loop(
        lambda state: jnp.any(state[0]), _step, (_pred(carry), carry)
    )
    note_lowered("while")
    return tuple(final[:n_vars])


def _broadcast_lead(c, n: int) -> jnp.ndarray:
    """Give every carry the row axis: arrays already leading with the
    block's row count pass through; sub-lead carries (a shared initial
    accumulator, an invariant capture) replicate per row."""
    c = jnp.asarray(c)
    if c.ndim >= 1 and c.shape[0] == n:
        return c
    return jnp.broadcast_to(c, (n,) + c.shape)
