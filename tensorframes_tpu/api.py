"""The five execution verbs + schema utilities: the public API.

TPU-native implementation of the reference's `OperationsInterface`
(`Operations.scala:20-135`) and Python surface (`core.py`):

- ``map_blocks(fetches, frame, trim=...)``   (`Operations.scala:43,59`)
- ``map_rows(fetches, frame)``               (`Operations.scala:77`)
- ``reduce_rows(fetches, frame)``            (`Operations.scala:96`)
- ``reduce_blocks(fetches, frame)``          (`Operations.scala:108`)
- ``aggregate(fetches, frame.group_by(k))``  (`Operations.scala:126`)
- ``analyze`` / ``print_schema`` / ``append_shape`` (`ExperimentalOperations.scala`)
- ``block`` / ``row`` placeholder helpers    (`core.py:451-474`)

Graphs may be builder-DSL tensors, imported GraphDefs (bytes / file path /
`Graph`), or plain Python functions over column arrays (the TPU-native
tracer front-end — no GraphDef needed).

Execution model vs the reference: instead of one native TF session per
Spark partition (`performMap`, `DebugRowOps.scala:773-810`), each graph is
jitted once into an XLA executable and applied per block; reductions stack
per-block partials and run one combine step (the driver-funneled pairwise
`RDD.reduce` at `DebugRowOps.scala:507,530` becomes a single on-device
fold — distributed variants ride ICI collectives, see `parallel/`).

Validation mirrors `SchemaTransforms` (`DebugRowOps.scala:80-272`): dtype
equality (TF graphs don't promote), column shapes must be at least as
precise as placeholder shapes (else the error points at `analyze`), and
the reduce verbs enforce the reference's naming conventions
(``x`` ↔ ``x_input`` for block reduces, ``x`` ↔ ``x_1``/``x_2`` for row
reduces, `DebugRowOps.scala:80-262`).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax import lax

from .frame import Column, TensorFrame, factorize_keys
from .graph import builder as dsl
from .graph.analysis import GraphSummary, ShapeHints, analyze_graph
from .graph.ir import Graph, base_name, parse_edge
from .ops.lowering import build_callable
from .runtime import deadline as _dl
from .runtime.deadline import deadline_entry as _deadline_entry
from .runtime.executor import Executor, default_executor
from .runtime.faults import maybe_check_numerics
from .schema import Shape

__all__ = [
    "map_blocks",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "reduce_blocks_stream",
    "aggregate",
    "analyze",
    "print_schema",
    "append_shape",
    "block",
    "row",
    "group_by",
    "GroupedFrame",
    "explain",
    "explain_detailed",
    "block_to_row",
    "lazy",
    "LazyFrame",
]

Fetches = Union[dsl.Tensor, Sequence[dsl.Tensor], Graph, bytes, str, Callable]


def _is_pandas(obj) -> bool:
    return type(obj).__module__.startswith("pandas")


def _pandas_in_out(verb):
    """Verb wrapper: pandas in/out (the reference's local-debug path,
    `_map_pd`, `core.py:171-183`) + execution stats recording
    (`utils.profiling.record`)."""
    import functools

    from .utils.profiling import record

    @functools.wraps(verb)
    def wrapper(fetches, frame, *args, **kwargs):
        if _is_pandas(frame):
            tf_frame = TensorFrame.from_pandas(frame)
            with record(verb.__name__, tf_frame.nrows):
                out = verb(fetches, tf_frame, *args, **kwargs)
            from .lazy import LazyFrame

            if isinstance(out, LazyFrame):
                # pandas in -> pandas out is the eager debug path; a
                # lazy() mode active around it must not leak a deferred
                # plan to a pandas caller
                out = out.force()
            return out.to_pandas() if isinstance(out, TensorFrame) else out
        rows = frame.nrows if isinstance(frame, TensorFrame) else 0
        with record(verb.__name__, rows):
            return verb(fetches, frame, *args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# graph normalization
# ---------------------------------------------------------------------------


def _as_graph(
    fetches: Fetches, fetch_names: Optional[Sequence[str]]
) -> Tuple[Graph, List[str]]:
    if isinstance(fetches, dsl.Tensor):
        return dsl.build(fetches)
    if isinstance(fetches, (list, tuple)) and all(
        isinstance(f, dsl.Tensor) for f in fetches
    ):
        return dsl.build(list(fetches))
    if isinstance(fetches, Graph):
        g = fetches
    elif isinstance(fetches, bytes):
        g = Graph.from_bytes(fetches)
    elif isinstance(fetches, str):
        g = Graph.from_file(fetches)
    else:
        raise TypeError(f"cannot interpret fetches of type {type(fetches)!r}")
    if not fetch_names:
        raise ValueError(
            "imported graphs need explicit fetch_names=[...] "
            "(the reference's builder.fetches, PythonInterface.scala:105-108)"
        )
    # Control flow (v1 Switch/Merge rings, v2 If/While, function calls)
    # functionalizes to _Cond/_While pseudo-nodes FIRST — the reference
    # could hand any GraphDef to libtensorflow (`TensorFlowOps.scala:76-95`);
    # here the same graphs must become lax.cond/lax.while_loop to compile.
    from .graph.control_flow import functionalize

    g, fetch_names = functionalize(g, list(fetch_names))
    # Stateful graphs are frozen at import, exactly where the reference
    # freezes them (`_get_graph` -> `_initialize_variables`, core.py:42-56).
    from .graph.freeze import freeze_variables

    return freeze_variables(g), list(fetch_names)


_base = base_name


# ---------------------------------------------------------------------------
# placeholder <-> column matching + validation (SchemaTransforms)
# ---------------------------------------------------------------------------

_REDUCE_SUFFIXES = ("_input", "_1", "_2")


def _default_column(ph_name: str, frame: TensorFrame) -> str:
    """Reference naming conventions: placeholder ``x_input``/``x_1``/``x_2``
    reads column ``x`` by default (`DebugRowOps.scala:80-262`). An exact
    column-name match always wins — suffix stripping only kicks in when no
    column carries the placeholder's literal name (so a column named
    ``temp_1`` is not hijacked by the convention)."""
    if ph_name in frame.info:
        return ph_name
    for suf in _REDUCE_SUFFIXES:
        if ph_name.endswith(suf):
            candidate = ph_name[: -len(suf)]
            if candidate in frame.info:
                return candidate
    return ph_name


def _check_bindings(
    summary: GraphSummary, bindings: Dict[str, "np.ndarray"]
) -> None:
    """Validate per-call bound arrays against their placeholders.

    Bindings are the TPU-native answer to the reference's pattern of
    re-embedding updated values as graph constants each iteration (e.g.
    `kmeans_demo.py` rebuilds the graph with new centers every Lloyd step,
    which under XLA would force a recompile per step): a bound array is a
    *jit argument*, so the compiled executable is reused across calls as
    long as the shape is stable."""
    from .schema import ScalarType

    for name, arr in bindings.items():
        if name not in summary.inputs:
            raise ValueError(
                f"binding {name!r} does not match any placeholder "
                f"(placeholders: {sorted(summary.inputs)})"
            )
        ph = summary.inputs[name]
        st = ScalarType.from_np_dtype(np.dtype(arr.dtype))
        if st is not ph.dtype:
            raise ValueError(
                f"binding {name!r} has dtype {st.name} but placeholder wants "
                f"{ph.dtype.name} (TF graphs do not promote dtypes)"
            )
        if not Shape(arr.shape).check_more_precise_than(ph.shape):
            raise ValueError(
                f"binding {name!r} with shape {tuple(arr.shape)} is not "
                f"compatible with placeholder shape {ph.shape}"
            )


def _match_columns(
    summary: GraphSummary,
    frame: TensorFrame,
    feed_dict: Optional[Dict[str, str]],
    block_level: bool,
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
) -> Dict[str, str]:
    """Map placeholder name -> column name; validate dtype + shape precision.

    Placeholders named in ``bindings`` are fed the bound array per call
    instead of a column and are excluded from the mapping."""
    feed_dict = feed_dict or {}
    mapping: Dict[str, str] = {}
    for ph_name, ph in summary.inputs.items():
        if bindings and ph_name in bindings:
            continue
        col_name = feed_dict.get(ph_name, _default_column(ph_name, frame))
        if col_name not in frame.info:
            raise ValueError(
                f"placeholder {ph_name!r} wants column {col_name!r} which is "
                f"not in the frame (columns: {frame.columns}); use feed_dict "
                "to rename"
            )
        info = frame.info[col_name]
        if info.dtype is not ph.dtype:
            raise ValueError(
                f"placeholder {ph_name!r} has dtype {ph.dtype.name} but "
                f"column {col_name!r} has dtype {info.dtype.name} (TF graphs "
                "do not promote dtypes)"
            )
        col_shape = info.block_shape if block_level else info.cell_shape
        if not col_shape.check_more_precise_than(ph.shape):
            raise ValueError(
                f"column {col_name!r} with shape {col_shape} is not compatible"
                f" with shape {ph.shape} requested by placeholder {ph_name!r}."
                " If the column shape has unknown dims, run tfs.analyze(frame)"
                " first (ExperimentalOperations.analyze)"
            )
        mapping[ph_name] = col_name
    return mapping


def _require_dense(frame: TensorFrame, cols: Sequence[str], verb: str) -> None:
    for c in cols:
        if not frame.column(c).is_dense:
            raise ValueError(
                f"{verb}: column {c!r} is ragged (rows have varying shapes); "
                "block-level ops need uniform cells — use map_rows, or fix "
                "the data"
            )


def _ph_overrides(
    summary_graph: Graph,
    frame: TensorFrame,
    feed_dict: Optional[Dict[str, str]],
    block_level: bool,
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
) -> Dict[str, Shape]:
    """Column shapes are usually *more* precise than placeholder attrs
    (e.g. imported graphs carry [?,?]); inject them for tighter analysis,
    mirroring how `block()` stamps column shapes onto placeholders
    (`DslImpl.scala:90-107`)."""
    feed_dict = feed_dict or {}
    bindings = bindings or {}
    overrides: Dict[str, Shape] = {}
    for ph in summary_graph.placeholders():
        if ph.name in bindings:
            shape = Shape(np.asarray(bindings[ph.name]).shape)
            attr = ph.shape_attr
            # Only overriding when compatible (same guard as the column
            # path below) keeps the declared placeholder shape visible to
            # _check_bindings for incompatible bindings.
            if attr is None or shape.check_more_precise_than(attr):
                overrides[ph.name] = shape
            continue
        col_name = feed_dict.get(ph.name, _default_column(ph.name, frame))
        if col_name in frame.info:
            info = frame.info[col_name]
            shape = info.block_shape if block_level else info.cell_shape
            attr = ph.shape_attr
            if attr is None or shape.check_more_precise_than(attr):
                overrides[ph.name] = shape
    return overrides


# ---------------------------------------------------------------------------
# output frame assembly
# ---------------------------------------------------------------------------


_donation_warning_filtered = False
_donation_filter_lock = threading.Lock()


def _quiet_donation_warning() -> None:
    """Register (once, process-wide) an ignore filter for jax's "Some
    donated buffers were not usable" warning: a reduce's output is
    smaller than its stacked partials by construction, so most donated
    partial buffers are freed for intermediate reuse rather than
    aliased into the output — exactly the intent, not a bug worth
    warning about. One-time registration (module-level lock) instead of
    a per-call ``warnings.catch_warnings`` because the latter mutates
    and restores process-global filter state and is not thread-safe
    under concurrent verbs."""
    global _donation_warning_filtered
    if _donation_warning_filtered:
        return
    import warnings

    with _donation_filter_lock:
        if not _donation_warning_filtered:
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            _donation_warning_filtered = True


def _dispatch_reduce_block(
    span_name, fp, fn, mask_plan, sched, fscope, bi, lo, hi,
    feeds_for, split_combs, what_verb,
):
    """One reduce-block dispatch with classified fault handling — THE
    shared recipe of the eager reduce and the fused lazy reduce
    terminal. Transient errors retry with backoff (+ device failover
    under the scheduler, via ``fscope``); a RESOURCE error (OOM)
    splits ``[lo, hi)`` in half down the bucket ladder and
    monoid-combines the half partials (`faults.combine_split_partials`)
    when ``split_combs`` — the chunk-classifier verdict, in fetch
    order — proves the graph combinable; unclassifiable graphs
    re-raise the original error exactly. Returns the partial tuple."""
    from . import shape_policy as _sp
    from .runtime import faults as _flt
    from .utils import telemetry as _tele

    def run(lo_, hi_, depth):
        feeds = feeds_for(lo_, hi_)
        bucket = None
        if mask_plan is not None:
            # pad ONCE per logical dispatch, OUTSIDE the retried thunk
            # (the same discipline as the map paths): a transient
            # retry re-dispatches the already-padded feeds instead of
            # re-padding, and pad_feeds' bucket_fill observation fires
            # exactly once per logical dispatch, not once per attempt
            feeds, bucket = _sp.pad_feeds(feeds, hi_ - lo_)

        def _thunk():
            # per-attempt span: retried/failed-over attempts each
            # charge the device they actually dispatched to; a masked
            # dispatch labels its bucket rung (the dispatched lead
            # dim) so pad waste and the ledger-shape join see it
            with _tele.dispatch_span(
                span_name, program=fp, block=bi, rows=hi_ - lo_,
                bucket=bucket,
                masked=mask_plan is not None or None,
                device=sched.label(bi) if sched is not None else None,
            ):
                if mask_plan is not None:
                    if sched is not None:
                        return sched.bind(bi, fn, valid=hi_ - lo_)(*feeds)
                    return fn(np.int32(hi_ - lo_), *feeds)
                if sched is not None:
                    return sched.bind(bi, fn)(*feeds)
                return fn(*feeds)

        try:
            outs = fscope.dispatch(
                _thunk,
                what=f"{what_verb} block {bi} rows [{lo_}:{hi_})",
                sched=sched, index=bi,
            )
        except Exception as e:
            if _flt.classify(e) != _flt.RESOURCE:
                raise
            if split_combs is None:
                # OOM on an unclassifiable reduce: no monoid recipe to
                # combine halves — re-raise the original error, with
                # the forensic snapshot explaining WHY no split ran
                _flt.record_oom(
                    what_verb, fp, hi_ - lo_, depth,
                    "reraise:unclassifiable-reduce", e, bucket=bucket,
                )
                raise
            if not _flt.split_allowed(hi_ - lo_, depth):
                _flt.record_oom(
                    what_verb, fp, hi_ - lo_, depth,
                    "reraise:split-depth-exhausted", e, bucket=bucket,
                )
                raise
            mid = (lo_ + hi_) // 2
            _flt.record_oom(
                what_verb, fp, hi_ - lo_, depth,
                f"split:[{lo_}:{mid})+[{mid}:{hi_})", e, bucket=bucket,
            )
            _flt.note_split(what_verb)
            left = run(lo_, mid, depth + 1)
            right = run(mid, hi_, depth + 1)
            return _flt.combine_split_partials(
                split_combs, left, right, mid - lo_, hi_ - mid
            )
        return tuple(outs)

    return run(lo, hi, 0)


def _combine_partials(ex, kind, graph, fetch_list, feed_names, build, partials):
    """One jitted donated combine over all per-block partials — the ONE
    donation/caching discipline both reduce verbs share.

    The partials arrive as a tuple of per-block fetch tuples of device
    arrays (never host-fetched); ``build()`` returns the combine
    function of that parts-pytree (stack on device, re-reduce — the
    stacking recipe differs between reduce_blocks' re-fed graph and
    reduce_rows' scan fold, which is why it is a parameter). On
    executors that support it the partial buffers are DONATED — after
    the combine they are dead by construction, so XLA reuses their HBM
    for the stacked intermediate instead of allocating fresh buffers.
    """

    def make():
        combine = build()
        if getattr(ex, "supports_donation", False):
            _quiet_donation_warning()
            return jax.jit(combine, donate_argnums=0)
        return jax.jit(combine)

    # cooperative deadline boundary: a verb whose budget ran out during
    # the per-block dispatches must not start the combine
    _dl.check(kind)
    cfn = ex.cached(kind, graph, fetch_list, feed_names, make)
    from .runtime import faults as _flt
    from .utils import telemetry as _tele

    # rows stays unset: the combine consumes per-block PARTIALS, and a
    # partial count in the block_rows histogram would skew the per-block
    # row-size distribution the histogram documents
    with _tele.dispatch_span(
        kind, program=graph.fingerprint(), partials=len(partials)
    ):
        # Classified transient retry — with a donation caveat: on
        # donating executors a failure INSIDE the compiled call may
        # have consumed the partial buffers already, in which case the
        # retry dies on deleted arrays. That secondary error must not
        # mask the real one, so the ORIGINAL transient error re-raises
        # whenever the retry fails differently. (Injected faults raise
        # before the program runs, so their retries do recover.) No
        # split handler here — partials are already reduced, there is
        # no row range to halve — so resource errors surface
        # immediately.
        from . import config as _config

        try:
            return tuple(cfn(tuple(partials)))
        except Exception as first:
            attempts = _config.get().block_retry_attempts
            if _flt.classify(first) != _flt.TRANSIENT or attempts < 1:
                # attempts=0 means retries are OFF — the config contract
                # every FaultScope site honors applies here too
                raise
            _flt.note_transient_retry()
            try:
                return tuple(
                    _flt.run_with_retries(
                        cfn, tuple(partials),
                        attempts=attempts - 1,
                        what=f"{kind} combine", verb=kind,
                    )
                )
            except Exception as second:
                raise first from second


def _assoc_reduce(graph, fetch_list, summary) -> bool:
    """True when re-feeding partials through ``graph`` is an associative
    monoid combine (sum/min/max/prod consuming its placeholder
    DIRECTLY) — the class whose partials may fold hierarchically. Mean
    and transform-then-reduce graphs re-weight/re-apply under nesting
    (the same gate `reduce_blocks_stream` uses before tree-folding)."""
    from .aggregate import _chunk_combiners

    comb = _chunk_combiners(graph, fetch_list, summary, require_direct=True)
    return comb is not None and "mean" not in comb.values()


def _combine_partials_scheduled(
    ex, kind, graph, fetch_list, feed_names, build, partials, owners,
    sched, assoc,
):
    """Combine per-block partials under the block scheduler.

    ``assoc`` graphs (see `_assoc_reduce`) fold each device's partials
    LOCALLY first, then one final cross-device combine over the
    per-device results on the anchor device — transfer volume O(ndev)
    instead of O(blocks), and every step is an async device op (host
    syncs do not grow). Results are bit-identical for min/max under any
    grouping; float sum stays within the documented reassociation
    tolerance. Non-associative graphs (mean, transform-then-reduce,
    unclassified — and reduce_rows folds, whose left-fold-in-block-order
    contract admits no regrouping) gather ALL partials onto the anchor
    (async D2D) and run the single combine in block order, bit-identical
    to the unscheduled verb."""
    groups: Dict[int, List[Tuple]] = {}
    for p, o in zip(partials, owners):
        groups.setdefault(o, []).append(p)
    anchor = sched.anchor_device()
    if assoc and len(groups) > 1:
        stage: List[Tuple] = []
        for slot in sorted(groups):
            parts = groups[slot]
            stage.append(
                parts[0]
                if len(parts) == 1
                else _combine_partials(
                    ex, kind, graph, fetch_list, feed_names, build, parts
                )
            )
        moved = [
            tuple(jax.device_put(x, anchor) for x in p) for p in stage
        ]
        return _combine_partials(
            ex, kind, graph, fetch_list, feed_names, build, moved
        )
    # gather unconditionally, not only when owners span several slots: a
    # reduce_rows single-row partial is a column SLICE whose actual
    # device is the column's home, not its nominal slot, so owners alone
    # cannot prove colocation (device_put to the current device is free)
    partials = [
        tuple(jax.device_put(x, anchor) for x in p) for p in partials
    ]
    return _combine_partials(
        ex, kind, graph, fetch_list, feed_names, build, partials
    )


def _colocate_parts(parts: List, anchor=None) -> List:
    """Move parts spanning several devices onto one anchor device so a
    single jnp op can consume them (jax refuses committed arrays from
    different devices in one computation). The block scheduler's map
    outputs and stream partials hit this; everything is `device_put`
    (async D2D/H2D) — no host sync.

    ``anchor`` (a jax device) is the scheduler's anchor: scheduled verbs
    MUST pass it so every call over the same device set commits its
    output to the SAME device — per-call anchors (e.g. most-rows) would
    leave one frame's columns committed to different devices, and any
    later dispatch feeding two such columns into one jit call (the
    segment-plan aggregate, or any verb after turning the scheduler
    off) would crash on jax's incompatible-devices check. (Chaining
    verbs with *different* explicit ``devices=`` pins still produces
    mixed commitments — that is the user's deliberate placement, see
    ARCHITECTURE.md "Output coherence".) Without an anchor (unscheduled
    callers over user-mixed inputs), the device already holding the
    most rows wins (first seen breaks ties), minimizing transfer."""
    weight: Dict = {}
    devs: List = []
    for p in parts:
        d = None
        if isinstance(p, jax.Array):
            try:
                ds = p.devices()
                d = next(iter(ds)) if len(ds) == 1 else None
            except Exception:
                d = None
        devs.append(d)
        if d is not None:
            rows = p.shape[0] if getattr(p, "ndim", 0) else 1
            weight[d] = weight.get(d, 0) + rows
    if len(weight) <= 1:
        return list(parts)
    if anchor is None:
        anchor = max(weight.items(), key=lambda kv: kv[1])[0]
    return [
        p if d is anchor else jax.device_put(p, anchor)
        for p, d in zip(parts, devs)
    ]


def _concat_parts(parts: List, anchor=None) -> "np.ndarray":
    """Concatenate block outputs, staying on device when the parts are
    device arrays (no host round-trip for device-resident frames;
    cross-device parts converge via `_colocate_parts` first — scheduled
    callers pass their schedule's anchor device)."""
    if len(parts) == 1:
        return parts[0]
    if any(isinstance(p, jax.Array) for p in parts):
        import jax.numpy as jnp

        return jnp.concatenate(
            [jnp.asarray(p) for p in _colocate_parts(parts, anchor)]
        )
    return np.concatenate(parts)


def _stack_parts(parts: List, anchor=None) -> "np.ndarray":
    """Stack partials: on device when any is a `jax.Array` (cross-device
    partials converge via `_colocate_parts` first), else with host
    numpy. The host branch matters beyond convenience — for
    native-executor partials (host numpy), a `jnp.stack` would
    initialize the in-process JAX backend next to a native host that
    may own the same device (the double-client hazard `NativeExecutor`
    documents)."""
    if any(isinstance(p, jax.Array) for p in parts):
        import jax.numpy as jnp

        return jnp.stack(
            [jnp.asarray(p) for p in _colocate_parts(parts, anchor)]
        )
    return np.stack([np.asarray(p) for p in parts])


def _empty_output(summary: GraphSummary, base: str, drop_lead: bool) -> np.ndarray:
    """Zero-row array for a graph output over an all-empty frame.

    Closes the reference's standing empty-partition TODO
    (`DebugRowOps.scala:386-387,496,520`): unknown trailing dims collapse
    to 0 (there are no rows to disagree with) and the dtype comes from the
    graph analysis rather than defaulting to float64."""
    info = summary.outputs[base]
    dims = info.shape.dims[1:] if drop_lead else info.shape.dims
    shape = (0,) + tuple(0 if d is None else d for d in dims)
    return np.zeros(shape, dtype=info.dtype.np_dtype)


# _empty_fn_outputs lives in fn_frontend.py (re-exported below)


def _output_frame(
    frame: TensorFrame,
    out_cols: List[Column],
    append_input: bool,
    offsets: Optional[List[int]] = None,
) -> TensorFrame:
    """TF output columns first, sorted by name, then passthrough input
    columns (`DebugRowOps.scala:355,375-379`). On a name collision the graph
    output wins (the frame analogue of SQL duplicate columns)."""
    out_cols = sorted(out_cols, key=lambda c: c.name)
    cols = list(out_cols)
    if append_input:
        shadow = {c.name for c in out_cols}
        cols += [frame.column(n) for n in frame.columns if n not in shadow]
    return TensorFrame(cols, offsets if offsets is not None else frame.offsets)


# ---------------------------------------------------------------------------
# function front-end: trace a Python fn over named column arrays
# ---------------------------------------------------------------------------


# _fn_feed_columns/_fn_outputs_to_dict live in fn_frontend.py


# ---------------------------------------------------------------------------
# bytes/string cells: identity pass-through (the reference's Binary scope)
# ---------------------------------------------------------------------------


def _split_string_passthrough(
    graph: Graph, fetch_list: List[str]
) -> Tuple[Graph, List[str], Dict[str, str]]:
    """Partition fetches into device fetches and bytes pass-throughs.

    The reference supports Binary cells at exactly one scope: a single
    scalar cell carried through the conversion path, never computed on
    (`datatypes.scala:577-581`). Mirrored here: a fetch whose node is an
    Identity-chain over a string placeholder becomes a host-side cell
    copy; any fetch that COMPUTES on string data raises. Returns the
    device-only subgraph, the device fetches, and
    ``{fetch base -> string placeholder name}``.
    """
    from .schema import ScalarType

    str_phs = {
        ph.name
        for ph in graph.placeholders()
        if ph.dtype_attr is ScalarType.string
    }
    if not str_phs:
        return graph, fetch_list, {}
    passthrough: Dict[str, str] = {}
    device_fetches: List[str] = []
    for f in fetch_list:
        cur = _base(f)
        ph = None
        while True:
            node = graph[cur]
            if node.op in ("Placeholder", "PlaceholderV2"):
                ph = node.name if node.name in str_phs else None
                break
            if node.op in ("Identity", "Snapshot", "StopGradient"):
                cur = node.data_inputs()[0][0]
                continue
            break
        if ph is not None:
            passthrough[_base(f)] = ph
        else:
            device_fetches.append(f)
    if device_fetches:
        keep = {n.name for n in graph.toposort(device_fetches)}
        touched = keep & str_phs
        if touched:
            raise ValueError(
                f"fetches {sorted(_base(f) for f in device_fetches)} compute "
                f"on bytes-column data (via {sorted(touched)}); bytes cells "
                "support identity pass-through only (the reference's "
                "one-scalar-cell Binary scope, datatypes.scala:577-581)"
            )
        dev_graph = Graph([n for n in graph.nodes if n.name in keep])
    else:
        dev_graph = Graph([])
    return dev_graph, device_fetches, passthrough


def _string_passthrough_columns(
    passthrough: Dict[str, str],
    frame: TensorFrame,
    feed_dict: Optional[Dict[str, str]],
) -> List[Column]:
    """Resolve + validate the bytes columns and copy their cells."""
    from .schema import ScalarType

    feed_dict = feed_dict or {}
    cols = []
    for base, ph in passthrough.items():
        col_name = feed_dict.get(ph, _default_column(ph, frame))
        if col_name not in frame.info:
            raise ValueError(
                f"placeholder {ph!r} wants column {col_name!r} which is not "
                f"in the frame (columns: {frame.columns})"
            )
        info = frame.info[col_name]
        if info.dtype is not ScalarType.string:
            raise ValueError(
                f"placeholder {ph!r} is a bytes placeholder but column "
                f"{col_name!r} has dtype {info.dtype.name}"
            )
        if info.cell_shape.rank != 0:
            raise ValueError(
                f"bytes column {col_name!r} must hold one scalar cell per "
                "row (the reference's Binary scope, datatypes.scala:577-581)"
            )
        cols.append(
            Column(base, list(frame.column(col_name).rows()), ScalarType.string)
        )
    return cols


# ---------------------------------------------------------------------------
# map_blocks
# ---------------------------------------------------------------------------


@_pandas_in_out
@_deadline_entry("map_blocks")
def map_blocks(
    fetches: Fetches,
    frame: TensorFrame,
    feed_dict: Optional[Dict[str, str]] = None,
    trim: bool = False,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    mesh=None,
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
    devices=None,
) -> TensorFrame:
    """Apply a graph to each block; one jitted XLA call per block.

    `DebugRowOps.mapBlocks` (`DebugRowOps.scala:290-400`). With
    ``trim=True`` the row count may change and input columns are dropped
    (`Operations.scala:59-76`). With ``mesh=`` the blocks shard across the
    device mesh (see `parallel.verbs`). ``bindings`` feeds named
    placeholders a per-call array instead of a column — updates between
    calls do NOT recompile (see `_check_bindings`).

    Without a mesh, per-block dispatches spread across
    ``jax.local_devices()`` under the block scheduler
    (`runtime.scheduler`; ``config.block_scheduler``, default auto-on
    when >1 local device). ``devices=`` pins the dispatch to an explicit
    device list (one device = pinning); mesh= takes precedence.

    On a `LazyFrame` — or on a plain frame under ``with tfs.lazy():``
    with graph fetches (function/``trim``/``bindings`` calls stay
    eager: they cannot be spliced) — the verb DEFERS: it returns a
    `LazyFrame` carrying the chain as one pending fused graph; see
    `tensorframes_tpu.lazy`.
    """
    from .lazy import LazyFrame, lazy_active

    if isinstance(frame, LazyFrame):
        return frame.map_blocks(
            fetches, feed_dict=feed_dict, trim=trim,
            fetch_names=fetch_names, executor=executor, mesh=mesh,
            bindings=bindings, devices=devices,
        )
    from . import globalframe as _gf

    if isinstance(frame, _gf.GlobalFrame):
        # sharded-array frame: ONE SPMD dispatch over its data mesh
        # (mesh=/devices= rejected there — the frame owns placement)
        return _gf.map_blocks_global(
            fetches, frame, feed_dict=feed_dict, trim=trim,
            fetch_names=fetch_names, executor=executor, mesh=mesh,
            bindings=bindings, devices=devices,
        )
    if (
        lazy_active()
        and isinstance(frame, TensorFrame)
        and not trim
        and not bindings
        and not (callable(fetches) and not isinstance(fetches, dsl.Tensor))
    ):
        from .schema import ScalarType

        lazy_graph, lazy_fetches = _as_graph(fetches, fetch_names)
        if not any(
            ph.dtype_attr is ScalarType.string
            for ph in lazy_graph.placeholders()
        ):
            # _fuse_stage directly: the graph is already normalized
            # (functionalized + frozen), and re-running _as_graph on it
            # would pay that pass twice per deferred call
            return LazyFrame(
                frame, executor=executor, mesh=mesh, devices=devices
            )._fuse_stage(
                "map_blocks", lazy_graph, lazy_fetches, feed_dict
            )
        # bytes pass-through cannot splice: stay eager under the mode
        # (the documented contract), falling through to the graph path
    if callable(fetches) and not isinstance(fetches, dsl.Tensor):
        if mesh is not None:
            from .parallel import verbs as _pverbs

            return _pverbs.map_blocks(
                fetches, frame, mesh, feed_dict, trim, fetch_names, executor,
                bindings=bindings,
            )
        return _map_blocks_fn(
            fetches, frame, trim, executor or default_executor(),
            bindings=bindings, devices=devices,
        )
    graph, fetch_list = _as_graph(fetches, fetch_names)
    graph, fetch_list, str_pass = _split_string_passthrough(graph, fetch_list)
    if str_pass:
        # bytes columns ride host-side in every topology: split them off
        # BEFORE the mesh dispatch so mesh= behaves like the local path
        if trim:
            raise ValueError(
                "map_blocks(trim): bytes pass-through requires a "
                "row-preserving map"
            )
        str_cols = _string_passthrough_columns(str_pass, frame, feed_dict)
        if fetch_list:
            dev = map_blocks(
                graph, frame, feed_dict, False, fetch_list, executor,
                mesh=mesh, bindings=bindings, devices=devices,
            )
            dev_cols = [dev.column(_base(f)) for f in fetch_list]
        else:
            if bindings:
                # All fetches were string pass-throughs, so no compute
                # graph runs and no placeholder can consume a binding —
                # a typo'd key must not be dropped on the floor.
                raise ValueError(
                    "map_blocks: bindings "
                    f"{sorted(bindings)} match no placeholder (the "
                    "graph is pure string pass-through)"
                )
            dev_cols = []
        return _output_frame(frame, dev_cols + str_cols, append_input=True)
    if mesh is not None:
        from .parallel import verbs as _pverbs

        return _pverbs.map_blocks(
            graph, frame, mesh, feed_dict, trim, fetch_list, executor,
            bindings=bindings,
        )
    ex = executor or default_executor()
    bindings = {k: np.asarray(v) for k, v in (bindings or {}).items()}
    if not trim and not bindings:
        # block_scheduler="global": eligible row-local graphs dispatch
        # as ONE sharded SPMD program instead of one program per block
        routed = _gf.maybe_map_blocks(
            graph, fetch_list, frame, feed_dict, ex, devices
        )
        if routed is not _gf.SKIP:
            return routed
    overrides = _ph_overrides(
        graph, frame, feed_dict, block_level=True, bindings=bindings
    )
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _check_bindings(summary, bindings)
    mapping = _match_columns(
        summary, frame, feed_dict, block_level=True, bindings=bindings
    )
    _require_dense(frame, list(mapping.values()), "map_blocks")

    feed_names = sorted(summary.inputs)
    fn = ex.callable_for(graph, fetch_list, feed_names)
    # Shape bucketing (`shape_policy`): pad row-local graphs' block feeds
    # up to the bucket ladder and slice the pad rows off every output, so
    # drifting block sizes compile O(log max-rows) jit specializations of
    # this program instead of one per distinct size. trim/bindings/
    # non-rowwise graphs keep the exact per-shape dispatch.
    from . import shape_policy as _sp

    from . import config as _config

    # the row-local walk feeds bucketing AND OOM split eligibility;
    # with both knobs off it is dead weight on the hot path — skip it
    rowwise = (
        not trim
        and not bindings
        and (_sp.enabled(ex) or _config.get().oom_split_depth > 0)
        and _sp.rowwise_fetches(
            graph,
            fetch_list,
            {p: ph.shape.rank for p, ph in summary.inputs.items()},
        )
    )
    bucketed = rowwise and _sp.enabled(ex)

    from .runtime import faults as _flt
    from .runtime import scheduler as _rs
    from .utils import telemetry as _tele

    sched = _rs.schedule_for(frame, devices=devices, executor=ex)
    fscope = _flt.scope("map_blocks")
    fp = graph.fingerprint()

    def _dispatch_rows(bi: int, lo_: int, hi_: int, depth: int) -> List:
        """Dispatch rows ``[lo_, hi_)`` of block ``bi`` with classified
        fault handling (`runtime.faults`): transient errors retry with
        backoff (+ device failover under the scheduler); a RESOURCE
        error (OOM) splits the range in half down the bucket ladder
        and concatenates the halves — valid exactly for row-local
        graphs, bounded by ``config.oom_split_depth``; unclassifiable
        graphs re-raise the original error."""
        feeds = [
            bindings[n]
            if n in bindings
            else (
                frame.column(mapping[n]).values
                if (lo_ == 0 and hi_ == frame.nrows)
                else frame.column(mapping[n]).values[lo_:hi_]
            )
            for n in feed_names
        ]
        bucket = hi_ - lo_
        if bucketed:
            feeds, bucket = _sp.pad_feeds(feeds, hi_ - lo_)

        def _thunk():
            # span inside the thunk: each ATTEMPT records its own
            # dispatch span labeled with the device it actually ran on
            # (after failover the retry charges the NEW device, and
            # backoff sleeps stay outside dispatch spans)
            call = sched.bind(bi, fn) if sched is not None else fn
            with _tele.dispatch_span(
                "map_blocks.block", program=fp, block=bi, rows=hi_ - lo_,
                bucket=bucket if bucketed else None,
                device=sched.label(bi) if sched is not None else None,
            ):
                return call(*feeds)

        try:
            outs = fscope.dispatch(
                _thunk,
                what=f"map_blocks block {bi} rows [{lo_}:{hi_})",
                sched=sched, index=bi,
            )
        except Exception as e:
            if _flt.classify(e) != _flt.RESOURCE:
                raise
            if not rowwise:
                _flt.record_oom(
                    "map_blocks", fp, hi_ - lo_, depth,
                    "reraise:not-row-local", e,
                    bucket=bucket if bucketed else None,
                )
                raise
            if not _flt.split_allowed(hi_ - lo_, depth):
                _flt.record_oom(
                    "map_blocks", fp, hi_ - lo_, depth,
                    "reraise:split-depth-exhausted", e,
                    bucket=bucket if bucketed else None,
                )
                raise
            mid = (lo_ + hi_) // 2
            _flt.record_oom(
                "map_blocks", fp, hi_ - lo_, depth,
                f"split:[{lo_}:{mid})+[{mid}:{hi_})", e,
                bucket=bucket if bucketed else None,
            )
            _flt.note_split("map_blocks")
            left = _dispatch_rows(bi, lo_, mid, depth + 1)
            right = _dispatch_rows(bi, mid, hi_, depth + 1)
            return [_concat_parts([a, b]) for a, b in zip(left, right)]
        return _sp.slice_pad_rows(outs, hi_ - lo_, bucket)

    acc: Dict[str, List[np.ndarray]] = {_base(f): [] for f in fetch_list}
    out_sizes: List[int] = []
    for bi in range(frame.num_blocks):
        lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
        if lo == hi:
            out_sizes.append(0)
            continue  # empty block: contributes nothing (the reference's
            # empty-partition TODO, `DebugRowOps.scala:386-387`)
        outs = _dispatch_rows(bi, lo, hi, 0)
        maybe_check_numerics(fetch_list, outs, f"map_blocks block {bi}")
        bsize = None
        for f, o in zip(fetch_list, outs):
            # keep device arrays on device; shape checks are metadata-only
            if not trim and (o.ndim == 0 or o.shape[0] != hi - lo):
                raise ValueError(
                    f"map_blocks: output {f!r} has lead dim "
                    f"{o.shape[0] if o.ndim else '<scalar>'} but the block "
                    f"has {hi - lo} rows; use trim=True for row-count-"
                    "changing maps"
                )
            if trim:
                if o.ndim == 0:
                    raise ValueError(
                        f"map_blocks(trim): output {f!r} must have a lead dim"
                    )
                if bsize is None:
                    bsize = o.shape[0]
                elif o.shape[0] != bsize:
                    raise ValueError(
                        "map_blocks(trim): outputs disagree on row count"
                    )
            acc[_base(f)].append(o)
        out_sizes.append(bsize if trim else hi - lo)

    anchor = sched.anchor_device() if sched is not None else None
    out_cols = []
    for f in fetch_list:
        base = _base(f)
        parts = acc[base]
        data = (
            _concat_parts(parts, anchor)
            if parts
            else _empty_output(summary, base, drop_lead=True)
        )
        out_cols.append(Column(base, data))
    offsets = list(np.cumsum([0] + out_sizes)) if trim else frame.offsets
    return _output_frame(frame, out_cols, append_input=not trim, offsets=offsets)


# function front-end kernels + ragged bucketing live in
# fn_frontend.py; re-exported at the end of this module.


# ---------------------------------------------------------------------------
# map_rows
# ---------------------------------------------------------------------------


# ragged bucketing lives in fn_frontend.py (re-exported below)


@_pandas_in_out
@_deadline_entry("map_rows")
def map_rows(
    fetches: Fetches,
    frame: TensorFrame,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    mesh=None,
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
    devices=None,
) -> TensorFrame:
    """Apply a graph independently to every row.

    `DebugRowOps.mapRows` (`DebugRowOps.scala:403-484`). Dense columns take
    the vmap fast path: the per-row graph is vectorized over the block and
    runs as ONE XLA call per block — versus the reference's one session.run
    per row (`performMapRows`, `DebugRowOps.scala:826-864`). Ragged columns
    fall back to a per-row loop (compile-cached per distinct cell shape),
    the moral equivalent of the reference's variable-length row support
    (`TFDataOps.scala:90-103`). With ``mesh=`` rows shard across the
    device mesh (see `parallel.verbs.map_rows`). ``bindings`` holds
    per-call bound placeholders constant across all rows (vmap
    in_axes=None), the same jit-argument semantics as map_blocks
    bindings.
    """
    from .lazy import LazyFrame

    if isinstance(frame, LazyFrame):
        # terminal in effect: force the fused plan (one program per
        # block), then run the per-row verb on the concrete result
        frame = frame.force()
    from . import globalframe as _gf

    if isinstance(frame, _gf.GlobalFrame):
        # one vmapped SPMD dispatch over the frame's data mesh
        return _gf.map_rows_global(
            fetches, frame, feed_dict=feed_dict, fetch_names=fetch_names,
            executor=executor, mesh=mesh, bindings=bindings,
            devices=devices,
        )
    ex = executor or default_executor()
    bindings = {k: np.asarray(v) for k, v in (bindings or {}).items()}
    if callable(fetches) and not isinstance(fetches, dsl.Tensor):
        if mesh is not None:
            from .parallel import verbs as _pverbs

            return _pverbs.map_rows(
                fetches, frame, mesh, feed_dict, fetch_names, executor,
                bindings=bindings,
            )
        return _map_rows_fn(
            fetches, frame, ex, bindings=bindings, devices=devices
        )
    graph, fetch_list = _as_graph(fetches, fetch_names)
    graph, fetch_list, str_pass = _split_string_passthrough(graph, fetch_list)
    if str_pass:
        # bytes columns ride host-side in every topology: split them off
        # BEFORE the mesh dispatch so mesh= behaves like the local path
        str_cols = _string_passthrough_columns(str_pass, frame, feed_dict)
        if fetch_list:
            dev = map_rows(
                graph, frame, feed_dict, fetch_list, executor,
                mesh=mesh, bindings=bindings, devices=devices,
            )
            dev_cols = [dev.column(_base(f)) for f in fetch_list]
        else:
            if bindings:
                # Mirror the map_blocks check: pure string pass-through
                # runs no compute graph, so every binding key is a typo.
                raise ValueError(
                    "map_rows: bindings "
                    f"{sorted(bindings)} match no placeholder (the "
                    "graph is pure string pass-through)"
                )
            dev_cols = []
        return _output_frame(frame, dev_cols + str_cols, append_input=True)
    if mesh is not None:
        from .parallel import verbs as _pverbs

        return _pverbs.map_rows(
            graph, frame, mesh, feed_dict, fetch_list, executor,
            bindings=bindings,
        )
    overrides = _ph_overrides(
        graph, frame, feed_dict, block_level=False, bindings=bindings
    )
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _check_bindings(summary, bindings)
    mapping = _match_columns(
        summary, frame, feed_dict, block_level=False, bindings=bindings
    )
    params = sorted(summary.inputs)
    col_params = [p for p in params if p not in bindings]
    cols_used = [mapping[p] for p in col_params]
    out_names = [_base(f) for f in fetch_list]
    dense = all(frame.column(c).is_dense for c in cols_used)
    if bindings and not dense:
        raise ValueError(
            "map_rows: bindings are not supported with ragged feed "
            "columns; densify the columns or bake the values as constants"
        )
    if bindings and not col_params:
        raise ValueError(
            "map_rows: every placeholder is bound, so nothing varies per "
            "row; use map_blocks (or run the graph once and broadcast)"
        )

    if dense and not bindings:
        # block_scheduler="global": one vmapped SPMD dispatch instead
        # of one per block
        routed = _gf.maybe_map_rows(
            graph, fetch_list, frame, feed_dict, ex, devices,
            pre=(summary, mapping),
        )
        if routed is not _gf.SKIP:
            return routed
    if dense:
        in_axes = tuple(None if p in bindings else 0 for p in params)
        bind_sig = ",".join(sorted(bindings))
        vfn = ex.cached(
            f"vmap-rows-[{bind_sig}]" if bindings else "vmap-rows",
            graph,
            fetch_list,
            params,
            lambda: jax.jit(
                jax.vmap(
                    build_callable(graph, fetch_list, params),
                    in_axes=in_axes,
                )
            ),
        )
        # per-block dispatches spread across local devices like
        # map_blocks; outputs stay device-resident per block and
        # `_concat_parts` below concatenates ON DEVICE (colocating
        # cross-device parts), so a chained verb never pays a hidden
        # per-block D2H sync
        from . import shape_policy as _sp
        from .graph import vectorize as _vec
        from .runtime import faults as _flt
        from .runtime import scheduler as _rs
        from .utils import telemetry as _tele

        # Bucketed vmapped dispatch (`graph/vectorize.py` companion):
        # the vmapped per-row program is row-independent by
        # construction, so padding a block up the bucket ladder and
        # slicing the pad rows off is always sound — drifting block
        # sizes (and the branchy per-row graphs the vectorizer just
        # unlocked) compile O(log max-rows) specializations instead of
        # one per distinct size. Bindings keep the exact per-shape
        # dispatch (bound feeds must stay whole).
        bucketed = not bindings and _sp.enabled(ex) and _vec.enabled()

        sched = _rs.schedule_for(frame, devices=devices, executor=ex)
        fscope = _flt.scope("map_rows")
        fp = graph.fingerprint()

        def _dispatch_rows(bi: int, lo_: int, hi_: int, depth: int):
            # classified faults: transient retries (+ failover under the
            # scheduler); OOM splits the row range in half — always
            # valid here, the vmapped per-row program is row-independent
            # by construction (bound placeholders stay whole)
            feeds = [
                bindings[p]
                if p in bindings
                else frame.column(mapping[p]).values[lo_:hi_]
                for p in params
            ]
            bucket = hi_ - lo_
            if bucketed:
                feeds, bucket = _sp.pad_feeds(feeds, hi_ - lo_)

            def _thunk():
                # per-attempt span (see map_blocks._dispatch_rows)
                call = sched.bind(bi, vfn) if sched is not None else vfn
                with _tele.dispatch_span(
                    "map_rows.block", program=fp, block=bi,
                    rows=hi_ - lo_,
                    bucket=bucket if bucketed else None,
                    device=sched.label(bi) if sched is not None else None,
                ):
                    return call(*feeds)

            try:
                outs_ = _thunk_outs(_thunk, bi, lo_, hi_)
                return _sp.slice_pad_rows(outs_, hi_ - lo_, bucket)
            except Exception as e:
                if _flt.classify(e) != _flt.RESOURCE:
                    raise
                if not _flt.split_allowed(hi_ - lo_, depth):
                    _flt.record_oom(
                        "map_rows", fp, hi_ - lo_, depth,
                        "reraise:split-depth-exhausted", e,
                    )
                    raise
                mid = (lo_ + hi_) // 2
                _flt.record_oom(
                    "map_rows", fp, hi_ - lo_, depth,
                    f"split:[{lo_}:{mid})+[{mid}:{hi_})", e,
                )
                _flt.note_split("map_rows")
                left = _dispatch_rows(bi, lo_, mid, depth + 1)
                right = _dispatch_rows(bi, mid, hi_, depth + 1)
                return [
                    _concat_parts([a, b]) for a, b in zip(left, right)
                ]

        def _thunk_outs(thunk, bi, lo_, hi_):
            return fscope.dispatch(
                thunk,
                what=f"map_rows block {bi} rows [{lo_}:{hi_})",
                sched=sched, index=bi,
            )

        acc: Dict[str, List[np.ndarray]] = {n: [] for n in out_names}
        for bi in range(frame.num_blocks):
            lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
            if lo == hi:
                continue
            outs = _dispatch_rows(bi, lo, hi, 0)
            maybe_check_numerics(out_names, outs, f"map_rows block {bi}")
            for n, o in zip(out_names, outs):
                acc[n].append(o)
        anchor = sched.anchor_device() if sched is not None else None
        out_cols = [
            Column(
                n,
                _concat_parts(parts, anchor)
                if parts
                else _empty_output(summary, n, drop_lead=False),
            )
            for n, parts in acc.items()
        ]
    else:
        vfn = ex.cached(
            "vmap-rows",
            graph,
            fetch_list,
            params,
            lambda: jax.jit(
                jax.vmap(build_callable(graph, fetch_list, params))
            ),
        )
        per_out = _run_ragged_bucketed(
            vfn,
            [frame.column(c) for c in cols_used],
            frame.nrows,
            out_names_hint=out_names,
        )
        out_cols = [
            Column(
                n,
                per_out[n]
                if n in per_out
                else _empty_output(summary, n, drop_lead=False),
            )
            for n in out_names
        ]

    return _output_frame(frame, out_cols, append_input=True)


# _map_rows_fn lives in fn_frontend.py (re-exported below)


# ---------------------------------------------------------------------------
# reduce_blocks
# ---------------------------------------------------------------------------


def _validate_reduce_blocks(
    summary: GraphSummary, fetch_list: List[str]
) -> None:
    """`reduceBlocksSchema` naming + shape contract
    (`DebugRowOps.scala:80-170`): output ``x`` ↔ placeholder ``x_input``,
    same dtype, placeholder = output shape + unknown lead dim."""
    allowed = {_base(f) + "_input" for f in fetch_list}
    extra = set(summary.inputs) - allowed
    if extra:
        raise ValueError(
            f"reduce_blocks: placeholders {sorted(extra)} do not follow the "
            f"x -> x_input convention for outputs {sorted(allowed)} "
            "(every input must be re-fed a partial during the combine step)"
        )
    for f in fetch_list:
        base = _base(f)
        ph_name = base + "_input"
        if ph_name not in summary.inputs:
            raise ValueError(
                f"reduce_blocks: output {base!r} requires a placeholder "
                f"named {ph_name!r} (inputs: {sorted(summary.inputs)})"
            )
        ph = summary.inputs[ph_name]
        out = summary.outputs[base]
        if ph.dtype is not out.dtype:
            raise ValueError(
                f"reduce_blocks: {base!r} has dtype {out.dtype.name} but "
                f"{ph_name!r} has dtype {ph.dtype.name}"
            )
        if ph.shape.rank != out.shape.rank + 1:
            raise ValueError(
                f"reduce_blocks: placeholder {ph_name!r} (shape {ph.shape}) "
                f"must be output {base!r} (shape {out.shape}) plus a lead "
                "block dim"
            )
        if not out.shape.check_more_precise_than(ph.shape.tail):
            raise ValueError(
                f"reduce_blocks: output {base!r} shape {out.shape} does not "
                f"match placeholder cell shape {ph.shape.tail}; partials "
                "must be re-feedable for the combine step"
            )


@_pandas_in_out
@_deadline_entry("reduce_blocks")
def reduce_blocks(
    fetches: Fetches,
    frame: TensorFrame,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    mesh=None,
    devices=None,
):
    """Per-block reduce, then one on-device combine over stacked partials.

    `DebugRowOps.reduceBlocks` (`DebugRowOps.scala:510-533`). The reference
    funnels partials to the driver and merges PAIRWISE, each pair a fresh
    session on a 2-row block (`reducePairBlock`, `:748-757`); since the
    contract already demands associativity (Spark `RDD.reduce`), we stack
    all partials into one (num_blocks)-row block and run the same graph
    once. Returns a single array for one fetch, a dict for several
    (`_unpack_row`, `core.py:111-125`).

    Execution is fully async and device-resident: all block dispatches
    are issued before anything is fetched, partials stay in device
    memory, and the combine donates their buffers. The result is a
    device array (`jax.Array` on the in-process executor) — apply
    ``np.asarray`` (or keep chaining) at the boundary you choose.

    On a `LazyFrame` this is a TERMINAL action: the reduce's per-block
    stage fuses into the pending map chain and the whole pipeline runs
    as ONE program per block (see `tensorframes_tpu.lazy`).
    """
    from .lazy import LazyFrame

    if isinstance(frame, LazyFrame):
        return frame.reduce_blocks(
            fetches, feed_dict, fetch_names, executor, mesh, devices=devices
        )
    from . import globalframe as _gf

    if isinstance(frame, _gf.GlobalFrame):
        # ONE masked SPMD dispatch; classified reductions lower to
        # in-program collectives over the frame's data mesh
        return _gf.reduce_blocks_global(
            fetches, frame, feed_dict=feed_dict, fetch_names=fetch_names,
            executor=executor, mesh=mesh, devices=devices,
        )
    if mesh is not None:
        from .parallel import verbs as _pverbs

        return _pverbs.reduce_blocks(
            fetches, frame, mesh, feed_dict, fetch_names, executor
        )
    ex = executor or default_executor()
    graph, fetch_list = _as_graph(fetches, fetch_names)
    # block_scheduler="global": classified monoid reduces dispatch as
    # one sharded program with in-program collectives
    routed = _gf.maybe_reduce_blocks(
        graph, fetch_list, frame, feed_dict, ex, devices
    )
    if routed is not _gf.SKIP:
        return routed
    overrides = _ph_overrides(graph, frame, feed_dict, block_level=True)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _validate_reduce_blocks(summary, fetch_list)
    mapping = _match_columns(summary, frame, feed_dict, block_level=True)
    _require_dense(frame, list(mapping.values()), "reduce_blocks")

    feed_names = sorted(summary.inputs)
    # Shape bucketing: graphs the chunk classifier proves to be monoid
    # reduces over row-local transforms run a MASKED bucketed program
    # ("block-bucketed" kind) — block feeds pad to the bucket ladder and
    # pad rows mask to the reduction identity at the transform output,
    # so drifting block sizes compile O(log max-rows) programs. The
    # `valid` row count rides as a traced scalar (no respecialization
    # within a bucket). Unclassifiable graphs keep the exact program.
    from . import shape_policy as _sp

    # one classification serves the masked bucketed program AND the
    # OOM split-retry combine recipe (`faults.combine_split_partials`):
    # the mask plan already carries the fetch-ordered combiner verdicts,
    # so the walk runs at most once per call — and not at all when both
    # bucketing and splitting are off. split_combs=None means a
    # resource failure re-raises exactly instead of splitting.
    from . import config as _config

    mask_plan = (
        _sp.masked_reduce_plan(graph, fetch_list, summary)
        if _sp.enabled(ex)
        else None
    )
    if mask_plan is not None:
        split_combs = list(mask_plan.combiners)
    elif _config.get().oom_split_depth > 0:
        classified = _chunk_combiners(graph, fetch_list, summary)
        split_combs = (
            [classified[_base(f)] for f in fetch_list]
            if classified is not None
            else None
        )
    else:
        split_combs = None
    if mask_plan is not None:
        fn = _sp.masked_callable(ex, graph, fetch_list, feed_names, mask_plan)
    else:
        fn = ex.callable_for(graph, fetch_list, feed_names)
    # feed_src[j] = fetch whose partial re-feeds feed_names[j] (fetch
    # order and sorted-feed order differ with several fetches)
    fetch_of_feed = {_base(f) + "_input": i for i, f in enumerate(fetch_list)}
    feed_src = [fetch_of_feed[n] for n in feed_names]

    # Dispatch EVERY block before fetching anything: each fn call is an
    # async dispatch whose partial stays in device memory, so B blocks
    # queue back-to-back instead of serializing on a per-block
    # device->host copy (the per-task sync the reference paid in
    # `DataOps.scala:63-81`). maybe_check_numerics is a no-op unless the
    # debug mode is on, in which case it deliberately syncs per block to
    # name the offender.
    from .runtime import faults as _flt
    from .runtime import scheduler as _rs

    sched = _rs.schedule_for(frame, devices=devices, executor=ex)
    fscope = _flt.scope("reduce_blocks")
    fp = graph.fingerprint()
    partials: List[Tuple] = []
    owners: List[int] = []  # device slot per partial (scheduled runs)
    for bi in range(frame.num_blocks):
        lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
        if lo == hi:
            # zero-row blocks (repartition(num_blocks > nrows)) are never
            # dispatched: a padded all-pad block would contribute the bare
            # reduction identity (e.g. +inf for Min) and poison the combine
            continue
        outs = _dispatch_reduce_block(
            "reduce_blocks.block", fp, fn, mask_plan, sched, fscope,
            bi, lo, hi,
            lambda lo_, hi_: [
                frame.column(mapping[n]).values[lo_:hi_]
                for n in feed_names
            ],
            split_combs, "reduce_blocks",
        )
        maybe_check_numerics(fetch_list, outs, f"reduce_blocks block {bi}")
        partials.append(tuple(outs))
        owners.append(sched.slot(bi) if sched is not None else 0)
    if not partials:
        raise ValueError("reduce_blocks on an empty frame")
    if len(partials) == 1:
        final = partials[0]
    else:
        def build_block_combine():
            import jax.numpy as jnp

            raw = build_callable(graph, fetch_list, feed_names)

            def combine(parts):
                stacked = [
                    jnp.stack([p[i] for p in parts]) for i in feed_src
                ]
                return raw(*stacked)

            return combine

        if sched is not None:
            final = _combine_partials_scheduled(
                ex, "reduce-combine", graph, fetch_list, feed_names,
                build_block_combine, partials, owners, sched,
                assoc=_assoc_reduce(graph, fetch_list, summary),
            )
        else:
            final = _combine_partials(
                ex, "reduce-combine", graph, fetch_list, feed_names,
                build_block_combine, partials,
            )
    if len(fetch_list) == 1:
        return final[0]
    return {_base(f): v for f, v in zip(fetch_list, final)}


# Streaming reduce lives in streaming.py; re-exported here so the
# public surface (and api._prefetch_iter-style internal references)
# are unchanged. Import is at the END of this module (late-bound).


# ---------------------------------------------------------------------------
# reduce_rows
# ---------------------------------------------------------------------------


def _validate_reduce_rows(summary: GraphSummary, fetch_list: List[str]) -> None:
    """`reduceRowsSchema` (`DebugRowOps.scala:172-262`): output ``x`` ↔
    placeholders ``x_1``/``x_2``, all three the same dtype and cell shape."""
    allowed = {_base(f) + s for f in fetch_list for s in ("_1", "_2")}
    extra = set(summary.inputs) - allowed
    if extra:
        raise ValueError(
            f"reduce_rows: placeholders {sorted(extra)} do not follow the "
            "x -> x_1/x_2 convention"
        )
    for f in fetch_list:
        base = _base(f)
        for suf in ("_1", "_2"):
            if base + suf not in summary.inputs:
                raise ValueError(
                    f"reduce_rows: output {base!r} requires placeholders "
                    f"{base}_1 and {base}_2 (inputs: {sorted(summary.inputs)})"
                )
        p1, p2 = summary.inputs[base + "_1"], summary.inputs[base + "_2"]
        out = summary.outputs[base]
        if not (p1.dtype is p2.dtype is out.dtype):
            raise ValueError(f"reduce_rows: dtype mismatch around {base!r}")
        if not (
            out.shape.check_more_precise_than(p1.shape)
            and out.shape.check_more_precise_than(p2.shape)
        ):
            raise ValueError(
                f"reduce_rows: shapes around {base!r} must all agree "
                f"(out {out.shape}, {base}_1 {p1.shape}, {base}_2 {p2.shape})"
            )


@_pandas_in_out
@_deadline_entry("reduce_rows")
def reduce_rows(
    fetches: Fetches,
    frame: TensorFrame,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    mesh=None,
    devices=None,
):
    """Pairwise fold over all rows.

    `DebugRowOps.reduceRows` (`DebugRowOps.scala:486-508`): the reference
    folds each partition sequentially with one session.run PER ROW PAIR
    (`performReducePairwise`, `:939-979`). Here the pair graph is rolled
    into a `lax.scan` and the whole per-block fold is ONE XLA call; block
    partials then fold the same way. Fold order matches the reference
    (left fold in row order), so non-associative graphs agree too.

    On a `LazyFrame` this is a terminal action: the fused plan is
    forced first (one program per block), then the fold runs on the
    device-resident result.
    """
    from .lazy import LazyFrame

    if isinstance(frame, LazyFrame):
        frame = frame.force()
    from . import globalframe as _gf

    if isinstance(frame, _gf.GlobalFrame):
        # a left fold in row order is inherently sequential: cross the
        # local boundary (one block) and fold there — but the frame
        # still owns its placement, so per-call overrides stay loud
        _gf._reject_overrides("reduce_rows", mesh, devices)
        frame = frame.to_frame()
    if mesh is not None:
        from .parallel import verbs as _pverbs

        return _pverbs.reduce_rows(
            fetches, frame, mesh, feed_dict, fetch_names, executor
        )
    ex = executor or default_executor()
    graph, fetch_list = _as_graph(fetches, fetch_names)
    overrides = _ph_overrides(graph, frame, feed_dict, block_level=False)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _validate_reduce_rows(summary, fetch_list)
    mapping = _match_columns(summary, frame, feed_dict, block_level=False)
    _require_dense(frame, list(mapping.values()), "reduce_rows")

    bases = [_base(f) for f in fetch_list]
    for b in bases:
        c1, c2 = mapping[b + "_1"], mapping[b + "_2"]
        if c1 != c2:
            raise ValueError(
                f"reduce_rows: {b}_1 reads column {c1!r} but {b}_2 reads "
                f"{c2!r}; a fold's carry and next-row must come from the "
                "same column"
            )
    feed_names = [b + s for b in bases for s in ("_1", "_2")]

    def fold_body():
        pair = build_callable(graph, fetch_list, feed_names)

        def fold(cols: Dict[str, "jax.Array"]):
            carry0 = tuple(cols[b][0] for b in bases)
            xs = tuple(cols[b][1:] for b in bases)

            def step(carry, xrow):
                feeds = []
                for i, _ in enumerate(bases):
                    feeds.extend((carry[i], xrow[i]))
                return tuple(pair(*feeds)), None

            carry, _ = lax.scan(step, carry0, xs)
            return carry

        return fold

    jfold = ex.cached(
        "fold", graph, fetch_list, feed_names, lambda: jax.jit(fold_body())
    )
    # async dispatch, device-resident partials: same discipline as
    # reduce_blocks — every block's fold is in flight before anything
    # is combined, and nothing is host-fetched on this path at all.
    # Scheduled runs spread the per-block folds across devices; the
    # FINAL combine always gathers every partial onto the anchor device
    # and folds them in block order (never hierarchically): the verb's
    # contract is a left fold in row order, which non-associative
    # graphs rely on — regrouping by device would break it.
    from .runtime import scheduler as _rs
    from .utils import telemetry as _tele

    # single-row blocks never dispatch (their partial is a bare column
    # slice), so they carry zero planning weight — otherwise their slot's
    # queue-depth ledger would count a dispatch that never drains
    sched = _rs.schedule_weights(
        [0 if s == 1 else s for s in frame.block_sizes()],
        devices=devices, executor=ex,
    )
    from .runtime import faults as _flt

    # classified transient retry + failover only: the verb's contract is
    # a LEFT FOLD in row order, so a resource failure cannot split the
    # block (regrouping would change non-associative results) — OOM
    # surfaces exactly
    fscope = _flt.scope("reduce_rows")
    fp = graph.fingerprint()
    partials: List[Tuple] = []
    owners: List[int] = []
    for bi in range(frame.num_blocks):
        lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
        if lo == hi:
            continue
        cols = {b: frame.column(mapping[b + "_1"]).values[lo:hi] for b in bases}
        if hi - lo == 1:
            partials.append(tuple(cols[b][0] for b in bases))
            owners.append(0)
        else:
            def _thunk(cols0=cols, bi=bi):
                # per-attempt span + per-attempt device_put: a failover
                # retry puts onto (and its span charges) the re-placed
                # device
                with _tele.dispatch_span(
                    "reduce_rows.block", program=fp, block=bi,
                    rows=hi - lo,
                    device=sched.label(bi) if sched is not None else None,
                ):
                    c = cols0
                    if sched is not None:
                        # dict feeds: device_put the values, keep keys
                        keys = list(c)
                        c = dict(
                            zip(keys, sched.put(bi, [c[k] for k in keys]))
                        )
                    return jfold(c)

            outs = fscope.dispatch(
                _thunk, what=f"reduce_rows block {bi}",
                sched=sched, index=bi,
            )
            maybe_check_numerics(bases, outs, f"reduce_rows block {bi}")
            partials.append(tuple(outs))
            owners.append(sched.slot(bi) if sched is not None else 0)
    if not partials:
        raise ValueError("reduce_rows on an empty frame")
    if len(partials) == 1:
        final = partials[0]
    else:
        def build_fold_combine():
            import jax.numpy as jnp

            fold = fold_body()

            def combine(parts):
                cols = {
                    b: jnp.stack([p[i] for p in parts])
                    for i, b in enumerate(bases)
                }
                return fold(cols)

            return combine

        if sched is not None:
            final = _combine_partials_scheduled(
                ex, "fold-combine", graph, fetch_list, feed_names,
                build_fold_combine, partials, owners, sched, assoc=False,
            )
        else:
            final = _combine_partials(
                ex, "fold-combine", graph, fetch_list, feed_names,
                build_fold_combine, partials,
            )
    if len(bases) == 1:
        return final[0]
    return dict(zip(bases, final))


# ---------------------------------------------------------------------------
# aggregate (keyed)
# ---------------------------------------------------------------------------


class GroupedFrame:
    """`frame.group_by(keys)` — the RelationalGroupedDataset analogue."""

    def __init__(self, frame: TensorFrame, keys: Sequence[str]):
        from .lazy import LazyFrame

        if isinstance(frame, LazyFrame):
            # aggregation is a terminal action for a lazy plan: the
            # fused chain lowers as one program per block here, then
            # the keyed plans see a concrete device-resident frame
            frame = frame.force()
        from . import globalframe as _gf

        self._from_global = isinstance(frame, _gf.GlobalFrame)
        if self._from_global:
            # keyed aggregation factorizes keys on the host: cross the
            # local boundary; the segment-plan aggregate then still
            # runs one transform dispatch over the single block. The
            # flag keeps `aggregate`'s placement-override rejection
            # loud even though the frame is local from here on.
            frame = frame.to_frame()
        self.frame = frame
        self.keys = list(keys)
        for k in self.keys:
            info = frame.info[k]
            if not info.cell_shape.is_scalar:
                raise ValueError(f"group key {k!r} must be a scalar column")
            # scalar columns are always groupable: dense ones directly,
            # string/object ones via Column.host_values() — the
            # reference grouped by ANY Catalyst column type, so string
            # keys (the common case from Arrow/Spark ingest) must work


def group_by(frame: TensorFrame, *keys: str) -> GroupedFrame:
    return GroupedFrame(frame, keys)


def _agg_spec_exprs(frame: TensorFrame, specs: Dict[str, Tuple[str, str]]):
    """Lower ``out=(op, column)`` aggregation specs to the DSL reduce
    fetches + feed_dict the `aggregate` verb wants — shared by the
    eager `GroupedFrame.agg` and the relational groupby plan node (both
    lower onto the same segment/vmap/chunk plans)."""
    from .graph.plan import AGG_OPS

    fetches = []
    feed: Dict[str, str] = {}
    for out, spec in sorted(specs.items()):
        if (
            not isinstance(spec, (tuple, list)) or len(spec) != 2
            or not all(isinstance(s, str) for s in spec)
        ):
            raise TypeError(
                f"agg spec {out}={spec!r}: want a ('op', 'column') pair"
            )
        op, colname = spec
        if op not in AGG_OPS:
            raise ValueError(f"agg op {op!r} is not one of {list(AGG_OPS)}")
        ph = dsl.block(frame, colname, tf_name=f"{out}_input")
        fetches.append(getattr(dsl, f"reduce_{op}")(ph, axes=[0]).named(out))
        feed[f"{out}_input"] = colname
    return fetches, feed


def scan(source, format: str = "auto", columns=None, chunk_groups: int = 1):
    """Lazily scan an on-disk dataset (parquet / arrow IPC) as a
    `RelationalFrame` — the relational plan's ingest leaf. Composes
    with `filter` / `select` / `map_blocks` / `group_by(...).agg(...)`;
    the plan optimizer pushes predicates and the pruned column set INTO
    the decode pipeline (skipping whole parquet row groups from footer
    stats), so a selective plan decodes the rows that survive, not the
    whole dataset. ``source`` is a path / path list / `ingest.Dataset`."""
    from .graph import plan as _plan
    from .ingest import Dataset
    from .lazy import RelationalFrame

    ds = (
        source
        if isinstance(source, Dataset)
        else Dataset(source, format=format, chunk_groups=chunk_groups)
    )
    payload: Dict[str, object] = {"dataset": ds}
    if columns is not None:
        payload["columns"] = tuple(columns)
    return RelationalFrame(_plan.PlanNode("scan", (), payload))


# The three aggregation plans live in aggregate.py (segment ops /
# exact per-size vmap / pow2-chunk monoid combine); re-exported below
# so parallel/verbs.py and parallel/multihost.py keep resolving them
# through this module.
from .aggregate import (  # noqa: E402
    _aggregate_chunked,
    _aggregate_segment,
    _chunk_combiners,
    _gid_dtype,
    _group_plan,
    _keyed_output,
    _monoid_combine,
)


@_deadline_entry("aggregate")
def aggregate(
    fetches: Fetches,
    grouped: GroupedFrame,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    mesh=None,
    devices=None,
) -> TensorFrame:
    """Keyed aggregation with reduce_blocks naming conventions.

    `DebugRowOps.aggregate` (`DebugRowOps.scala:554-599`). The reference
    buffers up to 10 rows per group in a Catalyst UDAF and repeatedly
    compacts with a fresh TF session (`TensorFlowUDAF`, `:608-702`). Here
    rows are sorted by key once, and groups OF THE SAME SIZE are stacked
    and vmapped — one XLA call per distinct group size, each batched over
    all groups of that size.
    """
    if getattr(grouped, "_from_global", False):
        from . import globalframe as _gf

        _gf._reject_overrides("aggregate", mesh, devices)
    if mesh is not None:
        from .parallel import verbs as _pverbs

        return _pverbs.aggregate(
            fetches, grouped, mesh, feed_dict, fetch_names, executor
        )
    ex = executor or default_executor()
    frame = grouped.frame
    graph, fetch_list = _as_graph(fetches, fetch_names)
    overrides = _ph_overrides(graph, frame, feed_dict, block_level=True)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _validate_reduce_blocks(summary, fetch_list)
    mapping = _match_columns(summary, frame, feed_dict, block_level=True)
    _require_dense(frame, list(mapping.values()), "aggregate")

    feed_names = sorted(summary.inputs)

    from . import config as _config

    # one structural classification serves the segment fast path AND the
    # chunked plan's eligibility check below
    classified = _chunk_combiners(graph, fetch_list, summary)
    from .utils.profiling import count as _count

    if (
        _config.get().aggregate_segment_fast
        and frame.nrows > 0
        and classified is not None
    ):
        # sort-free: one XLA call over all rows + device segment ops
        _count("aggregate.plan.segment")
        return _aggregate_segment(
            ex, graph, fetch_list, classified, feed_names, mapping, grouped,
            devices=devices,
        )

    key_out, num_groups, counts, starts, col_data = _group_plan(
        grouped, mapping, feed_names
    )
    vraw = ex.cached(
        "vmap-agg",
        graph,
        fetch_list,
        feed_names,
        lambda: jax.jit(
            jax.vmap(build_callable(graph, fetch_list, feed_names))
        ),
    )

    bases = [_base(f) for f in fetch_list]
    results: Dict[str, np.ndarray] = {}

    unique_sizes = np.unique(counts[counts > 0])
    combiners = None
    if len(unique_sizes) > _config.get().aggregate_exact_size_limit:
        # only chunk when the graph is provably chunk-safe; otherwise the
        # exact plan keeps correctness at the cost of more compiles
        combiners = classified
    _count(
        "aggregate.plan.exact" if combiners is None else "aggregate.plan.chunk"
    )
    from .utils import telemetry as _tele

    fp = graph.fingerprint()
    if combiners is None:
        # exact plan: one vmapped call per distinct size, whole groups —
        # no associativity assumption, best for regular key distributions.
        # Two phases: dispatch EVERY per-size program first (partials
        # stay as device arrays; under the block scheduler the per-size
        # programs spread across local devices, weighted by their total
        # row count), then scatter into the host result — the first
        # host fetch happens only after all sizes are in flight, so
        # per-size device work overlaps instead of serializing on each
        # size's D2H copy.
        from .runtime import scheduler as _rs

        sched = _rs.schedule_weights(
            [int(s) * int((counts == s).sum()) for s in unique_sizes],
            devices=devices, executor=ex,
        )
        from .runtime import faults as _flt

        fscope = _flt.scope("aggregate")
        pending: List[Tuple[np.ndarray, Tuple]] = []
        with _tele.span("aggregate.plan.exact", kind="stage", program=fp):
            for si, size in enumerate(unique_sizes):
                gids = np.nonzero(counts == size)[0]
                row_idx = starts[gids][:, None] + np.arange(size)[None, :]
                feeds = [col_data[n][row_idx] for n in feed_names]  # (g, size, *cell)

                def _thunk(si=si, size=size, gids=gids, feeds=feeds):
                    # per-attempt span (see map_blocks._dispatch_rows)
                    call = (
                        sched.bind(si, vraw) if sched is not None else vraw
                    )
                    with _tele.dispatch_span(
                        "aggregate.size", program=fp,
                        rows=int(size) * len(gids), size=int(size),
                        device=sched.label(si)
                        if sched is not None
                        else None,
                    ):
                        return call(*feeds)

                outs = fscope.dispatch(
                    _thunk, what=f"aggregate groups of size {int(size)}",
                    sched=sched, index=si,
                )
                maybe_check_numerics(
                    bases, outs, f"aggregate groups of size {size}"
                )
                pending.append((gids, tuple(outs)))
        out_buffers: Dict[str, Optional[np.ndarray]] = {b: None for b in bases}
        for gids, outs in pending:
            for b, o in zip(bases, outs):
                o = np.asarray(o)
                if out_buffers[b] is None:
                    out_buffers[b] = np.zeros(
                        (num_groups,) + o.shape[1:], o.dtype
                    )
                out_buffers[b][gids] = o
        for b in bases:
            if out_buffers[b] is None:  # empty frame: zero groups
                out_buffers[b] = _empty_output(summary, b, drop_lead=False)
            results[b] = out_buffers[b]
    else:
        # pathological size distributions: pow2 chunk decomposition keeps
        # the compile count O(log max_size) instead of O(#distinct sizes)
        with _tele.span("aggregate.plan.chunk", kind="stage", program=fp):
            results.update(
                _aggregate_chunked(
                    lambda feeds: vraw(*feeds),
                    feed_names,
                    col_data,
                    counts,
                    starts,
                    num_groups,
                    bases,
                    combiners,
                    program=fp,
                    executor=ex,
                    devices=devices,
                )
            )

    return _keyed_output(key_out, results, bases)


# ---------------------------------------------------------------------------
# schema utilities
# ---------------------------------------------------------------------------


def analyze(frame: TensorFrame) -> TensorFrame:
    """Scan the data and refine column shapes (`ExperimentalOperations.analyze`)."""
    return frame.analyze()


def print_schema(frame: TensorFrame) -> None:
    """`tfs.print_schema` (`core.py:355-364`)."""
    frame.print_schema()


def append_shape(frame: TensorFrame, col: str, shape) -> TensorFrame:
    """`tfs.append_shape` (`ExperimentalOperations.scala:53-68`)."""
    if not isinstance(shape, Shape):
        shape = Shape(shape)
    return frame.append_shape(col, shape)


def explain(frame: TensorFrame) -> str:
    """`OperationsInterface.explain` (`DebugRowOps.scala:535-552`).

    For a `LazyFrame`, renders the fused plan with per-stage provenance
    (deferred verbs, feeds, pending outputs) above the schema. For a
    `RelationalFrame` (or its `LazyPlan`), renders the pre- AND
    post-optimization DAG with per-node costed estimates and every
    rewrite decision (accepted and rejected) — WITHOUT executing."""
    from .lazy import LazyFrame, LazyPlan, RelationalFrame

    if isinstance(frame, RelationalFrame):
        return frame.explain_plan()
    if isinstance(frame, LazyPlan):
        if frame.relational is not None:
            return RelationalFrame(frame.relational).explain_plan()
        return repr(frame)
    if isinstance(frame, LazyFrame):
        return frame.explain_plan()
    return frame.info.explain()


def explain_detailed(frame: TensorFrame):
    """Structured per-column tensor metadata, the analogue of
    `ExperimentalOperations.explainDetailed` (`ExperimentalOperations.scala:27`):
    returns the `FrameInfo` itself rather than a rendered string. For a
    `LazyFrame`, returns the structured `LazyPlan` (stages, fused graph,
    column sources, feeds, virtual schema)."""
    from .lazy import LazyFrame

    if isinstance(frame, LazyFrame):
        return frame.plan()
    return frame.info


# inspection helpers live in utils/inspection.py (re-exported below)


def block_to_row(frame: TensorFrame) -> TensorFrame:
    """Convert each block to a single row, augmenting every column's rank
    by one (lead dim = block row count).

    The reference declares this operation but never implements it
    (`ExperimentalOperations.convertBlockToRow` is literally `???`,
    `ExperimentalOperations.scala:25`); here it is real. Blocks of unequal
    size produce a ragged column (lead dim Unknown), exactly like the
    reference's variable-length rows."""
    per_col_cells: Dict[str, list] = {name: [] for name in frame.columns}
    for blk in frame.blocks():
        for name in frame.columns:
            col = blk[name]
            if col.is_dense:
                per_col_cells[name].append(np.asarray(col.values))
            else:
                # ragged rows inside a block cannot stack into one cell
                raise ValueError(
                    f"block_to_row: column {name!r} is ragged; analyze/pad first"
                )
    cols = [
        Column(name, per_col_cells[name], frame[name].dtype)
        for name in frame.columns
    ]
    return TensorFrame(cols)


def block(frame: TensorFrame, col_name: str, tf_name: Optional[str] = None):
    """Block placeholder for a column (`core.py:451-474`, `tfs.block`).

    Accepts a pandas DataFrame too (the reference's local-debug path,
    `core.py:263-265`, takes pandas through the same ``tfs.*`` calls)."""
    if _is_pandas(frame):
        frame = TensorFrame.from_pandas(frame)
    return dsl.block(frame, col_name, tf_name)


def row(frame: TensorFrame, col_name: str, tf_name: Optional[str] = None):
    """Row placeholder for a column (`tfs.row`)."""
    if _is_pandas(frame):
        frame = TensorFrame.from_pandas(frame)
    return dsl.row(frame, col_name, tf_name)


# ---------------------------------------------------------------------------
# fluent methods (the reference's Scala Implicits: RichDataFrame adds
# df.mapBlocks(...)/df.mapRows/... and RichRelationalGroupedDataset adds
# .aggregate — `dsl/Implicits.scala:25-124`)
# ---------------------------------------------------------------------------


def _install_fluent_methods() -> None:
    def _map_blocks(self, fetches, **kw):
        return map_blocks(fetches, self, **kw)

    def _map_rows(self, fetches, **kw):
        return map_rows(fetches, self, **kw)

    def _reduce_blocks(self, fetches, **kw):
        return reduce_blocks(fetches, self, **kw)

    def _reduce_rows(self, fetches, **kw):
        return reduce_rows(fetches, self, **kw)

    def _group_by(self, *keys):
        return GroupedFrame(self, keys)

    _slice_block = TensorFrame.block

    def _block(self, arg, tf_name=None):
        # polymorphic like the reference's dual use: df.block(i) slices
        # block i; df.block("col") builds a placeholder for the column
        if isinstance(arg, str):
            return dsl.block(self, arg, tf_name)
        return _slice_block(self, arg)

    def _row(self, col, tf_name=None):
        return dsl.row(self, col, tf_name)

    # relational verbs: compose lazily as plan-DAG nodes (graph.plan);
    # force() runs them through the cost-based optimizer
    def _filter(self, pred, selectivity=None):
        return self.lazy().filter(pred, selectivity=selectivity)

    def _sort_by(self, *keys, descending=False):
        return self.lazy().sort_by(*keys, descending=descending)

    def _join(self, other, on, how="inner"):
        return self.lazy().join(other, on, how=how)

    TensorFrame.map_blocks = _map_blocks
    TensorFrame.map_rows = _map_rows
    TensorFrame.reduce_blocks = _reduce_blocks
    TensorFrame.reduce_rows = _reduce_rows
    TensorFrame.group_by = _group_by
    TensorFrame.block = _block
    TensorFrame.row = _row
    TensorFrame.filter = _filter
    TensorFrame.sort_by = _sort_by
    TensorFrame.join = _join

    def _agg(self, fetches, **kw):
        return aggregate(fetches, self, **kw)

    def _agg_specs(self, **specs):
        """Keyed aggregation from ``out=('op', column)`` specs (ops:
        sum / mean / min / max) — the eager sibling of the relational
        `LazyGroupedFrame.agg`; lowers onto the same segment/vmap
        aggregation plans."""
        fetches, feed = _agg_spec_exprs(self.frame, specs)
        return aggregate(fetches, self, feed_dict=feed)

    GroupedFrame.aggregate = _agg
    GroupedFrame.agg = _agg_specs


_install_fluent_methods()


# late import: streaming.py references this module's helpers at call
# time, so it must load after every definition above
from .fn_frontend import (  # noqa: E402
    _assemble_ragged,
    _empty_fn_outputs,
    _fn_feed_columns,
    _fn_outputs_to_dict,
    _map_blocks_fn,
    _map_rows_fn,
    _run_ragged_bucketed,
)
from .lazy import LazyFrame, lazy  # noqa: E402
from .streaming import _prefetch_iter, reduce_blocks_stream  # noqa: E402
from .utils.inspection import (  # noqa: E402
    _lower_for_inspection,
    cost_analysis,
    executor_stats,
    explain_hlo,
)
