"""Keyed-aggregation planner: the three execution plans behind
`api.aggregate`.

Extracted from `api.py` (round-4 verdict task 7: the three plans alone
were a module's worth). The public verb surface — `aggregate()`,
`GroupedFrame`, `group_by` — stays in `api.py`; this module holds the
planning/execution machinery:

- `_aggregate_segment`: device segment ops over factorized keys (with
  the one-hot MXU lowering for small key counts on TPU);
- the exact per-size vmap plan (`_group_plan` + batched groups);
- `_aggregate_chunked`: pow2-chunk partials + derived-monoid combine
  (`_chunk_combiners` classifies which graphs are chunk-safe).

`parallel/verbs.py` and `parallel/multihost.py` reuse the same planner
pieces for the mesh and DCN paths; `api.py` re-exports every name so
existing `api._chunk_combiners`-style references keep resolving.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .frame import Column, TensorFrame, factorize_keys
from .graph.analysis import GraphSummary
from .graph.ir import Graph, base_name as _base
from .ops.lowering import build_callable
from .runtime.faults import maybe_check_numerics


def _group_plan(
    grouped: GroupedFrame,
    mapping: Dict[str, str],
    feed_names: List[str],
):
    """Shared keyed-aggregation prologue: factorize keys, sort rows by
    group, gather sorted feed columns. Returns
    ``(key_out, num_groups, counts, starts, col_data)`` — the one copy of
    the Catalyst-shuffle analogue both the host and mesh paths use."""
    frame = grouped.frame
    key_arrays = [frame.column(k).host_values() for k in grouped.keys]
    key_out, inverse = factorize_keys(grouped.keys, key_arrays)
    num_groups = len(next(iter(key_out.values())))
    order = np.argsort(inverse, kind="stable")
    counts = np.bincount(inverse, minlength=num_groups)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    col_data = {n: frame.column(mapping[n]).values[order] for n in feed_names}
    return key_out, num_groups, counts, starts, col_data


def _keyed_output(
    key_out: Dict[str, np.ndarray],
    results: Dict[str, np.ndarray],
    bases: List[str],
) -> TensorFrame:
    """Key columns + sorted output columns (`DebugRowOps.scala:583-598`)."""
    from .schema import ScalarType

    cols = []
    for k, v in key_out.items():
        v = np.asarray(v)
        if v.size == 0 and v.dtype == object:
            # a 0-row string-keyed aggregate (empty Spark/Arrow
            # partition) must return an empty frame like the numeric
            # case, not fail Column's empty-ragged dtype check
            cols.append(Column(k, v, ScalarType.string))
        else:
            cols.append(Column(k, v))
    cols += [Column(b, results[b]) for b in sorted(bases)]
    return TensorFrame(cols)


# Reduce roots the chunked plan can combine, and their partial combiners.
_CHUNK_COMBINERS = {
    "Sum": "sum",
    "Min": "min",
    "Max": "max",
    "Prod": "prod",
    "Mean": "mean",
}

# Ops that act row-locally (each output row depends only on the matching
# input row and on sub-lead-rank constants) — safe between a placeholder
# and the root reduce under chunking.
_ROWWISE_OPS = {
    "Identity", "StopGradient", "PreventGradient", "CheckNumerics",
    "Snapshot", "Cast",
    "Abs", "Neg", "Exp", "Log", "Log1p", "Sqrt", "Rsqrt", "Square",
    "Sign", "Floor", "Ceil", "Round", "Relu", "Relu6", "Elu", "Selu",
    "Softplus", "Softsign", "Sigmoid", "Tanh", "Sin", "Cos", "Tan",
    "Erf", "Reciprocal",
    "Add", "AddV2", "Sub", "Mul", "Div", "RealDiv", "TruncateDiv",
    "FloorDiv", "Maximum", "Minimum", "Pow", "SquaredDifference", "Mod",
    "FloorMod",
    # elementwise predicates/selects: how per-row control-flow conditions
    # are authored — row-local like any other elementwise op
    "Greater", "GreaterEqual", "Less", "LessEqual", "Equal", "NotEqual",
    "LogicalAnd", "LogicalOr", "LogicalNot", "Select", "SelectV2",
}


def _rowwise_transform(graph: Graph, roots, ph_rank) -> bool:
    """THE row-local walk both classifiers share (`_chunk_combiners`
    below and `shape_policy.rowwise_fetches`): every node reachable from
    ``roots`` is a Placeholder (block rank via the ``ph_rank(name)``
    callable, None = unknown → reject), a Const, or an op in
    `_ROWWISE_OPS`; all placeholders agree on ONE lead rank; and every
    constant stays strictly below it (or has an explicit size-1 lead) —
    a lead-rank constant broadcasts along the row axis, so sliced/padded
    feeds would mismatch it. One implementation so map-bucketing
    eligibility can never silently diverge from reduce-chunk
    eligibility.

    Functionalized control flow (`_Cond`/`_While`) is deferred, not
    rejected: once the lead rank is known, `graph.vectorize` re-runs
    this walk over each branch/cond/body subgraph at that rank — a
    control node whose subgraphs are row-local lowers to a masked dense
    program (cond -> select, while -> convergence-masked fixed point)
    and is therefore row-local itself. Gated on `config.row_vectorize`;
    rejections are counted by reason for diagnostics."""
    from .graph import vectorize as _vec

    seen: set = set()
    stack = [_base(r) for r in roots]
    const_shapes: List[tuple] = []
    ranks: set = set()
    control_nodes: List = []
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        try:
            node = graph[name]
        except KeyError:
            return False
        if node.op in ("Placeholder", "PlaceholderV2"):
            r = ph_rank(name)
            if r is None:
                return False
            ranks.add(int(r))
            continue
        if node.op == "Const":
            const_shapes.append(
                tuple(node.attrs["value"].value.to_numpy().shape)
            )
            continue
        if node.op in _vec.CONTROL_OPS:
            # verdict needs the lead rank — defer until it is resolved,
            # but keep walking the node's own inputs (pred, loop vars,
            # captures must all be row-local too)
            control_nodes.append(node)
            stack.extend(src for src, _ in node.data_inputs())
            continue
        if node.op not in _ROWWISE_OPS:
            return False
        stack.extend(src for src, _ in node.data_inputs())
    if len(ranks) != 1:
        return False
    lead_rank = ranks.pop()
    for cs in const_shapes:
        if len(cs) > lead_rank or (
            len(cs) == lead_rank and cs and cs[0] != 1
        ):
            return False
    for node in control_nodes:
        if not _vec.subgraphs_row_local(graph, node, lead_rank):
            return False
    return True


def _chunk_combiners(
    graph: Graph, fetch_list: List[str], summary: GraphSummary,
    require_direct: bool = False,
) -> Optional[Dict[str, str]]:
    """Classify each fetch as ``Reduce(rowwise(placeholder), axis=0)``.

    Returns base -> combiner tag when EVERY fetch is a recognized monoid
    reduce over the lead axis of a row-local transform of its
    placeholder — the class the chunked plan computes exactly (chunk
    partials combine with the derived monoid, size-weighted for Mean).
    Returns None otherwise; callers then use the exact whole-group plan.
    Structural, so transform-then-reduce graphs like ``Sum(x*x)`` chunk
    correctly and unclassifiable graphs are never silently wrong.

    ``require_direct`` additionally demands each reduce consume its
    placeholder DIRECTLY (no transform in between) — the stricter class
    for callers that recombine partials through the same graph (e.g.
    `reduce_blocks_stream` tree-folding), where an interposed transform
    would be re-applied to the partials.
    """
    out: Dict[str, str] = {}
    for f in fetch_list:
        try:
            node = graph[_base(f)]
        except KeyError:
            return None
        if node.op not in _CHUNK_COMBINERS:
            return None
        if bool(node.attr("keep_dims", node.attr("keepdims", False))):
            return None
        if (
            node.op == "Mean"
            and not summary.outputs[_base(f)].dtype.is_floating
        ):
            # integer Mean truncates per chunk (TF semantics: div of sum
            # by count), so truncated partials cannot recombine exactly
            return None
        data_in = node.data_inputs()
        if len(data_in) != 2:
            return None
        if require_direct and graph[data_in[0][0]].op not in (
            "Placeholder", "PlaceholderV2"
        ):
            return None
        idx_node = graph[data_in[1][0]]
        if idx_node.op != "Const":
            return None
        axes = idx_node.attrs["value"].value.to_numpy().ravel().tolist()
        if axes != [0]:
            return None
        # walk the transform subgraph: placeholder/const leaves, rowwise
        # ops, one lead rank, sub-lead-rank constants (`_rowwise_transform`
        # — a lead-rank constant would broadcast along the group-size
        # axis and mismatch sliced chunk feeds)
        if not _rowwise_transform(
            graph,
            [data_in[0][0]],
            lambda name: (
                len(summary.inputs[name].shape.dims)
                if name in summary.inputs
                else None
            ),
        ):
            return None
        out[_base(f)] = _CHUNK_COMBINERS[node.op]
    return out


def _gid_dtype(num_keys: int):
    """Group-id dtype for the segment paths (host AND mesh — the mesh
    path aliases this, `parallel/verbs.py`). int32 silently wraps past
    2^31-1 DISTINCT KEYS — within 2x of the 1B+-row regime the north
    star targets — so widen to int64 at the cliff. JAX without x64 mode
    would silently downcast int64 ids back to int32, so that
    configuration is refused loudly instead."""
    if num_keys <= np.iinfo(np.int32).max:
        return np.int32
    if not jax.config.read("jax_enable_x64"):
        raise ValueError(
            f"aggregate: {num_keys} distinct keys overflows int32 group "
            "ids and jax x64 is disabled (int64 ids would be silently "
            "truncated); enable jax_enable_x64 for this key cardinality"
        )
    return np.int64


def _aggregate_segment(
    ex,
    graph: Graph,
    fetch_list: List[str],
    combiners: Dict[str, str],
    feed_names: List[str],
    mapping: Dict[str, str],
    grouped: GroupedFrame,
    devices=None,
) -> TensorFrame:
    """Sort-free keyed aggregation for classified monoid graphs.

    The rowwise transform of every fetch runs over ALL rows in one XLA
    call, then one device ``segment_<op>`` per fetch produces the dense
    (num_groups, *cell) result — no host argsort, no per-size or chunk
    programs. This is the single-device analogue of the mesh path's
    segment_sum+psum (`parallel/verbs.py`), generalized to min/max/prod
    and size-weighted mean via the same structural classifier. FP
    accumulation order differs from the whole-group exact plan (the
    documented reassociation tolerance for reductions; the reference's
    own driver-side pairwise combine reassociated too,
    `DebugRowOps.scala:748-757`)."""
    frame = grouped.frame
    key_arrays = [frame.column(k).host_values() for k in grouped.keys]
    key_out, inverse = factorize_keys(grouped.keys, key_arrays)
    num_groups = len(next(iter(key_out.values())))
    bases = [_base(f) for f in fetch_list]
    # the data operand of each root reduce = the rowwise transform output
    roots = [graph[_base(f)].data_inputs()[0][0] for f in fetch_list]
    comb_sig = ",".join(combiners[b] for b in bases)

    needs_counts = "mean" in combiners.values()

    # TPU-first sum lowering: XLA turns segment_sum into scatter-add,
    # which serializes on the TPU; for modest key counts a one-hot
    # matmul computes the same dense table on the MXU
    # (out[k] = sum_n onehot[n,k] * data[n] — one big matmul). Keys the
    # cache entry because it changes the compiled program.
    from . import config as _config

    onehot_keys = _config.get().aggregate_onehot_keys
    if onehot_keys is None:  # auto: only where scatter-add is the slow path
        onehot_keys = 256 if jax.default_backend() == "tpu" else 0
    # the one-hot operand is a dense (rows x keys) matrix XLA must
    # materialize — bound the PRODUCT too, or a row count the scatter
    # plan handled fine would OOM HBM (256M f32 elements = 1 GB). The
    # decision is per CALL (row count varies across calls of one graph)
    # and is part of the cache kind below, so plans never alias.
    use_onehot = (
        0 < num_groups <= int(onehot_keys)
        and grouped.frame.nrows * num_groups <= 268_435_456
    )

    def make():
        import jax.numpy as jnp

        raw = build_callable(graph, roots, feed_names)
        # sum/mean route through seg_sum above this table
        segment_of = {
            "min": jax.ops.segment_min,
            "max": jax.ops.segment_max,
            "prod": jax.ops.segment_prod,
        }

        def seg_sum(o, gid):
            if not (use_onehot and jnp.issubdtype(o.dtype, jnp.floating)):
                return jax.ops.segment_sum(o, gid, num_groups)
            onehot = jax.nn.one_hot(gid, num_groups, dtype=o.dtype)
            flat = o.reshape(o.shape[0], -1)
            out = jax.lax.dot_general(
                onehot, flat, (((0,), (0,)), ((), ())),
                precision=_config.get().lax_precision(),
            )
            return out.reshape((num_groups,) + o.shape[1:])

        def fn(gid, counts, *feeds):
            outs = raw(*feeds)
            res = []
            for b, o in zip(bases, outs):
                comb = combiners[b]
                if comb == "mean":
                    s = seg_sum(o, gid)
                    c = counts.astype(o.dtype).reshape(
                        (-1,) + (1,) * (s.ndim - 1)
                    )
                    res.append(s / c)
                elif comb == "sum":
                    res.append(seg_sum(o, gid))
                else:
                    res.append(segment_of[comb](o, gid, num_groups))
            return tuple(res)

        return jax.jit(fn)

    sfn = ex.cached(
        f"segagg-{num_groups}-{comb_sig}-{int(use_onehot)}",
        graph, fetch_list, feed_names, make,
    )
    gid = inverse.astype(_gid_dtype(num_groups))
    # counts ride as exact int32 and convert to the fetch dtype in-graph;
    # the O(n) bincount is skipped entirely when no fetch is a Mean
    counts = (
        np.bincount(inverse, minlength=num_groups).astype(np.int32)
        if needs_counts
        else np.zeros(0, np.int32)
    )
    feeds = [frame.column(mapping[n]).values for n in feed_names]
    # the segment plan is ONE whole-frame dispatch — there is no block
    # fan-out to spread, so the scheduler only matters as an explicit
    # placement pin: devices=[d, ...] commits the dispatch to the first
    # listed device (auto scheduling leaves it on the default device)
    dev_label = None
    if devices is not None:  # [] must hit resolve()'s loud rejection too
        from .runtime import scheduler as _rs

        devs = _rs.resolve(devices=devices, executor=ex)
        if devs is not None:
            target = devs[0]
            gid = jax.device_put(gid, target)
            counts = jax.device_put(counts, target)
            feeds = [jax.device_put(f, target) for f in feeds]
            dev_label = _rs.device_label(target)
            _rs._bump(ex, "device_dispatches", dev_label, 1)
    from .utils import telemetry as _tele

    from . import config as _config2
    from .runtime import faults as _faults

    with _tele.span(
        "aggregate.plan.segment", kind="stage", program=graph.fingerprint()
    ):
        with _tele.dispatch_span(
            "aggregate.segment", program=graph.fingerprint(),
            rows=frame.nrows, groups=num_groups, device=dev_label,
        ):
            # classified transient retry (one whole-frame dispatch — no
            # block fan-out to fail over or split)
            outs = _faults.run_with_retries(
                sfn, gid, counts, *feeds,
                attempts=_config2.get().block_retry_attempts,
                what="aggregate segment dispatch", verb="aggregate",
            )
    maybe_check_numerics(bases, outs, "aggregate (segment fast path)")
    # device-resident output: the per-group table stays where the
    # segment ops produced it; a chained verb (or host_values) decides
    # when — and whether — it crosses to the host
    results = {b: o for b, o in zip(bases, outs)}
    return _keyed_output(key_out, results, bases)


def _monoid_combine(
    tab: np.ndarray,
    bounds: np.ndarray,
    comb: str,
    weights: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Combine partial-reduce segments with a derived monoid: one ufunc
    reduceat over a flat partial table (segments delimited by ``bounds``).
    ``weights`` (contributing row counts per partial) is required for
    the size-weighted ``mean`` combine."""
    if comb == "sum":
        return np.add.reduceat(tab, bounds, axis=0)
    if comb == "min":
        return np.minimum.reduceat(tab, bounds, axis=0)
    if comb == "max":
        return np.maximum.reduceat(tab, bounds, axis=0)
    if comb == "prod":
        return np.multiply.reduceat(tab, bounds, axis=0)
    if comb == "mean":
        if weights is None:
            raise ValueError("mean combine needs partial weights")
        w = weights.reshape((-1,) + (1,) * (tab.ndim - 1))
        num = np.add.reduceat(tab * w, bounds, axis=0)
        den = np.add.reduceat(weights, bounds)
        return (num / den.reshape((-1,) + (1,) * (tab.ndim - 1))).astype(
            tab.dtype
        )
    raise AssertionError(f"unknown combiner {comb!r}")


def _aggregate_chunked(
    run: Callable,
    feed_names: List[str],
    col_data: Dict[str, np.ndarray],
    counts: np.ndarray,
    starts: np.ndarray,
    num_groups: int,
    bases: List[str],
    combiners: Dict[str, str],
    pad_quantum: int = 1,
    program: Optional[str] = None,
    executor=None,
    devices=None,
) -> Dict[str, np.ndarray]:
    """Keyed aggregation by pow2 chunk decomposition + monoid combine.

    The exact plan (one vmapped call per distinct group size) compiles
    O(#distinct sizes) programs — a pathological key distribution with
    all-distinct sizes compiles one program per group. Here each sorted
    group splits into power-of-two chunks (binary decomposition of its
    size, in row order); all chunks of one size run as ONE vmapped call
    of the FULL graph (per-row transforms apply inside the chunk); then
    each group's partials combine with the fetch's derived monoid — one
    `np.ufunc.reduceat` over all groups per fetch, size-weighted for
    Mean. Compile count: O(log max_size), independent of the size
    distribution. Only graphs classified by `_chunk_combiners` reach
    this plan, so results are exact, not merely associativity-approximate.

    ``run(feeds)`` executes the vmapped graph on ``(n, size, *cell)``
    feeds; lead dims are padded to ``pad_quantum * 2**k`` (mesh callers
    pass the device count so every batched call shards evenly; padding
    rows replicate real data and their outputs are discarded).
    """
    if num_groups == 0:
        return {}
    # 1. binary chunk decomposition of every sorted group, in row order
    chunk_starts_by_p: Dict[int, List[int]] = {}
    chunk_slots_by_p: Dict[int, List[int]] = {}
    chunk_sizes: List[int] = []  # per global chunk slot, in group order
    group_nchunks = np.zeros(num_groups, dtype=np.int64)
    next_slot = 0
    for g in range(num_groups):
        s = int(counts[g])
        pos = int(starts[g])
        while s:
            p = 1 << (s.bit_length() - 1)
            chunk_starts_by_p.setdefault(p, []).append(pos)
            chunk_slots_by_p.setdefault(p, []).append(next_slot)
            chunk_sizes.append(p)
            group_nchunks[g] += 1
            next_slot += 1
            pos += p
            s -= p

    def _padded(n: int) -> int:
        q = pad_quantum
        while q < n:
            q *= 2
        return q

    # 2. chunk stage: one batched call per distinct pow2 chunk size;
    #    results land in a flat per-fetch partial table (group order).
    #    All chunk-size programs are DISPATCHED before any result is
    #    host-fetched (async device partials, same discipline as the
    #    reduce verbs); the scatter into the flat table then drains them.
    from .utils import telemetry as _tele

    # block-scheduler fan-out: the per-chunk-size programs are
    # independent dispatches, so they spread across local devices
    # weighted by their total row volume (mesh callers pass no
    # executor/devices and stay unscheduled — the mesh owns placement)
    chunk_ps = sorted(chunk_starts_by_p, reverse=True)
    sched = None
    if executor is not None or devices is not None:
        from .runtime import scheduler as _rs

        sched = _rs.schedule_weights(
            [len(chunk_starts_by_p[p]) * p for p in chunk_ps],
            devices=devices, executor=executor,
        )
    pending = []
    for pi, p in enumerate(chunk_ps):
        starts_list = chunk_starts_by_p[p]
        n_p = len(starts_list)
        padded = _padded(n_p)
        st = np.asarray(starts_list + [starts_list[-1]] * (padded - n_p))
        row_idx = st[:, None] + np.arange(p)[None, :]
        feeds = [col_data[n][row_idx] for n in feed_names]
        if sched is not None:
            feeds = sched.put(pi, feeds)
        from . import config as _config
        from .runtime import faults as _faults

        with _tele.dispatch_span(
            "aggregate.chunk", program=program, rows=n_p * p, size=p,
            device=sched.label(pi) if sched is not None else None,
        ):
            # classified transient retry; the feeds are already
            # committed (sched.put above), so the retry re-runs in
            # place — per-chunk-size programs are few and large, the
            # useful failover unit here is the whole verb call
            outs = _faults.run_with_retries(
                run, feeds,
                attempts=_config.get().block_retry_attempts,
                what=f"aggregate chunks of size {p}", verb="aggregate",
            )
        maybe_check_numerics(bases, outs, f"aggregate chunks of size {p}")
        pending.append((n_p, np.asarray(chunk_slots_by_p[p]), tuple(outs)))
    partials: Dict[str, Optional[np.ndarray]] = {b: None for b in bases}
    for n_p, slots, outs in pending:
        for b, o in zip(bases, outs):
            o = np.asarray(o)
            if partials[b] is None:
                partials[b] = np.empty(
                    (next_slot,) + o.shape[1:], dtype=o.dtype
                )
            partials[b][slots] = o[:n_p]

    # 3. combine: one reduceat per fetch over the flat partial tables
    bounds = np.concatenate(
        [[0], np.cumsum(group_nchunks)[:-1]]
    ).astype(np.int64)
    sizes = np.asarray(chunk_sizes, dtype=np.float64)
    return {
        b: _monoid_combine(partials[b], bounds, combiners[b], weights=sizes)
        for b in bases
    }


