"""Op registry: maps TF op names to JAX lowering rules.

The reference delegated op semantics wholesale to libtensorflow kernels
(`TensorFlowOps.withSession`, session.run). Here each supported GraphDef op
has a *lowering rule*: a function from input values to JAX values, executed
while tracing the graph into a single XLA computation. XLA then fuses the
whole graph — there is no per-op kernel dispatch at runtime.

Static-value machinery: several TF ops take *data* inputs that must be
compile-time constants under XLA (reshape targets, reduction axes, fill
dims, ...). During lowering, `Const` nodes evaluate to numpy arrays and
stay numpy until an op forces them onto the device; `LowerCtx.static`
recovers such values (constant folding — the same job TF's variable
freezing + GraphDef constant nodes did for the reference, `core.py:42-56`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..graph.ir import GraphNode

__all__ = ["OpRule", "LowerCtx", "register", "get_rule", "GraphLoweringError", "registered_ops"]


class GraphLoweringError(ValueError):
    """Raised when a graph cannot be lowered to XLA."""

    # a lowering failure is a property of the graph, not of the device:
    # re-running the identical dispatch fails identically
    tfs_fault_class = "deterministic"


@dataclass
class OpRule:
    name: str
    # fn(ctx, node, inputs) -> value | tuple of values (multi-output ops)
    fn: Callable[["LowerCtx", GraphNode, List[Any]], Any]


_REGISTRY: Dict[str, OpRule] = {}


def register(*names: str):
    """Decorator: register a lowering rule under one or more TF op names."""

    def deco(fn):
        for n in names:
            _REGISTRY[n] = OpRule(n, fn)
        return fn

    return deco


def get_rule(op: str) -> Optional[OpRule]:
    return _REGISTRY.get(op)


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


class LowerCtx:
    """Per-lowering context: static-value recovery + helpers."""

    def static(self, value, node: GraphNode, what: str) -> np.ndarray:
        """Return ``value`` as a host numpy array, or fail with a clear error
        if it is a traced (data-dependent) value. Shape-of results and Const
        nodes are always static."""
        import jax

        if isinstance(value, jax.core.Tracer):
            raise GraphLoweringError(
                f"op {node.op!r} (node {node.name!r}) requires a "
                f"compile-time-constant {what}, but it is data-dependent. "
                "XLA compiles static graphs; make this a Const."
            )
        return np.asarray(value)

    def static_int_list(self, value, node: GraphNode, what: str) -> List[int]:
        arr = self.static(value, node, what)
        return [int(x) for x in np.atleast_1d(arr)]
