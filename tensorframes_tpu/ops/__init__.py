"""Op registry and JAX lowerings for GraphDef ops."""

from .lowering import GraphLoweringError, build_callable, supported
from .registry import LowerCtx, OpRule, get_rule, register, registered_ops

__all__ = [
    "GraphLoweringError",
    "build_callable",
    "supported",
    "LowerCtx",
    "OpRule",
    "get_rule",
    "register",
    "registered_ops",
]
