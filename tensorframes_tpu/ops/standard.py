"""Standard op set: JAX lowerings for the TF GraphDef ops this framework
executes.

Coverage = the op families the reference's tests, demos, and configs
exercise (SURVEY.md §7.2): the DSL core (Placeholder/Const/Identity/Add/
Div/Sum/Min, `dsl/package.scala:32-133`), the k-means demo family
(MatMul/Square/ArgMin/UnsortedSegmentSum, `kmeans_demo.py`), and the
Inception-family conv ops (Conv2D/Pool/BatchNorm/Concat/Softmax), plus the
surrounding elementwise/shape/segment ops any frozen TF-1.x graph leans on.

Semantics notes (TF 1.x):
- binary ops do NOT promote dtypes (the graph's ``T`` attr fixes one dtype);
- ``Div`` on integers truncates toward zero (C semantics), ``FloorDiv``
  floors; ``RealDiv`` is true division;
- reductions take ``reduction_indices`` as a *tensor input* plus a
  ``keep_dims`` attr (`DslImpl.scala:175-188`);
- ``Conv2D``/pooling default to NHWC with explicit stride/ksize quads.
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..graph.ir import GraphNode
from ..schema import ScalarType
from .registry import GraphLoweringError, LowerCtx, register

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _is_int(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)


def _reduction_axes(ctx: LowerCtx, node: GraphNode, x, indices) -> tuple:
    rank = jnp.ndim(x)
    axes = ctx.static_int_list(indices, node, "reduction_indices")
    return tuple(sorted(a % rank for a in axes)) if axes else tuple(range(rank))


def _keep_dims(node: GraphNode) -> bool:
    return bool(node.attr("keep_dims", node.attr("keepdims", False)))


def _padding_str(node: GraphNode) -> str:
    p = node.attr("padding", b"VALID")
    return (p.decode() if isinstance(p, bytes) else str(p)).upper()


def _data_format(node: GraphNode) -> str:
    df = node.attr("data_format", b"NHWC")
    return df.decode() if isinstance(df, bytes) else str(df)


# ---------------------------------------------------------------------------
# sources / identity
# ---------------------------------------------------------------------------


@register("Const")
def _const(ctx, node, inputs):
    av = node.attrs.get("value")
    if av is None or av.kind != "tensor":
        raise GraphLoweringError(f"Const node {node.name!r} has no value attr")
    return av.value.to_numpy()  # stays host-side until an op needs it on device


@register("Identity", "StopGradient", "PreventGradient", "CheckNumerics", "Snapshot")
def _identity(ctx, node, inputs):
    return inputs[0]


@register("IdentityN")
def _identity_n(ctx, node, inputs):
    return tuple(inputs)


@register("NoOp")
def _noop(ctx, node, inputs):
    return ()


@register("Assert")
def _assert(ctx, node, inputs):
    # Runtime assertions are host-side control flow TF threads through
    # the graph (frozen BERT carries seq-length Asserts); under XLA the
    # shapes they guard are compile-time facts, so the node reduces to
    # its control-dependency role — like NoOp, it produces nothing.
    return ()


# ---------------------------------------------------------------------------
# elementwise unary
# ---------------------------------------------------------------------------

_UNARY = {
    "Neg": jnp.negative,
    "Abs": jnp.abs,
    "Square": jnp.square,
    "Sqrt": jnp.sqrt,
    "Rsqrt": lambda x: lax.rsqrt(jnp.asarray(x)),
    "Exp": jnp.exp,
    "Log": jnp.log,
    "Log1p": jnp.log1p,
    "Expm1": jnp.expm1,
    "Sign": jnp.sign,
    "Floor": jnp.floor,
    "Ceil": jnp.ceil,
    "Round": jnp.round,
    "Rint": jnp.round,
    "Reciprocal": lambda x: 1 / jnp.asarray(x),
    "Inv": lambda x: 1 / jnp.asarray(x),
    "Tanh": jnp.tanh,
    "Sigmoid": jax.nn.sigmoid,
    "Relu": jax.nn.relu,
    "Relu6": lambda x: jnp.clip(jnp.asarray(x), 0, 6),
    "Elu": jax.nn.elu,
    "Selu": jax.nn.selu,
    "Softplus": jax.nn.softplus,
    "Softsign": jax.nn.soft_sign,
    "Erf": jax.scipy.special.erf,
    "Erfc": jax.scipy.special.erfc,
    "Sin": jnp.sin,
    "Cos": jnp.cos,
    "Tan": jnp.tan,
    "Asin": jnp.arcsin,
    "Acos": jnp.arccos,
    "Atan": jnp.arctan,
    "Sinh": jnp.sinh,
    "Cosh": jnp.cosh,
    "IsNan": jnp.isnan,
    "IsInf": jnp.isinf,
    "IsFinite": jnp.isfinite,
    "LogicalNot": jnp.logical_not,
    "OnesLike": jnp.ones_like,
    "ZerosLike": jnp.zeros_like,
}

for _name, _fn in _UNARY.items():
    register(_name)(lambda ctx, node, inputs, _fn=_fn: _fn(inputs[0]))


# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------


def _tf_div(x, y):
    if _is_int(x) and _is_int(y):
        return lax.div(jnp.asarray(x), jnp.asarray(y))  # C truncation
    return jnp.true_divide(x, y)


_BINARY = {
    "Add": jnp.add,
    "AddV2": jnp.add,
    "Sub": jnp.subtract,
    "Mul": jnp.multiply,
    "Div": _tf_div,
    "RealDiv": jnp.true_divide,
    "TruncateDiv": _tf_div,
    "FloorDiv": jnp.floor_divide,
    "FloorMod": jnp.mod,
    "Mod": jnp.mod,
    "Maximum": jnp.maximum,
    "Minimum": jnp.minimum,
    "Pow": jnp.power,
    "SquaredDifference": lambda x, y: jnp.square(jnp.subtract(x, y)),
    "Atan2": jnp.arctan2,
    "Equal": jnp.equal,
    "NotEqual": jnp.not_equal,
    "Less": jnp.less,
    "LessEqual": jnp.less_equal,
    "Greater": jnp.greater,
    "GreaterEqual": jnp.greater_equal,
    "LogicalAnd": jnp.logical_and,
    "LogicalOr": jnp.logical_or,
}

for _name, _fn in _BINARY.items():
    register(_name)(lambda ctx, node, inputs, _fn=_fn: _fn(inputs[0], inputs[1]))


@register("AddN", "AccumulateNV2")
def _add_n(ctx, node, inputs):
    out = inputs[0]
    for x in inputs[1:]:
        out = jnp.add(out, x)
    return out


@register("Select", "SelectV2")
def _select(ctx, node, inputs):
    return jnp.where(inputs[0], inputs[1], inputs[2])


@register("ClipByValue")
def _clip(ctx, node, inputs):
    return jnp.clip(inputs[0], inputs[1], inputs[2])


# ---------------------------------------------------------------------------
# reductions (input-tensor axes + keep_dims attr)
# ---------------------------------------------------------------------------


def _make_reducer(jfn, keep_dtype=False):
    def rule(ctx, node, inputs):
        axes = _reduction_axes(ctx, node, inputs[0], inputs[1])
        if keep_dtype:
            # TF reductions keep the input dtype (no numpy-style int32 ->
            # int64 accumulator promotion under x64).
            dt = jnp.asarray(inputs[0]).dtype
            return jfn(inputs[0], axis=axes, keepdims=_keep_dims(node), dtype=dt)
        return jfn(inputs[0], axis=axes, keepdims=_keep_dims(node))

    return rule


@register("Mean")
def _mean(ctx, node, inputs):
    axes = _reduction_axes(ctx, node, inputs[0], inputs[1])
    x = jnp.asarray(inputs[0])
    if jnp.issubdtype(x.dtype, jnp.integer):
        # TF Mean on integers = integer division of sum by count
        total = jnp.sum(x, axis=axes, keepdims=_keep_dims(node), dtype=x.dtype)
        count = 1
        for a in axes:
            count *= x.shape[a]
        return lax.div(total, jnp.asarray(count, x.dtype))
    return jnp.mean(x, axis=axes, keepdims=_keep_dims(node))


register("Sum")(_make_reducer(jnp.sum, keep_dtype=True))
register("Prod")(_make_reducer(jnp.prod, keep_dtype=True))
register("Min")(_make_reducer(jnp.min))
register("Max")(_make_reducer(jnp.max))
register("All")(_make_reducer(jnp.all))
register("Any")(_make_reducer(jnp.any))


@register("ArgMax")
def _argmax(ctx, node, inputs):
    axis = int(ctx.static(inputs[1], node, "dimension")) if len(inputs) > 1 else 0
    out_t = node.attr("output_type", ScalarType.int64)
    return jnp.argmax(inputs[0], axis=axis).astype(out_t.np_dtype)


@register("ArgMin")
def _argmin(ctx, node, inputs):
    axis = int(ctx.static(inputs[1], node, "dimension")) if len(inputs) > 1 else 0
    out_t = node.attr("output_type", ScalarType.int64)
    return jnp.argmin(inputs[0], axis=axis).astype(out_t.np_dtype)


# ---------------------------------------------------------------------------
# segment ops (k-means / aggregate family)
# ---------------------------------------------------------------------------


@register("UnsortedSegmentSum")
def _unsorted_segment_sum(ctx, node, inputs):
    num = int(ctx.static(inputs[2], node, "num_segments"))
    return jax.ops.segment_sum(jnp.asarray(inputs[0]), jnp.asarray(inputs[1]), num)


@register("UnsortedSegmentMax")
def _unsorted_segment_max(ctx, node, inputs):
    num = int(ctx.static(inputs[2], node, "num_segments"))
    return jax.ops.segment_max(jnp.asarray(inputs[0]), jnp.asarray(inputs[1]), num)


@register("UnsortedSegmentMin")
def _unsorted_segment_min(ctx, node, inputs):
    num = int(ctx.static(inputs[2], node, "num_segments"))
    return jax.ops.segment_min(jnp.asarray(inputs[0]), jnp.asarray(inputs[1]), num)


@register("SegmentSum")
def _segment_sum(ctx, node, inputs):
    ids = ctx.static(inputs[1], node, "segment_ids (data-dependent segment "
                     "count; use UnsortedSegmentSum with static num_segments)")
    num = int(ids.max()) + 1 if ids.size else 0
    return jax.ops.segment_sum(
        jnp.asarray(inputs[0]), jnp.asarray(ids), num, indices_are_sorted=True
    )


# ---------------------------------------------------------------------------
# linear algebra
# ---------------------------------------------------------------------------


@register("MatMul", "BatchMatMul", "BatchMatMulV2")
def _matmul(ctx, node, inputs):
    a, b = jnp.asarray(inputs[0]), jnp.asarray(inputs[1])
    ta = bool(node.attr("transpose_a", node.attr("adj_x", False)))
    tb = bool(node.attr("transpose_b", node.attr("adj_y", False)))
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    # TF float32 matmul is true fp32; JAX's default lets the MXU use bf16
    # passes. Default is HIGHEST for numerical parity with the reference;
    # config.matmul_precision="default" opts into MXU-native speed.
    from .. import config

    return jnp.matmul(a, b, precision=config.get().lax_precision())


@register("L2Loss")
def _l2loss(ctx, node, inputs):
    x = jnp.asarray(inputs[0])
    return jnp.sum(jnp.square(x)) / 2


# ---------------------------------------------------------------------------
# shape / layout
# ---------------------------------------------------------------------------


@register("Shape")
def _shape(ctx, node, inputs):
    # Static under XLA even for traced inputs: shapes are compile-time facts.
    out_t = node.attr("out_type", ScalarType.int32)
    return np.asarray(jnp.shape(inputs[0]), dtype=out_t.np_dtype)


@register("ShapeN")
def _shape_n(ctx, node, inputs):
    out_t = node.attr("out_type", ScalarType.int32)
    return tuple(np.asarray(jnp.shape(x), dtype=out_t.np_dtype) for x in inputs)


@register("Size")
def _size(ctx, node, inputs):
    out_t = node.attr("out_type", ScalarType.int32)
    return np.asarray(jnp.size(inputs[0]), dtype=out_t.np_dtype)


@register("Rank")
def _rank(ctx, node, inputs):
    return np.asarray(jnp.ndim(inputs[0]), dtype=np.int32)


@register("Reshape")
def _reshape(ctx, node, inputs):
    target = ctx.static_int_list(inputs[1], node, "shape")
    return jnp.reshape(inputs[0], target)


@register("ExpandDims")
def _expand_dims(ctx, node, inputs):
    axis = int(ctx.static(inputs[1], node, "dim"))
    return jnp.expand_dims(inputs[0], axis)


@register("Squeeze")
def _squeeze(ctx, node, inputs):
    dims = node.attr("squeeze_dims", node.attr("axis", None))
    if dims is not None and getattr(dims, "i", None) is not None:
        dims = list(dims.i)
    axes = tuple(dims) if dims else None
    return jnp.squeeze(inputs[0], axis=axes)


@register("Transpose")
def _transpose(ctx, node, inputs):
    perm = ctx.static_int_list(inputs[1], node, "perm")
    return jnp.transpose(inputs[0], perm)


@register("Fill")
def _fill(ctx, node, inputs):
    dims = ctx.static_int_list(inputs[0], node, "dims")
    return jnp.full(dims, inputs[1])


@register("Range")
def _range(ctx, node, inputs):
    start = ctx.static(inputs[0], node, "start")
    limit = ctx.static(inputs[1], node, "limit")
    delta = ctx.static(inputs[2], node, "delta")
    return np.arange(start, limit, delta)


@register("Tile")
def _tile(ctx, node, inputs):
    multiples = ctx.static_int_list(inputs[1], node, "multiples")
    return jnp.tile(inputs[0], multiples)


@register("Concat")
def _concat(ctx, node, inputs):
    axis = int(ctx.static(inputs[0], node, "concat_dim"))
    return jnp.concatenate([jnp.asarray(x) for x in inputs[1:]], axis=axis)


@register("ConcatV2")
def _concat_v2(ctx, node, inputs):
    axis = int(ctx.static(inputs[-1], node, "axis"))
    return jnp.concatenate([jnp.asarray(x) for x in inputs[:-1]], axis=axis)


@register("Pack", "Stack")  # "Stack" is the legacy TF 1.x alias
def _pack(ctx, node, inputs):
    return jnp.stack([jnp.asarray(x) for x in inputs], axis=int(node.attr("axis", 0)))


@register("Unpack")
def _unpack(ctx, node, inputs):
    axis = int(node.attr("axis", 0))
    num = int(node.attr("num", jnp.shape(inputs[0])[axis]))
    parts = jnp.split(jnp.asarray(inputs[0]), num, axis=axis)
    return tuple(jnp.squeeze(p, axis=axis) for p in parts)


@register("Split")
def _split(ctx, node, inputs):
    axis = int(ctx.static(inputs[0], node, "split_dim"))
    num = int(node.attr("num_split", 1))
    return tuple(jnp.split(jnp.asarray(inputs[1]), num, axis=axis))


@register("SplitV")
def _split_v(ctx, node, inputs):
    sizes = ctx.static_int_list(inputs[1], node, "size_splits")
    axis = int(ctx.static(inputs[2], node, "split_dim"))
    x = jnp.asarray(inputs[0])
    if -1 in sizes:  # one size may be inferred from the remainder
        known = sum(s for s in sizes if s >= 0)
        sizes = [s if s >= 0 else x.shape[axis] - known for s in sizes]
    bounds = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, bounds, axis=axis))


@register("LeakyRelu")
def _leaky_relu(ctx, node, inputs):
    import jax

    alpha = float(node.attr("alpha", 0.2))
    return jax.nn.leaky_relu(jnp.asarray(inputs[0]), negative_slope=alpha)


@register("GatherNd")
def _gather_nd(ctx, node, inputs):
    params = jnp.asarray(inputs[0])
    indices = jnp.asarray(inputs[1])
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    return params[idx]


@register("ScatterNd")
def _scatter_nd(ctx, node, inputs):
    indices = jnp.asarray(inputs[0])
    updates = jnp.asarray(inputs[1])
    shape = tuple(ctx.static_int_list(inputs[2], node, "shape"))
    out = jnp.zeros(shape, updates.dtype)
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    return out.at[idx].add(updates)


@register("ResizeBilinear")
def _resize_bilinear(ctx, node, inputs):
    """TF1 bilinear resize with its exact coordinate conventions:
    legacy asymmetric (default), align_corners, or half_pixel_centers —
    jax.image.resize only offers half-pixel, so interpolate directly.
    Output is always float32 (TF's contract for any input dtype)."""
    x = jnp.asarray(inputs[0]).astype(jnp.float32)  # NHWC
    out_h, out_w = (int(v) for v in ctx.static_int_list(inputs[1], node, "size"))
    in_h, in_w = x.shape[1], x.shape[2]
    align = bool(node.attr("align_corners", False))
    half_pixel = bool(node.attr("half_pixel_centers", False))

    def src(out_n, in_n):
        o = jnp.arange(out_n, dtype=jnp.float32)
        if align and out_n > 1:
            return o * ((in_n - 1) / (out_n - 1))
        if half_pixel:
            return jnp.maximum((o + 0.5) * (in_n / out_n) - 0.5, 0.0)
        return o * (in_n / out_n)

    def lerp_axis(arr, coords, in_n, axis):
        lo = jnp.clip(jnp.floor(coords).astype(jnp.int32), 0, in_n - 1)
        hi = jnp.minimum(lo + 1, in_n - 1)
        w = (coords - lo).astype(arr.dtype)
        shape = [1] * arr.ndim
        shape[axis] = w.shape[0]
        w = w.reshape(shape)
        a = jnp.take(arr, lo, axis=axis)
        b = jnp.take(arr, hi, axis=axis)
        return a * (1 - w) + b * w

    out = lerp_axis(x, src(out_h, in_h), in_h, axis=1)
    return lerp_axis(out, src(out_w, in_w), in_w, axis=2)


@register("Slice")
def _slice(ctx, node, inputs):
    begin = ctx.static_int_list(inputs[1], node, "begin")
    size = ctx.static_int_list(inputs[2], node, "size")
    x = jnp.asarray(inputs[0])
    limits = [
        b + (s if s != -1 else x.shape[i] - b)
        for i, (b, s) in enumerate(zip(begin, size))
    ]
    return lax.slice(x, begin, limits)


@register("StridedSlice")
def _strided_slice(ctx, node, inputs):
    x = jnp.asarray(inputs[0])
    begin = ctx.static_int_list(inputs[1], node, "begin")
    end = ctx.static_int_list(inputs[2], node, "end")
    strides = ctx.static_int_list(inputs[3], node, "strides")
    bm = int(node.attr("begin_mask", 0))
    em = int(node.attr("end_mask", 0))
    ellipsis_mask = int(node.attr("ellipsis_mask", 0))
    new_axis_mask = int(node.attr("new_axis_mask", 0))
    shrink_mask = int(node.attr("shrink_axis_mask", 0))
    # Build a numpy-style index tuple; numpy slicing semantics match TF's
    # StridedSlice spec, so delegate the heavy lifting.
    idx: List[Any] = []
    for i in range(len(begin)):
        if ellipsis_mask & (1 << i):
            idx.append(Ellipsis)
        elif new_axis_mask & (1 << i):
            idx.append(None)
        elif shrink_mask & (1 << i):
            idx.append(begin[i])
        else:
            b = None if bm & (1 << i) else begin[i]
            e = None if em & (1 << i) else end[i]
            idx.append(slice(b, e, strides[i]))
    return x[tuple(idx)]


@register("Pad", "PadV2")
def _pad(ctx, node, inputs):
    paddings = ctx.static(inputs[1], node, "paddings")
    const = inputs[2] if len(inputs) > 2 else 0
    return jnp.pad(
        jnp.asarray(inputs[0]),
        [(int(a), int(b)) for a, b in paddings],
        constant_values=const,
    )


@register("MirrorPad")
def _mirror_pad(ctx, node, inputs):
    paddings = ctx.static(inputs[1], node, "paddings")
    mode = node.attr("mode", b"REFLECT")
    mode = (mode.decode() if isinstance(mode, bytes) else mode).lower()
    return jnp.pad(
        jnp.asarray(inputs[0]),
        [(int(a), int(b)) for a, b in paddings],
        mode="reflect" if mode == "reflect" else "symmetric",
    )


@register("TopK", "TopKV2")
def _top_k(ctx, node, inputs):
    k = int(ctx.static(inputs[1], node, "k")) if len(inputs) > 1 else int(
        node.attr("k", 1)
    )
    values, indices = lax.top_k(jnp.asarray(inputs[0]), k)
    return (values, indices.astype(jnp.int32))


@register("Cumsum")
def _cumsum(ctx, node, inputs):
    axis = int(ctx.static(inputs[1], node, "axis"))
    x = jnp.asarray(inputs[0])
    exclusive = bool(node.attr("exclusive", False))
    reverse = bool(node.attr("reverse", False))
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis, dtype=x.dtype)
    if exclusive:
        out = jnp.roll(out, 1, axis)
        idx = [slice(None)] * out.ndim
        idx[axis] = 0
        out = out.at[tuple(idx)].set(0)
    if reverse:
        out = jnp.flip(out, axis)
    return out


@register("GatherV2", "Gather")
def _gather(ctx, node, inputs):
    axis = int(ctx.static(inputs[2], node, "axis")) if len(inputs) > 2 else 0
    return jnp.take(jnp.asarray(inputs[0]), jnp.asarray(inputs[1]), axis=axis)


@register("OneHot")
def _one_hot(ctx, node, inputs):
    depth = int(ctx.static(inputs[1], node, "depth"))
    on = jnp.asarray(inputs[2]) if len(inputs) > 2 else jnp.float32(1.0)
    off = jnp.asarray(inputs[3]) if len(inputs) > 3 else jnp.float32(0.0)
    axis = int(node.attr("axis", -1))
    # output dtype follows on/off_value (TF's T attr), not the x64 default
    oh = jax.nn.one_hot(
        jnp.asarray(inputs[0]), depth, axis=axis, dtype=on.dtype
    )
    return oh * on + (1 - oh) * off


@register("Cast")
def _cast(ctx, node, inputs):
    dst = node.attr("DstT")
    if dst is None:
        raise GraphLoweringError(f"Cast {node.name!r} missing DstT")
    return jnp.asarray(inputs[0]).astype(dst.np_dtype)


@register("BroadcastTo")
def _broadcast_to(ctx, node, inputs):
    target = ctx.static_int_list(inputs[1], node, "shape")
    return jnp.broadcast_to(inputs[0], target)


# ---------------------------------------------------------------------------
# NN ops (Inception / MLP family) — NHWC on the MXU via lax conv/reduce_window
# ---------------------------------------------------------------------------


@register("BiasAdd")
def _bias_add(ctx, node, inputs):
    x, b = jnp.asarray(inputs[0]), jnp.asarray(inputs[1])
    if _data_format(node) == "NCHW" and x.ndim == 4:
        return x + b.reshape(1, -1, 1, 1)
    return x + b


@register("Softmax")
def _softmax(ctx, node, inputs):
    return jax.nn.softmax(jnp.asarray(inputs[0]), axis=-1)


@register("LogSoftmax")
def _log_softmax(ctx, node, inputs):
    return jax.nn.log_softmax(jnp.asarray(inputs[0]), axis=-1)


@register("Conv2D")
def _conv2d(ctx, node, inputs):
    x, w = jnp.asarray(inputs[0]), jnp.asarray(inputs[1])
    strides = [int(s) for s in node.attrs["strides"].value.i]
    fmt = _data_format(node)
    if fmt == "NHWC":
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
        window_strides = strides[1:3]
    else:
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "HWIO", "NCHW"))
        window_strides = strides[2:4]
    dil = node.attrs.get("dilations")
    rhs_dilation = None
    if dil is not None:
        d = [int(v) for v in dil.value.i]
        rhs_dilation = d[1:3] if fmt == "NHWC" else d[2:4]
    from .. import config

    return lax.conv_general_dilated(
        x, w, window_strides, _padding_str(node),
        rhs_dilation=rhs_dilation, dimension_numbers=dn,
        precision=config.get().lax_precision(),
    )


@register("DepthwiseConv2dNative")
def _depthwise_conv(ctx, node, inputs):
    x, w = jnp.asarray(inputs[0]), jnp.asarray(inputs[1])
    strides = [int(s) for s in node.attrs["strides"].value.i]
    # w: [H, W, C, M] -> grouped conv, feature_group_count=C, [H,W,1,C*M].
    # Output channel o = c*M + m belongs to group o // M = c, so the
    # filter reshapes channel-major — no transpose (TF orders outputs
    # [c0m0, c0m1, c1m0, ...]).
    h, wd, c, m = w.shape
    w2 = jnp.reshape(w, (h, wd, 1, c * m))
    dn = lax.conv_dimension_numbers(x.shape, w2.shape, ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w2, strides[1:3], _padding_str(node),
        dimension_numbers=dn, feature_group_count=c,
    )


def _pool(ctx, node, inputs, init, op, avg=False):
    x = jnp.asarray(inputs[0])
    ksize = [int(k) for k in node.attrs["ksize"].value.i]
    strides = [int(s) for s in node.attrs["strides"].value.i]
    pad = _padding_str(node)
    out = lax.reduce_window(x, init, op, ksize, strides, pad)
    if avg:
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, ksize, strides, pad)
        out = out / counts
    return out


@register("MaxPool", "MaxPoolV2")
def _max_pool(ctx, node, inputs):
    return _pool(ctx, node, inputs, -jnp.inf, lax.max)


@register("AvgPool")
def _avg_pool(ctx, node, inputs):
    return _pool(ctx, node, inputs, 0.0, lax.add, avg=True)


@register("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_batch_norm(ctx, node, inputs):
    x, scale, offset, mean, var = (jnp.asarray(v) for v in inputs[:5])
    eps = float(node.attr("epsilon", 1e-4))
    if bool(node.attr("is_training", False)):
        axes = (0, 1, 2) if _data_format(node) == "NHWC" else (0, 2, 3)
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    if _data_format(node) == "NCHW":
        shape = (1, -1, 1, 1)
        scale, offset, mean, var = (v.reshape(shape) for v in (scale, offset, mean, var))
    inv = scale * lax.rsqrt(var + eps)
    y = (x - mean) * inv + offset
    # TF returns (y, batch_mean, batch_var, ...); only y is commonly fetched.
    return (y, jnp.ravel(mean), jnp.ravel(var))


@register("BatchNormWithGlobalNormalization")
def _batch_norm_global(ctx, node, inputs):
    x, mean, var, beta, gamma = (jnp.asarray(v) for v in inputs[:5])
    eps = float(node.attr("variance_epsilon", 1e-4))
    inv = lax.rsqrt(var + eps)
    if bool(node.attr("scale_after_normalization", True)):
        inv = inv * gamma
    return x * inv + (beta - mean * inv)


@register("LRN")
def _lrn(ctx, node, inputs):
    x = jnp.asarray(inputs[0])
    depth_radius = int(node.attr("depth_radius", 5))
    bias = float(node.attr("bias", 1.0))
    alpha = float(node.attr("alpha", 1.0))
    beta = float(node.attr("beta", 0.5))
    sq = jnp.square(x)
    win = 2 * depth_radius + 1
    summed = lax.reduce_window(
        sq, 0.0, lax.add, (1, 1, 1, win), (1, 1, 1, 1), "SAME"
    )
    return x / jnp.power(bias + alpha * summed, beta)
