"""Lowering rules for functionalized control flow.

`graph.control_flow` rewrites imported TF control flow (v1
Switch/Merge/Enter/Exit rings and v2 functional If/While) into `_Cond`
and `_While` pseudo-nodes whose bodies live in the graph's `subgraphs`
side table. These rules lower them to `lax.cond` / `lax.while_loop` —
the compiler-friendly forms XLA requires (SURVEY.md L8: libtensorflow
ran any GraphDef interpretively; here control flow compiles).

Both rules build the body callables with `build_callable` on the
extracted `Subgraph`s, so nested control flow, function calls, and the
whole op registry work inside bodies for free.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import GraphLoweringError, register


def _sub(ctx, node, attr_key):
    key = node.attr(attr_key)
    key = key.decode() if isinstance(key, bytes) else key
    graph = getattr(ctx, "graph", None)
    if graph is None or key not in getattr(graph, "subgraphs", {}):
        raise GraphLoweringError(
            f"node {node.name!r} references missing subgraph {key!r} — "
            "was the graph functionalized by graph.control_flow?"
        )
    return graph.subgraphs[key]


@register("_Cond")
def _cond(ctx, node, inputs):
    from .lowering import build_callable

    tsub = _sub(ctx, node, "cond_then")
    esub = _sub(ctx, node, "cond_else")
    tfn = build_callable(tsub.graph, tsub.fetches, tsub.feeds)
    efn = build_callable(esub.graph, esub.fetches, esub.feeds)
    pred, *operands = inputs
    pred = jnp.reshape(jnp.asarray(pred).astype(bool), ())
    out = lax.cond(
        pred,
        lambda ops: tuple(tfn(*ops)),
        lambda ops: tuple(efn(*ops)),
        tuple(jnp.asarray(v) for v in operands),
    )
    return tuple(out)


@register("_While")
def _while(ctx, node, inputs):
    from .lowering import build_callable

    csub = _sub(ctx, node, "while_cond")
    bsub = _sub(ctx, node, "while_body")
    n_vars = int(node.attr("n_vars"))
    cond_fn = build_callable(csub.graph, csub.fetches, csub.feeds)
    body_fn = build_callable(bsub.graph, bsub.fetches, bsub.feeds)
    carry = tuple(jnp.asarray(v) for v in inputs)
    out = lax.while_loop(
        lambda c: jnp.reshape(cond_fn(*c)[0], ()).astype(bool),
        lambda c: tuple(body_fn(*c)),
        carry,
    )
    # invariant captures ride the carry but are not node outputs
    return tuple(out[:n_vars])
