"""Lowering rules for functionalized control flow.

`graph.control_flow` rewrites imported TF control flow (v1
Switch/Merge/Enter/Exit rings and v2 functional If/While) into `_Cond`
and `_While` pseudo-nodes whose bodies live in the graph's `subgraphs`
side table. These rules lower them to `lax.cond` / `lax.while_loop` —
the compiler-friendly forms XLA requires (SURVEY.md L8: libtensorflow
ran any GraphDef interpretively; here control flow compiles).

Both rules build the body callables with `build_callable` on the
extracted `Subgraph`s, so nested control flow, function calls, and the
whole op registry work inside bodies for free.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from .registry import GraphLoweringError, register


def _sub(ctx, node, attr_key):
    key = node.attr(attr_key)
    key = key.decode() if isinstance(key, bytes) else key
    graph = getattr(ctx, "graph", None)
    if graph is None or key not in getattr(graph, "subgraphs", {}):
        raise GraphLoweringError(
            f"node {node.name!r} references missing subgraph {key!r} — "
            "was the graph functionalized by graph.control_flow?"
        )
    return graph.subgraphs[key]


@register("_Cond")
def _cond(ctx, node, inputs):
    from .lowering import build_callable
    from ..graph import vectorize as _vec

    tsub = _sub(ctx, node, "cond_then")
    esub = _sub(ctx, node, "cond_else")
    tfn = build_callable(tsub.graph, tsub.fetches, tsub.feeds)
    efn = build_callable(esub.graph, esub.fetches, esub.feeds)
    pred, *operands = inputs
    pred = jnp.asarray(pred)
    operands = tuple(jnp.asarray(v) for v in operands)
    if pred.size != 1:
        # batched predicate: the per-row graph is executing at block
        # level, so the cond selects per row — evaluate both (pure)
        # branches and mask (graph/vectorize.py)
        if not _vec.enabled():
            raise GraphLoweringError(
                f"_Cond (node {node.name!r}) has a batched predicate of "
                f"shape {pred.shape} but row vectorization is disabled "
                "(config.row_vectorize / TFS_ROW_VECTORIZE)"
            )
        return _vec.select_cond(node, pred, tfn(*operands), efn(*operands))
    _vec.check_branch_avals(node, tfn, efn, operands)
    out = lax.cond(
        jnp.reshape(pred.astype(bool), ()),
        lambda ops: tuple(tfn(*ops)),
        lambda ops: tuple(efn(*ops)),
        operands,
    )
    return tuple(out)


# ---------------------------------------------------------------------------
# TensorList ops — what Keras RNN layers (LSTM/GRU) put inside their
# while loops. Represented as DENSE (num_elements, *element_shape)
# arrays: XLA has no variant type, and a statically-sized array carry is
# exactly what lax.while_loop needs. Static bound: element_shape and
# num_elements must be compile-time constants (they are in frozen Keras
# graphs — both derive from Shape-of chains this lowering keeps static).
# ---------------------------------------------------------------------------


@register("TensorListReserve")
def _tl_reserve(ctx, node, inputs):
    import numpy as np

    eshape = ctx.static_int_list(inputs[0], node, "element_shape")
    n = int(ctx.static(inputs[1], node, "num_elements"))
    if any(d < 0 for d in eshape) or n < 0:
        raise GraphLoweringError(
            f"TensorListReserve (node {node.name!r}) has dynamic "
            f"element_shape {eshape} / num_elements {n}; XLA needs "
            "static list extents (frozen Keras RNN graphs satisfy this)"
        )
    st = node.attr("element_dtype")
    dtype = st.np_dtype if hasattr(st, "np_dtype") else np.float32
    return jnp.zeros((n, *eshape), dtype)


@register("TensorListSetItem")
def _tl_set_item(ctx, node, inputs):
    lst, idx, item = inputs
    lst = jnp.asarray(lst)
    item = jnp.asarray(item).astype(lst.dtype)
    start = (jnp.asarray(idx, jnp.int32),) + (0,) * item.ndim
    return lax.dynamic_update_slice(lst, item[None], start)


@register("TensorListGetItem")
def _tl_get_item(ctx, node, inputs):
    lst, idx = inputs[0], inputs[1]
    lst = jnp.asarray(lst)
    start = (jnp.asarray(idx, jnp.int32),) + (0,) * (lst.ndim - 1)
    return lax.dynamic_slice(lst, start, (1,) + lst.shape[1:])[0]


@register("TensorListStack", "TensorListFromTensor")
def _tl_passthrough(ctx, node, inputs):
    # the dense representation IS the stacked tensor (FromTensor's
    # second input is the element_shape hint; Stack's is ignored too)
    return jnp.asarray(inputs[0])


@register("TensorListLength")
def _tl_length(ctx, node, inputs):
    import numpy as np

    return np.int32(jnp.asarray(inputs[0]).shape[0])


@register("_While")
def _while(ctx, node, inputs):
    import jax

    from .lowering import build_callable
    from ..graph import vectorize as _vec

    csub = _sub(ctx, node, "while_cond")
    bsub = _sub(ctx, node, "while_body")
    n_vars = int(node.attr("n_vars"))
    cond_fn = build_callable(csub.graph, csub.fetches, csub.feeds)
    body_fn = build_callable(bsub.graph, bsub.fetches, bsub.feeds)
    carry = tuple(jnp.asarray(v) for v in inputs)
    pred0 = jax.eval_shape(
        lambda *c: jnp.asarray(cond_fn(*c)[0]), *carry
    )
    if math.prod(pred0.shape) != 1:
        # batched predicate: the per-row loop is executing at block
        # level — lower to ONE convergence-masked dense fixed point
        # (graph/vectorize.py) instead of failing the scalar reshape
        if not _vec.enabled():
            raise GraphLoweringError(
                f"_While (node {node.name!r}) has a batched predicate "
                f"of shape {pred0.shape} but row vectorization is "
                "disabled (config.row_vectorize / TFS_ROW_VECTORIZE)"
            )
        return _vec.masked_while(
            node, carry, n_vars, cond_fn, body_fn, pred0
        )
    _vec.check_while_carry(node, body_fn, carry, n_vars)
    out = lax.while_loop(
        lambda c: jnp.reshape(cond_fn(*c)[0], ()).astype(bool),
        lambda c: tuple(body_fn(*c)),
        carry,
    )
    # invariant captures ride the carry but are not node outputs
    return tuple(out[:n_vars])
