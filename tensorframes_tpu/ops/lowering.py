"""Graph -> JAX callable lowering.

`build_callable` turns a `Graph` + fetch list into a pure Python function
over placeholder arrays. Calling it under `jax.jit` traces every node's
lowering rule into one XLA computation — the whole graph becomes a single
fused executable, where the reference paid a libtensorflow `session.run`
per partition with per-op kernel dispatch (`DebugRowOps.scala:794-801`).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import numpy as np

from ..graph.ir import Graph, parse_edge
from .registry import GraphLoweringError, LowerCtx, get_rule
from . import standard  # noqa: F401  (populates the registry)
from . import control  # noqa: F401  (_Cond/_While rules)

__all__ = ["build_callable", "supported", "GraphLoweringError"]


def supported(graph: Graph, fetches: Sequence[str]) -> Tuple[bool, str]:
    """Check that every op in the closure of ``fetches`` has a rule."""
    for node in graph.toposort(list(fetches)):
        if node.op in ("Placeholder", "PlaceholderV2"):
            continue
        if get_rule(node.op) is None:
            return False, f"unsupported op {node.op!r} (node {node.name!r})"
    return True, ""


def build_callable(
    graph: Graph, fetches: Sequence[str], feed_names: Sequence[str]
) -> Callable[..., Tuple[Any, ...]]:
    """Build ``fn(*feed_arrays) -> tuple(fetch_values)``.

    ``feed_names`` fixes the positional order of placeholder arguments (so
    the function is directly jittable). Fetches may use ``name:k`` syntax.
    """
    order = graph.toposort(list(fetches))
    feed_pos = {name: i for i, name in enumerate(feed_names)}
    ctx = LowerCtx()
    # _Cond/_While rules resolve their body Subgraphs through the ctx
    ctx.graph = graph

    for node in order:
        if node.op in ("Placeholder", "PlaceholderV2"):
            if node.name not in feed_pos:
                raise GraphLoweringError(
                    f"placeholder {node.name!r} is not fed; feeds: {list(feed_names)}"
                )
        elif get_rule(node.op) is None:
            raise GraphLoweringError(
                f"unsupported op {node.op!r} (node {node.name!r}); "
                "see ops.registry.registered_ops()"
            )

    # Constant subgraphs (no placeholder ancestors) are evaluated ONCE
    # here, at build time, and their host-numpy results baked into every
    # call. Two reasons beyond avoiding redundant recompute across
    # retraces/eval_shape probes: (a) shape arithmetic
    # (Shape -> StridedSlice -> Pack feeding a Reshape, the Keras
    # squeeze-excite pattern) must stay a compile-time fact — inside jit
    # the first jnp op would mint a tracer and a downstream `ctx.static`
    # would refuse a value that is in truth static; (b) XLA re-folds the
    # constants anyway, so there is no loss. ensure_compile_time_eval
    # guards the rare case of build_callable running under an outer
    # trace (it is a no-op otherwise).
    const_env: Dict[Tuple[str, int], Any] = {}
    folded: set = set()
    for node in order:
        if node.op in ("Placeholder", "PlaceholderV2"):
            continue
        ins: List[Any] = []
        ok = True
        for edge in node.inputs:
            dep, idx, ctrl = parse_edge(edge)
            if ctrl:
                continue
            if (dep, idx) not in const_env:
                ok = False
                break
            ins.append(const_env[(dep, idx)])
        if not ok:
            continue
        with jax.ensure_compile_time_eval():
            out = get_rule(node.op).fn(ctx, node, ins)
        if isinstance(out, tuple):
            for i, v in enumerate(out):
                const_env[(node.name, i)] = np.asarray(v)
        else:
            const_env[(node.name, 0)] = np.asarray(out)
        folded.add(node.name)

    def fn(*feed_arrays):
        if len(feed_arrays) != len(feed_pos):
            raise ValueError(
                f"expected {len(feed_pos)} feeds {list(feed_names)}, "
                f"got {len(feed_arrays)}"
            )
        env: Dict[Tuple[str, int], Any] = dict(const_env)
        for node in order:
            if node.name in folded:
                continue
            if node.op in ("Placeholder", "PlaceholderV2"):
                env[(node.name, 0)] = feed_arrays[feed_pos[node.name]]
                continue
            ins: List[Any] = []
            for edge in node.inputs:
                dep, idx, ctrl = parse_edge(edge)
                if ctrl:
                    continue  # purely functional: control edges are ordering-only
                key = (dep, idx)
                if key not in env:
                    raise GraphLoweringError(
                        f"node {node.name!r} consumes output {idx} of {dep!r} "
                        "which was not produced"
                    )
                ins.append(env[key])
            rule_fn = get_rule(node.op).fn
            if not any(isinstance(x, jax.core.Tracer) for x in ins):
                # Concrete at TRACE time but not at build time: the
                # Shape op returns a static numpy shape even for traced
                # inputs, so Shape -> StridedSlice -> Pack chains (the
                # Keras squeeze-excite reshape target) land here. They
                # must evaluate concretely or the first jnp op would
                # mint a tracer and a downstream `ctx.static` would
                # refuse a value that is in truth static. These are
                # per-specialization scalars — cheap — unlike the
                # weight-constant chains folded once above.
                with jax.ensure_compile_time_eval():
                    out = rule_fn(ctx, node, ins)
                out = (
                    tuple(np.asarray(v) for v in out)
                    if isinstance(out, tuple)
                    else np.asarray(out)
                )
            else:
                out = rule_fn(ctx, node, ins)
            if isinstance(out, tuple):
                for i, v in enumerate(out):
                    env[(node.name, i)] = v
            else:
                env[(node.name, 0)] = out
        results = []
        for f in fetches:
            name, idx, _ = parse_edge(f)
            key = (name, idx)
            if key not in env:
                raise GraphLoweringError(f"fetch {f!r} was not produced")
            results.append(env[key])
        return tuple(results)

    return fn
