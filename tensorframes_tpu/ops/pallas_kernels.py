"""Pallas TPU kernels for hot ops.

`flash_attention`: blockwise attention computed entirely in VMEM with
online softmax — O(seq) memory instead of the O(seq^2) score matrix.
Grid is (q_blocks, k_blocks); the k axis iterates sequentially (TPU grids
run minor-axis-last), carrying the running max / denominator / weighted
accumulator in VMEM scratch that persists across k iterations. Q·Kᵀ and
P·V ride the MXU via `jnp.dot(..., preferred_element_type=f32)`; masking
(causal + padded tail) happens on the VPU.

This kernel is the single-device building block the ring attention in
`parallel/ring.py` composes across chips (K/V rotation over ICI); it is
also used directly by `models.TransformerLM` for unsharded TPU runs. On
CPU it runs in Pallas interpret mode (tests) — production CPU paths use
`parallel.ring.full_attention`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc,
    *, scale: float, causal: bool, seq_len: int, blk_q: int, blk_k: int,
):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # Causal fast-skip: whole k-block strictly above the diagonal.
    needed = jnp.logical_or(
        not causal, j * blk_k <= i * blk_q + (blk_q - 1)
    )

    @pl.when(needed)
    def _step():
        q = q_ref[:].astype(jnp.float32)
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

        q_pos = i * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        k_pos = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        mask = k_pos < seq_len  # padded tail keys contribute nothing
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        # NB: f32-typed constants — x64-mode weak f64 literals trip Mosaic
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))

        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, jnp.float32(0.0))
        alpha = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = alpha * l_sc[:, 0] + jnp.sum(p, axis=-1)
        acc_sc[:] = alpha[:, None] * acc_sc[:] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_sc[:, 0] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        l = l_sc[:, 0]
        l = jnp.where(l == jnp.float32(0.0), jnp.float32(1.0), l)
        o_ref[:] = (acc_sc[:] / l[:, None]).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-device blockwise attention. q/k/v: (seq, head_dim)."""
    seq, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    blk_q = min(block_q, max(8, seq))
    blk_k = min(block_k, max(8, seq))
    pad_q = (-seq) % blk_q
    pad_k = (-seq) % blk_k
    qp = jnp.pad(q, ((0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, pad_k), (0, 0))) if pad_k else v
    nq = qp.shape[0] // blk_q
    nk = kp.shape[0] // blk_k

    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(
        _flash_kernel,
        scale=float(scale),
        causal=causal,
        seq_len=seq,
        blk_q=blk_q,
        blk_k=blk_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=(nq, nk),
        in_specs=[
            pl.BlockSpec((blk_q, d), lambda i, j: (i, jnp.int32(0))),
            pl.BlockSpec((blk_k, d), lambda i, j: (j, jnp.int32(0))),
            pl.BlockSpec((blk_k, d), lambda i, j: (j, jnp.int32(0))),
        ],
        out_specs=pl.BlockSpec((blk_q, d), lambda i, j: (i, jnp.int32(0))),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, 1), jnp.float32),  # running max
            pltpu.VMEM((blk_q, 1), jnp.float32),  # running denominator
            pltpu.VMEM((blk_q, d), jnp.float32),  # weighted accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:seq] if pad_q else out
