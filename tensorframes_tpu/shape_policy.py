"""Shape-bucketed block execution: bound XLA recompiles across ragged blocks.

The executor caches ONE lowered callable per ``(kind, graph, fetches,
feeds)`` key, but ``jax.jit`` still re-specializes (full XLA compile) for
every distinct concrete BLOCK SHAPE it sees — so uneven ``repartition``
remainders, filtered frames, and variable-size `reduce_blocks_stream`
chunks each pay full compile latency, which on TPU dwarfs the per-block
compute. A long-lived process whose block sizes drift is a recompile
storm the cache counters cannot even see (the same class of problem the
aggregate planner already solved for group sizes with its pow2 chunk
decomposition, and `_run_ragged_bucketed` solved for ragged cells).

This module is the block-level shape policy: every block feed is padded
up to a geometric row-bucket ladder (``config.shape_bucket_min`` *
``config.shape_bucket_growth``^k) by REPLICATING the last valid row, the
bucketed program executes, and the padding is removed semantically:

- map verbs slice the padded rows off every output (safe exactly when
  every fetch is a row-local transform — `rowwise_fetches` proves it
  with the same conservative op walk the aggregate chunk planner uses;
  anything else runs the ordinary unbucketed dispatch);
- per-block reduce stages mask the padded rows to the reduction
  identity at the TRANSFORM OUTPUT (sum→0, prod→1, min/max→±inf /
  integer extrema, mean via masked sum / true row count), so
  ``Sum(exp(x))`` stays exact — masking the *input* would feed
  ``exp(0)=1`` per pad row into the sum. Only graphs the structural
  classifier (`aggregate._chunk_combiners`) proves reducible this way
  are bucketed; the rest keep the exact unbucketed program.

Compile count per graph drops from O(#distinct block sizes) to
O(log_growth max-block-rows). Replicating the last row (instead of
zero-fill) keeps pad rows numerically ordinary, so ``check_numerics``
and non-total ops (Log, Reciprocal, ...) never see synthetic poison.

Exactness: map outputs, min/max, and integer-dtype reductions are
bit-identical to unbucketed eager execution. Float sum/mean reduce over
a wider (padded) axis, so XLA's vectorized accumulation may group the
REAL elements differently — the identical reassociation tolerance the
repo already documents for `_aggregate_segment` and for stacking block
partials; integer-valued float data stays bit-exact. Disable with
``config.update(shape_bucketing=False)`` when exact FP accumulation
order matters more than bounded compiles.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .aggregate import _chunk_combiners, _rowwise_transform
from .graph.ir import Graph, base_name as _base
from .ops.lowering import build_callable

__all__ = [
    "bucket_for",
    "bucket_ladder",
    "enabled",
    "observe_fill",
    "pad_feeds",
    "pad_lead",
    "slice_pad_rows",
    "rowwise_fetches",
    "MaskPlan",
    "masked_reduce_plan",
    "fused_mask_plan",
    "build_masked_reduce",
    "masked_callable",
    "dispatch_masked",
]


# ---------------------------------------------------------------------------
# bucket ladder
# ---------------------------------------------------------------------------


def bucket_for(
    n: int,
    growth: Optional[float] = None,
    min_bucket: Optional[int] = None,
) -> int:
    """Smallest ladder rung >= ``n``: ``min_bucket * growth^k`` rounded
    up to an int (each rung strictly larger than the last, so the
    ladder is finite for any growth > 1). ``n <= 0`` maps to 0 — empty
    blocks are never dispatched, bucketed or not."""
    from . import config as _config

    cfg = _config.get()
    g = float(growth if growth is not None else cfg.shape_bucket_growth)
    b = int(min_bucket if min_bucket is not None else cfg.shape_bucket_min)
    if g <= 1.0:
        raise ValueError(f"shape_bucket_growth must be > 1, got {g}")
    if b < 1:
        raise ValueError(f"shape_bucket_min must be >= 1, got {b}")
    if n <= 0:
        return 0
    while b < n:
        b = max(b + 1, int(-(-b * g // 1)))  # ceil(b * g), monotone
    return b


def bucket_ladder(
    max_rows: int,
    growth: Optional[float] = None,
    min_bucket: Optional[int] = None,
) -> List[int]:
    """The distinct rungs covering block sizes 1..max_rows — the bound
    on compiled shape specializations per program (benchmarks and tests
    assert against its length)."""
    rungs: List[int] = []
    n = 1
    while n <= max_rows:
        r = bucket_for(n, growth, min_bucket)
        rungs.append(r)
        n = r + 1
    return rungs


def enabled(executor=None) -> bool:
    """Bucketing is on for this dispatch: the config knob is set AND the
    executor opts in (`supports_bucketing`; both the in-process and the
    native executor do — the native host's per-shape-signature compile
    cache benefits identically)."""
    from . import config as _config

    if not _config.get().shape_bucketing:
        return False
    return executor is None or getattr(executor, "supports_bucketing", False)


# ---------------------------------------------------------------------------
# padding
# ---------------------------------------------------------------------------


def pad_lead(a, n: int, bucket: int):
    """Pad ``a``'s lead dim from ``n`` to ``bucket`` rows by replicating
    the last valid row (numerically ordinary pad rows — see module
    docstring). Device arrays pad with jnp (async, stays on device);
    host arrays with numpy."""
    if bucket <= n:
        return a
    import jax

    rep = (bucket - n,) + tuple(a.shape[1:])
    if isinstance(a, jax.Array):
        import jax.numpy as jnp

        return jnp.concatenate([a, jnp.broadcast_to(a[-1:], rep)])
    a = np.asarray(a)
    return np.concatenate([a, np.broadcast_to(a[-1:], rep)])


def observe_fill(n: int, bucket: int, verb: Optional[str] = None) -> None:
    """Record one bucketed dispatch's fill fraction (valid rows /
    rung rows) into the ``bucket_fill{verb=}`` histogram — exact-rung
    hits observe 1.0, so the distribution is the honest per-verb
    bucket-economics signal the workload profile and the future ladder
    autotuner consume. Gated on the telemetry master switch like every
    histogram; the verb label rides the ambient verb span."""
    from .utils import telemetry as _tele

    if bucket <= 0 or not _tele.enabled():
        return
    if verb is None:
        verb = _tele.current_verb() or "unattributed"
    _tele.histogram_observe(
        "bucket_fill", min(1.0, n / bucket), verb=verb
    )


def pad_feeds(feeds: Sequence, n: int) -> Tuple[List, int]:
    """Pad every feed's lead dim up to ``n``'s bucket. Returns
    ``(padded_feeds, bucket)``; when ``bucket == n`` the feeds pass
    through untouched (the already-on-a-rung fast path)."""
    b = bucket_for(n)
    observe_fill(n, b)
    if b == n:
        return list(feeds), n
    from .utils.profiling import count as _count

    _count("shape_bucketing.padded_dispatch")
    # pad waste observability: total synthetic rows dispatched (the
    # price paid for the bounded compile count — `diagnostics` readers
    # compare this against real row counters)
    _count("shape_bucketing.pad_rows", b - n)
    return [pad_lead(f, n, b) for f in feeds], b


def mesh_shard_plan(nrows: int, ndev: int):
    """Rung size + per-shard valid row counts for splitting ``nrows``
    into ``ndev`` contiguous bucket-rung shards — pure arithmetic, no
    data movement, so callers can decide ELIGIBILITY (e.g. the all-pad-
    shard gate in the mesh reduce) before paying for padded copies.
    ``valids[d]`` is 0 for shards that would be pure padding."""
    s = bucket_for(-(-nrows // ndev))
    valids = np.clip(nrows - s * np.arange(ndev), 0, s).astype(np.int32)
    return s, valids


def pad_mesh_shards(frame, cols_used: Sequence[str], ndev: int):
    """THE mesh padding recipe every bucketed `shard_map` verb shares:
    pad each used column so the frame splits into ``ndev`` contiguous
    shards of exactly one bucket rung (`mesh_shard_plan`) — `shard_map`
    then sees ONE static shape per rung and the varying ``rows % ndev``
    remainder-tail program disappears. Returns ``(main, tail,
    shard_rows, shard_valids)``; ``tail`` is empty by construction."""
    s, valids = mesh_shard_plan(frame.nrows, ndev)
    observe_fill(frame.nrows, s * ndev)
    main = {
        c: pad_lead(frame.column(c).values, frame.nrows, s * ndev)
        for c in set(cols_used)
    }
    tail = {c: main[c][:0] for c in main}
    return main, tail, s, valids


def slice_pad_rows(outs: Sequence, n: int, bucket: int) -> List:
    """Slice the pad rows back off a padded map dispatch's outputs (lazy
    device slices). An output that did not preserve the padded lead dim
    is returned untouched, so the caller's row-count validation can name
    it instead of a slice masking the contract violation."""
    if bucket == n:
        return list(outs)
    return [
        o[:n] if getattr(o, "ndim", 0) and o.shape[0] == bucket else o
        for o in outs
    ]


# ---------------------------------------------------------------------------
# map-safety classification (row-local graphs)
# ---------------------------------------------------------------------------


def rowwise_fetches(
    graph: Graph, fetches: Sequence[str], ph_ranks: Dict[str, int]
) -> bool:
    """True when every fetch is a row-local function of the placeholders:
    output row i depends only on input rows i (and on sub-lead-rank
    constants), so pad rows cannot perturb valid rows and slicing the
    output is a faithful inverse of padding the input. Delegates to the
    ONE shared walk (`aggregate._rowwise_transform` — the same check
    the chunk planner runs on reduce transforms), so map-bucketing
    eligibility cannot diverge from reduce-chunk eligibility. Anything
    unrecognized (reductions, matmuls, reshapes, control flow)
    conservatively disqualifies the graph; it simply runs unbucketed."""
    return _rowwise_transform(graph, list(fetches), ph_ranks.get)


# ---------------------------------------------------------------------------
# masked per-block reduce
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MaskPlan:
    """Per-fetch recipe for the masked bucketed reduce program: the edge
    feeding each root reduce node (the rowwise-transform output) and the
    reduction's monoid tag."""

    roots: Tuple[str, ...]
    combiners: Tuple[str, ...]


def _root_edge(graph: Graph, fetch: str) -> str:
    src, idx = graph[_base(fetch)].data_inputs()[0]
    return f"{src}:{idx}" if idx else src


def masked_reduce_plan(
    graph: Graph, fetch_list: Sequence[str], summary
) -> Optional[MaskPlan]:
    """Classify a reduce graph for bucketed execution. Piggybacks on the
    aggregate chunk classifier: every fetch must be a recognized monoid
    reduce (Sum/Min/Max/Prod, float Mean) over the lead axis of a
    row-local transform of its placeholder. Returns None (→ run the
    exact unbucketed program) otherwise."""
    combs = _chunk_combiners(graph, list(fetch_list), summary)
    if combs is None:
        return None
    return MaskPlan(
        tuple(_root_edge(graph, f) for f in fetch_list),
        tuple(combs[_base(f)] for f in fetch_list),
    )


def fused_mask_plan(
    fused_graph: Graph,
    fused_fetches: Sequence[str],
    combiners: Sequence[str],
    ph_ranks: Dict[str, int],
) -> Optional[MaskPlan]:
    """Mask plan for a FUSED lazy chain ending in a classified reduce:
    the reduce classification ran on the plain reduce graph, but in the
    fused graph each reduce root consumes the whole pending map chain —
    masking at that root is only valid when the chain is row-local, so
    the walk re-runs over the fused graph."""
    roots = [_root_edge(fused_graph, f) for f in fused_fetches]
    if not rowwise_fetches(fused_graph, roots, ph_ranks):
        return None
    return MaskPlan(tuple(roots), tuple(combiners))


def _mask_identity(comb: str, dtype):
    """The reduction identity pad rows mask to, dtype-aware (floats get
    ±inf for min/max, integers their extrema, bools the monoid unit)."""
    if comb in ("sum", "mean"):
        return np.zeros((), dtype)
    if comb == "prod":
        return np.ones((), dtype)
    dt = np.dtype(dtype)
    if comb == "min":
        if dt.kind == "b":
            return np.ones((), dt)  # True: the AND/min identity
        if dt.kind in ("i", "u"):
            return np.asarray(np.iinfo(dt).max, dt)
        return np.asarray(np.inf, dt)
    if comb == "max":
        if dt.kind == "b":
            return np.zeros((), dt)
        if dt.kind in ("i", "u"):
            return np.asarray(np.iinfo(dt).min, dt)
        return np.asarray(-np.inf, dt)
    raise AssertionError(f"unknown combiner {comb!r}")


def build_masked_reduce(
    graph: Graph, plan: MaskPlan, feed_names: Sequence[str]
):
    """Build ``fn(valid, *feeds) -> tuple(partials)``: run the rowwise
    transforms on the (padded) block, mask rows >= ``valid`` to each
    fetch's reduction identity, reduce over the lead axis. ``valid`` is
    a traced scalar, so ONE compiled program serves every true row count
    within a bucket. The reductions mirror the eager lowerings
    (`ops.standard`): Sum/Prod keep the input dtype, Mean divides the
    masked sum by the true count (the classifier already rejected
    integer Mean)."""
    raw = build_callable(graph, list(plan.roots), list(feed_names))
    combiners = plan.combiners

    def fn(valid, *feeds):
        import jax.numpy as jnp

        valid = jnp.asarray(valid).reshape(())  # shard callers pass (1,)
        outs = raw(*feeds)
        res = []
        for comb, o in zip(combiners, outs):
            o = jnp.asarray(o)
            m = (jnp.arange(o.shape[0]) < valid).reshape(
                (-1,) + (1,) * (o.ndim - 1)
            )
            masked = jnp.where(m, o, _mask_identity(comb, o.dtype))
            if comb == "sum":
                res.append(jnp.sum(masked, axis=0, dtype=o.dtype))
            elif comb == "mean":
                s = jnp.sum(masked, axis=0, dtype=o.dtype)
                # multiply by the reciprocal, NOT a true divide: the
                # eager `jnp.mean` divides by a compile-time constant
                # count, which XLA strength-reduces to multiplication by
                # the rounded reciprocal — reproducing that keeps masked
                # means bit-identical to eager ones
                res.append(
                    s * (jnp.asarray(1.0, o.dtype) / jnp.asarray(valid, o.dtype))
                )
            elif comb == "prod":
                res.append(jnp.prod(masked, axis=0, dtype=o.dtype))
            elif comb == "min":
                res.append(jnp.min(masked, axis=0))
            else:
                res.append(jnp.max(masked, axis=0))
        return tuple(res)

    return fn


def masked_callable(ex, graph: Graph, fetch_list, feed_names, plan: MaskPlan):
    """THE "block-bucketed" program constructor — every masked dispatch
    site (eager reduce_blocks, the fused lazy reduce terminal, the mesh
    reduce tail) goes through here so the cache kind, key components and
    calling convention stay identical by construction: that is what lets
    e.g. the mesh tail share the local verb's compiled entry."""
    import jax

    return ex.cached(
        "block-bucketed",
        graph,
        list(fetch_list),
        list(feed_names),
        lambda: jax.jit(build_masked_reduce(graph, plan, feed_names)),
    )


def dispatch_masked(fn, feeds: Sequence, n: int):
    """Run a masked bucketed program on one block: pad the feeds to the
    ladder and pass the true row count as the traced ``valid`` scalar."""
    feeds, _ = pad_feeds(feeds, n)
    return fn(np.int32(n), *feeds)
