"""Named endpoint registry: the serving runtime's control plane.

The paper's TensorFrames is a batch library — every invocation pays
graph normalization, analysis and XLA compile from cold, the same way
the reference re-imported its GraphDef into a fresh TF session per
Spark task (`DebugRowOps.scala:790`). A serving process inverts that:
programs are registered ONCE, validated against a declared column
schema, and compiled WARM across every bucket-ladder rung up to the
configured max batch — so steady-state traffic compiles nothing
(`Executor.jit_shape_compiles` flat, asserted by serving_bench), the
long-lived-session model of "TensorFlow: A system for large-scale
machine learning" (PAPERS.md).

An `Endpoint` is (name, graph, fetches, schema):

- ``register(name, fetches, schema)`` accepts everything `map_blocks`
  does (builder-DSL tensors, a `Graph`, GraphDef bytes / a file path
  with ``fetch_names=``) plus a `LazyFrame`/`LazyPlan` — a fused lazy
  chain built against a prototype frame becomes a servable program,
  its pending graph and feed wiring lifted verbatim.
- The declared schema (column -> dtype or (dtype, cell_shape)) is the
  serving contract: placeholders must resolve to schema columns with
  exact dtypes and compatible shapes AT REGISTRATION (the same
  `_match_columns` validation the verbs run per call), and every
  request is validated against it BEFORE entering the batching lane —
  one malformed request fails alone with a 400, never inside a
  coalesced batch where the error would poison its batch-mates.
- **Batchability is proven, not assumed**: an endpoint coalesces
  cross-request only when the shared row-local walk
  (`shape_policy.rowwise_fetches` — the same classifier that gates
  shape bucketing and OOM splitting) proves every fetch row-local.
  Then concat → dispatch → slice is bit-identical to per-request
  execution BY CONSTRUCTION, which is the batcher's correctness
  contract. Anything else still serves, one dispatch per request.

Registration is process-wide and thread-safe; `reset()` (tests) tears
down the registry AND the batching lanes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..frame import Column, TensorFrame
from ..graph.ir import Graph, base_name as _base
from ..schema import ColumnInfo, FrameInfo, ScalarType, Shape

__all__ = [
    "Endpoint",
    "register",
    "unregister",
    "get",
    "endpoints",
    "reset",
]

_lock = threading.Lock()
_endpoints: Dict[str, "Endpoint"] = {}


# ---------------------------------------------------------------------------
# schema normalization
# ---------------------------------------------------------------------------


def normalize_schema(schema) -> FrameInfo:
    """Normalize the declared request schema to a `FrameInfo`. Accepts a
    `FrameInfo` as-is, or a dict of column -> dtype-like (numpy dtype,
    dtype string, `ScalarType`) or (dtype-like, cell_shape)."""
    if isinstance(schema, FrameInfo):
        return schema
    if not isinstance(schema, dict) or not schema:
        raise TypeError(
            "serving schema must be a non-empty dict of column -> dtype "
            "or (dtype, cell_shape), or a FrameInfo; got "
            f"{type(schema).__name__}"
        )
    cols: List[ColumnInfo] = []
    for name, spec in schema.items():
        if isinstance(spec, ColumnInfo):
            cols.append(spec.with_name(name))
            continue
        if isinstance(spec, (tuple, list)) and len(spec) == 2:
            dtype_like, cell = spec
        else:
            dtype_like, cell = spec, ()
        if isinstance(dtype_like, ScalarType):
            st = dtype_like
        else:
            st = ScalarType.from_np_dtype(np.dtype(dtype_like))
        cols.append(ColumnInfo(name, st, Shape(tuple(cell))))
    return FrameInfo(cols)


def _schema_frame(info: FrameInfo, rows: int) -> TensorFrame:
    """A synthetic single-block frame matching the declared schema —
    what registration validates and warm-compiles against. Unknown cell
    dims materialize as 1 (documented: such endpoints get no
    zero-compile guarantee, real traffic picks its own widths)."""
    cols = []
    for ci in info:
        cell = tuple(1 if d is None else int(d) for d in ci.cell_shape.dims)
        if ci.dtype is ScalarType.string:
            data = np.array([b""] * rows, dtype=object)
        else:
            data = np.zeros((rows,) + cell, dtype=ci.dtype.np_dtype)
        cols.append(Column(ci.name, data, ci.dtype))
    return TensorFrame(cols)


# ---------------------------------------------------------------------------
# the endpoint
# ---------------------------------------------------------------------------


class Endpoint:
    """One registered serving program. Immutable after construction
    (replacing re-registers); holds the normalized graph, its fetch
    edges, the output naming, the feed wiring and the declared schema.

    ``run_frame`` is THE execution path — warm-up, unbatched requests
    and coalesced batch dispatches all go through it, so every one of
    them hits the identical compiled-program cache entries.
    """

    def __init__(
        self,
        name: str,
        graph: Graph,
        fetch_edges: Sequence[str],
        output_names: Sequence[str],
        feed_dict: Dict[str, str],
        schema: FrameInfo,
        outputs: FrameInfo,
        required_columns: Tuple[str, ...],
        batchable: bool,
        max_batch_rows: int,
        executor=None,
    ):
        self.name = name
        self.graph = graph
        self.fetch_edges = tuple(fetch_edges)
        self.output_names = tuple(output_names)
        self.feed_dict = dict(feed_dict)
        self.schema = schema
        self.outputs = outputs
        self.required_columns = tuple(required_columns)
        self.batchable = bool(batchable)
        self.max_batch_rows = int(max_batch_rows)
        self.executor = executor
        self.fingerprint = graph.fingerprint()
        self.warmed_rungs: Tuple[int, ...] = ()
        self.created_at = time.time()
        # per-endpoint batch-window override (milliseconds): None =
        # follow config.serve_batch_window_ms. Written by the
        # closed-loop autotuner (`runtime.autotune` — the batch-window
        # policy tunes each endpoint separately from the latency-vs-
        # fill histograms); the batcher reads it per batch, so a change
        # applies to the next window without restarting the lane.
        self.batch_window_ms: Optional[float] = None

    # -- request validation --------------------------------------------
    def validate_request(self, frame: TensorFrame) -> None:
        """Check one request frame against the declared schema BEFORE it
        enters a batching lane (a bad request must fail alone, not
        poison a coalesced batch). Raises ValueError."""
        if frame.nrows < 1:
            raise ValueError(
                f"endpoint {self.name!r}: request frame has no rows"
            )
        for col in self.required_columns:
            ci = self.schema[col]
            if col not in frame.info:
                raise ValueError(
                    f"endpoint {self.name!r}: request is missing column "
                    f"{col!r} (schema: {[c.name for c in self.schema]}; "
                    f"got: {frame.columns})"
                )
            got = frame.info[col]
            if got.dtype is not ci.dtype:
                raise ValueError(
                    f"endpoint {self.name!r}: column {col!r} has dtype "
                    f"{got.dtype.name} but the schema declares "
                    f"{ci.dtype.name} (TF graphs do not promote dtypes)"
                )
            if not got.cell_shape.check_more_precise_than(ci.cell_shape):
                raise ValueError(
                    f"endpoint {self.name!r}: column {col!r} with cell "
                    f"shape {got.cell_shape} is not compatible with the "
                    f"declared {ci.cell_shape}"
                )
            if not frame.column(col).is_dense:
                raise ValueError(
                    f"endpoint {self.name!r}: column {col!r} is ragged; "
                    "serving requests need uniform cells"
                )

    # -- execution ------------------------------------------------------
    def run_frame(
        self,
        frame: TensorFrame,
        timeout_s: Optional[float] = None,
        _use_cache: bool = True,
    ) -> TensorFrame:
        """Run the endpoint's program on ``frame`` and return ONLY the
        fetch outputs (renamed to the registered output names) — the
        response never echoes request columns back over the wire.

        When the materialization cache is on
        (``config.materialize_cache_bytes`` > 0), a repeated
        (request bytes, program, config) triple is served from the
        cache without dispatching — the RENAMED response frame is what
        gets keyed, so a hit is byte-for-byte the wire answer. Warm
        compiles pass ``_use_cache=False``: their synthetic frames must
        reach the device to build the jit cache, and their results are
        not real answers worth a cache slot."""
        from .. import api as _api
        from ..runtime import materialize as _mat

        cache_key = None
        if _use_cache and self.executor is None and _mat.enabled():
            data_fp = _mat.frame_fingerprint(frame)
            if data_fp is not None:
                plan_fp = _mat.plan_fingerprint(
                    self.fingerprint, self.feed_dict, self.output_names
                )
                hit = _mat.lookup(data_fp, plan_fp)
                if hit is not None:
                    return hit
                cache_key = (data_fp, plan_fp)
        t0 = time.perf_counter()
        res = _api.map_blocks(
            self.graph,
            frame,
            feed_dict=self.feed_dict or None,
            fetch_names=list(self.fetch_edges),
            executor=self.executor,
            timeout_s=timeout_s,
        )
        cols = [
            Column(out, res.column(_base(edge)).values)
            for out, edge in zip(self.output_names, self.fetch_edges)
        ]
        out_frame = TensorFrame(cols, offsets=[0, frame.nrows])
        if cache_key is not None:
            _mat.store(
                cache_key[0], cache_key[1], out_frame,
                ledger_fp=self.fingerprint,
                compute_s=time.perf_counter() - t0,
            )
        return out_frame

    # -- warm compile ---------------------------------------------------
    def warm(self) -> Tuple[int, ...]:
        """Compile every bucket-ladder rung up to ``max_batch_rows``
        (batchable endpoints only — the batcher pads every dispatch to a
        rung, so these are ALL the shapes steady-state traffic can
        produce; zero compiles afterwards, asserted via
        `jit_shape_compiles`). Non-batchable endpoints skip warming:
        they dispatch at raw request sizes that rung warming cannot
        cover."""
        from .. import shape_policy as _sp
        from ..utils import telemetry as _tele

        if not self.batchable:
            return ()
        rungs = tuple(_sp.bucket_ladder(self.max_batch_rows))
        t0 = time.perf_counter()
        with _tele.span(
            "serving.warm", kind="stage", endpoint=self.name,
            rungs=len(rungs),
        ):
            for rung in rungs:
                self.run_frame(
                    _schema_frame(self.schema, rung), _use_cache=False
                )
        self.warmed_rungs = rungs
        _tele.counter_inc(
            "serve_warm_rungs", float(len(rungs)), endpoint=self.name
        )
        from ..utils.log import get_logger

        get_logger("serving").info(
            "endpoint %r warm-compiled %d rung(s) up to %d rows in %.2fs",
            self.name, len(rungs), rungs[-1] if rungs else 0,
            time.perf_counter() - t0,
        )
        return rungs

    # -- introspection --------------------------------------------------
    def describe(self) -> dict:
        """JSON-friendly descriptor (the server's GET /serve listing)."""
        return {
            "name": self.name,
            "program": self.fingerprint,
            "batchable": self.batchable,
            "max_batch_rows": self.max_batch_rows,
            "batch_window_ms": self.batch_window_ms,
            "warmed_rungs": list(self.warmed_rungs),
            "columns": {
                ci.name: {
                    "dtype": ci.dtype.name,
                    "cell_shape": list(ci.cell_shape.dims),
                }
                for ci in self.schema
                if ci.name in self.required_columns
            },
            "outputs": {
                ci.name: {
                    "dtype": ci.dtype.name,
                    "cell_shape": list(ci.cell_shape.dims),
                }
                for ci in self.outputs
            },
        }

    def __repr__(self) -> str:
        return (
            f"Endpoint({self.name!r}, program {self.fingerprint[:12]}, "
            f"{'batchable' if self.batchable else 'unbatched'}, "
            f"max_batch_rows={self.max_batch_rows}, "
            f"{len(self.warmed_rungs)} warmed rung(s))"
        )


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------


def _normalize_program(fetches, fetch_names, feed_dict):
    """Resolve ``fetches`` to (graph, fetch_edges, output_names,
    feed_dict). Lazy plans carry their own feed wiring; everything else
    routes through the verbs' `_as_graph` normalization."""
    from .. import api as _api
    from ..lazy import LazyFrame, LazyPlan

    plan = None
    if isinstance(fetches, LazyFrame):
        plan = fetches.plan()
    elif isinstance(fetches, LazyPlan):
        plan = fetches
    if plan is not None:
        if feed_dict:
            raise ValueError(
                "register: feed_dict cannot be combined with a lazy "
                "plan — the plan carries its own placeholder->column "
                f"wiring ({plan.feeds})"
            )
        if not plan.sources:
            raise ValueError(
                "register: the lazy plan has no pending stages (nothing "
                "to serve); register the graph directly instead"
            )
        output_names = sorted(plan.sources)
        return (
            plan.graph,
            [plan.sources[c] for c in output_names],
            output_names,
            dict(plan.feeds),
        )
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    return graph, fetch_list, [_base(f) for f in fetch_list], dict(
        feed_dict or {}
    )


def register(
    name: str,
    fetches,
    schema,
    *,
    fetch_names: Optional[Sequence[str]] = None,
    feed_dict: Optional[Dict[str, str]] = None,
    max_batch_rows: Optional[int] = None,
    warm: Optional[bool] = None,
    executor=None,
    replace: bool = False,
) -> Endpoint:
    """Register a named serving endpoint: validate ``fetches`` against
    the declared ``schema``, classify batchability, warm-compile the
    bucket ladder, and make it servable via the micro-batcher / HTTP
    front-end. See the module docstring for the accepted program forms
    and the batchability contract."""
    from .. import api as _api
    from .. import config as _config
    from .. import shape_policy as _sp
    from ..graph.analysis import analyze_graph

    if not name or "/" in name or name != name.strip():
        raise ValueError(
            f"endpoint name {name!r} must be a non-empty path-safe token"
        )
    with _lock:
        dup = _endpoints.get(name)
    if dup is not None and not replace:
        # check the cheap precondition BEFORE the probe + warm compiles
        # (the authoritative re-check under the lock below still guards
        # the insert against a concurrent registration)
        raise ValueError(
            f"endpoint {name!r} is already registered (program "
            f"{dup.fingerprint[:12]}); pass replace=True to swap it"
        )
    info = normalize_schema(schema)
    graph, fetch_edges, output_names, feeds = _normalize_program(
        fetches, fetch_names, feed_dict
    )
    if not fetch_edges:
        raise ValueError(f"endpoint {name!r}: no fetches to serve")
    if len(set(output_names)) != len(output_names):
        raise ValueError(
            f"endpoint {name!r}: duplicate output names {output_names}"
        )

    # the SAME validation the verbs run per call, against a synthetic
    # schema frame — a registration-time failure names the endpoint
    probe = _schema_frame(info, 2)
    try:
        overrides = _api._ph_overrides(graph, probe, feeds, block_level=True)
        summary = analyze_graph(
            graph, list(fetch_edges), placeholder_shapes=overrides
        )
        mapping = _api._match_columns(summary, probe, feeds, block_level=True)
    except Exception as e:
        raise ValueError(
            f"endpoint {name!r}: program does not fit the declared "
            f"schema: {e}"
        ) from e

    out_cols = []
    for out, edge in zip(output_names, fetch_edges):
        ns = summary.outputs[_base(edge)]
        if ns.shape.rank == 0:
            raise ValueError(
                f"endpoint {name!r}: fetch {out!r} is a scalar — serving "
                "programs must be row-preserving maps (one output row "
                "per request row); reduce-shaped programs cannot be "
                "served"
            )
        out_cols.append(ColumnInfo(out, ns.dtype, ns.shape.tail))
    outputs = FrameInfo(out_cols)

    batchable = _sp.rowwise_fetches(
        graph,
        list(fetch_edges),
        {p: ph.shape.rank for p, ph in summary.inputs.items()},
    )
    cfg = _config.get()
    mbr = int(
        max_batch_rows
        if max_batch_rows is not None
        else cfg.serve_max_batch_rows
    )
    if mbr < 1:
        raise ValueError(f"max_batch_rows must be >= 1, got {mbr}")
    ep = Endpoint(
        name=name,
        graph=graph,
        fetch_edges=fetch_edges,
        output_names=output_names,
        feed_dict=feeds,
        schema=info,
        outputs=outputs,
        required_columns=tuple(sorted(set(mapping.values()))),
        batchable=batchable,
        max_batch_rows=mbr,
        executor=executor,
    )
    # probe run: serving is row-preserving map execution, and only an
    # actual dispatch proves it (a reduce-shaped program passes static
    # validation but changes the row count) — one tiny compile at
    # registration beats a 500 on the first live request
    try:
        probe_out = ep.run_frame(_schema_frame(info, 2))
    except Exception as e:
        raise ValueError(
            f"endpoint {name!r}: probe execution failed — serving "
            f"programs must be row-preserving maps over the schema "
            f"columns: {e}"
        ) from e
    if probe_out.nrows != 2:
        raise ValueError(
            f"endpoint {name!r}: program changed the row count "
            f"(2 -> {probe_out.nrows}); serving programs must be "
            "row-preserving"
        )
    if warm if warm is not None else cfg.serve_warm_compile:
        ep.warm()

    from .batcher import batcher as _the_batcher

    with _lock:
        old = _endpoints.get(name)
        if old is not None and not replace:
            raise ValueError(
                f"endpoint {name!r} is already registered (program "
                f"{old.fingerprint[:12]}); pass replace=True to swap it"
            )
        _endpoints[name] = ep
    if old is not None:
        _the_batcher().drop(name)
    from ..utils import telemetry as _tele

    _tele.counter_inc("serve_endpoints_registered", 1.0)
    return ep


def get(name: str) -> Endpoint:
    """Look up a registered endpoint; KeyError (→ HTTP 404) if absent."""
    with _lock:
        try:
            return _endpoints[name]
        except KeyError:
            raise KeyError(
                f"no serving endpoint {name!r} (registered: "
                f"{sorted(_endpoints)})"
            ) from None


def endpoints() -> List[dict]:
    """Descriptors of every registered endpoint (the listing route)."""
    with _lock:
        eps = list(_endpoints.values())
    return [ep.describe() for ep in eps]


def unregister(name: str) -> bool:
    """Remove an endpoint and tear down its batching lanes; True when
    something was removed. In-flight requests finish (the lane drains
    before its thread exits); new requests get a 404."""
    with _lock:
        ep = _endpoints.pop(name, None)
    if ep is None:
        return False
    from .batcher import batcher as _the_batcher

    _the_batcher().drop(name)
    return True


def reset() -> None:
    """Test hook: forget every endpoint and stop every batching lane."""
    with _lock:
        _endpoints.clear()
    from .batcher import batcher as _the_batcher

    _the_batcher().shutdown()
