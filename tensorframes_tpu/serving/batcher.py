"""Cross-request micro-batching: many small requests, one bucketed dispatch.

A serving workload is thousands of concurrent SMALL evaluations of the
same few programs — dispatch overhead per request (Python verb entry,
jit call, H2D) dwarfs the per-row compute the way per-row session.run
dwarfed it in the reference. This module coalesces concurrent requests
per ``(endpoint, program fingerprint)`` into ONE dispatch, the serving
analogue of the ingest engine's stage overlap:

- Requests queue into a per-key **lane**; the lane's dispatcher thread
  holds an open batch for ``config.serve_batch_window_ms``, closing
  EARLY the moment the row total lands exactly on a bucket-ladder rung
  (padding waste zero — waiting longer could only push the batch into
  the next rung) or reaches ``max_batch_rows``. A single oversized
  request dispatches alone.
- The closed batch concatenates request rows, pads to the rung with
  `shape_policy.pad_lead` (so the dispatch shape is ALWAYS a warmed
  rung, independent of the global ``shape_bucketing`` knob), runs the
  endpoint's program through the ordinary verb path — block scheduler
  placement, admission control (the coalesced dispatch takes ONE
  admission slot: batching composes with, not around, the PR 9 gate),
  fault handling — and scatters per-request row slices back through
  `concurrent.futures.Future`s.
- **Bit-identity**: the registry only marks endpoints batchable when
  the shared row-local walk proves every fetch row-local, so output
  row i is a function of input row i alone — concat + dispatch + slice
  is bit-identical to per-request execution by construction
  (serving_bench asserts it against direct verb calls).

Overload: a lane whose queue exceeds ``config.serve_queue_limit``
sheds new arrivals immediately with the same typed `OverloadError` the
admission controller uses (retry-after derived from the live
``verb_seconds`` histogram) — the HTTP front-end maps it to 429 +
``Retry-After``. Deadlines: each queued request carries its caller's
ambient absolute deadline; the batch runs under the LOOSEST member
budget (a tight-budget member that cannot wait raises its own
`DeadlineExceeded` at the waiter, never dragging batch-mates down),
and a waiter that gives up cancels its future so an unstarted request
is dropped instead of computed for nobody.

Telemetry (always-live): ``serve_requests{endpoint=}`` /
``serve_batches{endpoint=}`` / ``serve_shed{endpoint=}`` counters,
``serve_batch_rows`` / ``serve_batch_fill`` / ``serve_queue_seconds``
histograms, registered ``serve_pending`` gauge.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..frame import Column, TensorFrame
from ..runtime import deadline as _dl

__all__ = ["MicroBatcher", "batcher"]


def _window_s(endpoint, cfg) -> float:
    """Effective coalescing window for one endpoint, in seconds: the
    endpoint's own ``batch_window_ms`` (the autotuner's per-endpoint
    override) when set, else the global config knob. Read per batch,
    so a tuned change applies to the next window immediately — and an
    operator PIN of the global knob (explicit update/override/env)
    wins over any previously tuned endpoint value at read time, so
    pinning after the tuner ran still takes effect everywhere."""
    w = getattr(endpoint, "batch_window_ms", None)
    if w is None or _window_pinned():
        w = float(getattr(cfg, "serve_batch_window_ms", 0.0))
    return float(w) / 1e3


def _window_pinned() -> bool:
    try:
        from .. import config as _config

        return _config.is_explicit("serve_batch_window_ms")
    except Exception:
        return False


class _Request:
    __slots__ = (
        "frame", "rows", "future", "request_id", "deadline_at", "t_enq",
    )

    def __init__(self, frame, rows, future, request_id, deadline_at):
        self.frame = frame
        self.rows = rows
        self.future = future
        self.request_id = request_id
        self.deadline_at = deadline_at  # absolute monotonic, or None
        self.t_enq = time.monotonic()


class _Lane:
    """One (endpoint, program) batching lane: a bounded queue drained by
    a dedicated daemon dispatcher thread."""

    def __init__(self, key: Tuple[str, str], endpoint):
        self.key = key
        self.endpoint = endpoint
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.queue: deque = deque()
        self.stop = False
        self.thread: Optional[threading.Thread] = None

    def depth(self) -> int:
        return len(self.queue)  # GIL-atomic len; gauge read, see deadline


class MicroBatcher:
    """Process-wide batcher: one lane per (endpoint, fingerprint)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._lanes: Dict[Tuple[str, str], _Lane] = {}
        # accounting (under self._lock)
        self.requests = 0
        self.batches = 0
        self.batched_requests = 0
        self.inline = 0
        self.shed = 0

    # -- introspection --------------------------------------------------
    def pending(self) -> int:
        with self._lock:
            lanes = list(self._lanes.values())
        return sum(lane.depth() for lane in lanes)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "lanes": len(self._lanes),
                "pending": sum(l.depth() for l in self._lanes.values()),
                "requests": self.requests,
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "inline": self.inline,
                "shed": self.shed,
            }

    # -- the entry point ------------------------------------------------
    def submit(
        self,
        endpoint,
        frame: TensorFrame,
        request_id: Optional[str] = None,
        validate: bool = True,
    ) -> Future:
        """Queue one request; returns a Future resolving to the
        endpoint's outputs-only response frame. Validation errors and
        lane overload raise synchronously (the caller maps them to
        400 / 429); execution errors surface through the future.

        The caller's ambient deadline (`runtime.deadline`) rides along:
        it bounds the batch dispatch and the caller's own wait."""
        from ..utils import telemetry as _tele

        if validate:
            endpoint.validate_request(frame)
        with self._lock:
            self.requests += 1
        _tele.counter_inc("serve_requests", 1.0, endpoint=endpoint.name)

        from .. import config as _config

        cfg = _config.get()
        window_s = _window_s(endpoint, cfg)
        fut: Future = Future()

        if not endpoint.batchable or window_s <= 0.0:
            # unbatched: run inline on the caller's thread, under the
            # caller's own scope — one request, one dispatch, one slot
            with self._lock:
                self.inline += 1
            if not fut.set_running_or_notify_cancel():
                return fut
            try:
                fut.set_result(endpoint.run_frame(frame))
            except BaseException as e:
                fut.set_exception(e)
            return fut

        scope = _dl.current_scope()
        deadline_at = None
        if scope is not None and scope.deadline is not None:
            deadline_at = scope.deadline.at
        req = _Request(frame, frame.nrows, fut, request_id, deadline_at)

        qlimit = int(getattr(cfg, "serve_queue_limit", 0) or 0)
        while True:
            lane = self._lane(endpoint)
            with lane.cond:
                if lane.stop:
                    # lost a race with drop()/shutdown(): the dispatcher
                    # may already have drained and exited — an append
                    # here would never resolve. Re-fetch; _lane() makes
                    # a fresh lane for a stopped one.
                    continue
                if qlimit > 0 and len(lane.queue) >= qlimit:
                    with self._lock:
                        self.shed += 1
                    _tele.counter_inc(
                        "serve_shed", 1.0, endpoint=endpoint.name
                    )
                    depth = len(lane.queue)
                    mean = _dl._mean_verb_seconds()
                    retry_after = max(0.001, (mean or 0.05) * (depth + 1))
                    raise _dl.OverloadError(
                        f"endpoint {endpoint.name!r}: batching lane full "
                        f"— {depth} request(s) queued (limit {qlimit}); "
                        f"retry in ~{retry_after:.3f}s",
                        queue_depth=depth, limit=qlimit,
                        retry_after_s=retry_after,
                    )
                lane.queue.append(req)
                lane.cond.notify()
            return fut

    # -- lanes ----------------------------------------------------------
    def _lane(self, endpoint) -> _Lane:
        key = (endpoint.name, endpoint.fingerprint)
        with self._lock:
            lane = self._lanes.get(key)
            if lane is None or lane.stop:
                lane = _Lane(key, endpoint)
                lane.thread = threading.Thread(
                    target=self._run_lane,
                    args=(lane,),
                    daemon=True,
                    name=f"tfs-serve-{endpoint.name}",
                )
                self._lanes[key] = lane
                lane.thread.start()
            return lane

    def drop(self, endpoint_name: str) -> None:
        """Stop every lane of one endpoint (unregister / replace):
        queued requests still dispatch — the lane drains before its
        thread exits."""
        with self._lock:
            doomed = [
                lane for key, lane in self._lanes.items()
                if key[0] == endpoint_name
            ]
            for lane in doomed:
                self._lanes.pop(lane.key, None)
        for lane in doomed:
            with lane.cond:
                lane.stop = True
                lane.cond.notify_all()
            lane.thread.join(timeout=30.0)

    def shutdown(self) -> None:
        """Stop every lane (tests / process teardown). Queued requests
        drain through one final dispatch per lane."""
        with self._lock:
            lanes = list(self._lanes.values())
            self._lanes.clear()
            self.requests = self.batches = 0
            self.batched_requests = self.inline = self.shed = 0
        for lane in lanes:
            with lane.cond:
                lane.stop = True
                lane.cond.notify_all()
        for lane in lanes:
            lane.thread.join(timeout=30.0)

    # -- the dispatcher -------------------------------------------------
    def _run_lane(self, lane: _Lane) -> None:
        from .. import config as _config
        from .. import shape_policy as _sp

        ep = lane.endpoint
        while True:
            with lane.cond:
                while not lane.queue and not lane.stop:
                    lane.cond.wait(0.25)
                if not lane.queue and lane.stop:
                    return
                cfg = _config.get()
                window_s = _window_s(ep, cfg)
                max_rows = ep.max_batch_rows
                t_close = time.monotonic() + window_s
                batch: List[_Request] = []
                rows = 0
                while True:
                    while lane.queue:
                        r = lane.queue[0]
                        if batch and rows + r.rows > max_rows:
                            break  # r starts the NEXT batch
                        lane.queue.popleft()
                        batch.append(r)
                        rows += r.rows
                    if rows >= max_rows:
                        break
                    # rung-fill early close: exactly on a ladder rung,
                    # more coalescing could only cost the next rung
                    if rows and rows == _sp.bucket_for(rows):
                        break
                    if lane.stop:
                        break
                    left = t_close - time.monotonic()
                    if left <= 0.0:
                        break
                    lane.cond.wait(left)
            if batch:
                self._dispatch(ep, batch)

    def _dispatch(self, ep, batch: List[_Request]) -> None:
        from .. import shape_policy as _sp
        from ..utils import telemetry as _tele

        now = time.monotonic()
        # claim the futures at dispatch time (not enqueue): a waiter
        # whose deadline expired while queued has cancel()led — drop it
        # here instead of computing rows nobody will read
        live = [r for r in batch if r.future.set_running_or_notify_cancel()]
        if not live:
            return
        for r in live:
            _tele.histogram_observe(
                "serve_queue_seconds", max(0.0, now - r.t_enq)
            )
        rows = sum(r.rows for r in live)
        # the batch runs under the LOOSEST member budget: a member with
        # a tighter one gives up at its own waiter without dragging its
        # batch-mates down; any unbounded member leaves the batch on
        # the config default (verb entry still applies it)
        timeout_s = None
        deadlines = [r.deadline_at for r in live]
        if all(d is not None for d in deadlines):
            timeout_s = max(0.001, max(deadlines) - now)
        try:
            # ONE single-block frame of exactly the program's columns —
            # whatever block structure the requests arrived with, the
            # coalesced dispatch is one block on one warmed shape
            cols = []
            for c in ep.required_columns:
                parts = [
                    np.asarray(r.frame.column(c).values) for r in live
                ]
                cols.append(
                    Column(
                        c,
                        parts[0] if len(parts) == 1
                        else np.concatenate(parts),
                    )
                )
            base = TensorFrame(cols, offsets=[0, rows])
            # pad to the rung OURSELVES (replicated last row, the
            # numerically-ordinary pad `shape_policy` documents) so the
            # dispatch shape is a warmed rung regardless of the global
            # shape_bucketing knob; the pad tail is sliced off with the
            # scatter below
            rung = _sp.bucket_for(rows)
            # serving's own bucket-economics signal: fill fraction of
            # the rung this coalesced batch pads to, labeled per
            # endpoint (the batch-window autotuner reads it next to
            # serve_batch_rows/serve_queue_seconds). Observed ONLY for
            # rung-shaped dispatches — an oversized request dispatches
            # unpadded at its exact shape, so there is no rung fill to
            # report (the inner verb's own pad accounting covers it).
            # NB the padded frame below dispatches exactly on a rung,
            # so the inner verb records fill=1.0 under its OWN label —
            # true by construction: serving absorbs the pad waste here
            # and the map-level dispatch genuinely wastes nothing.
            if rows == rung or (rung > rows and rows <= ep.max_batch_rows):
                _sp.observe_fill(rows, rung, verb=f"serve:{ep.name}")
            if rung > rows and rows <= ep.max_batch_rows:
                padded = TensorFrame(
                    [
                        Column(
                            c,
                            _sp.pad_lead(base.column(c).values, rows, rung),
                        )
                        for c in ep.required_columns
                    ],
                    offsets=[0, rung],
                )
            else:
                padded = base
            ids = ",".join(
                r.request_id for r in live if r.request_id
            ) or None
            ctx = (
                _tele.request_scope(ids) if ids is not None
                else _nullcontext()
            )
            with ctx:
                out = ep.run_frame(padded, timeout_s=timeout_s)
            with self._lock:
                self.batches += 1
                self.batched_requests += len(live)
            _tele.counter_inc("serve_batches", 1.0, endpoint=ep.name)
            _tele.histogram_observe("serve_batch_rows", float(rows))
            _tele.histogram_observe("serve_batch_fill", float(len(live)))
            # scatter: per-request row slices of every output column
            out_vals = [
                (name, out.column(name).values) for name in ep.output_names
            ]
            lo = 0
            for r in live:
                hi = lo + r.rows
                res = TensorFrame(
                    [Column(name, v[lo:hi]) for name, v in out_vals],
                    offsets=[0, r.rows],
                )
                lo = hi
                try:
                    r.future.set_result(res)
                except Exception:
                    pass  # waiter gone; nothing to tell
        except BaseException as e:  # typed errors flow to every member
            for r in live:
                try:
                    r.future.set_exception(e)
                except Exception:
                    pass  # cancelled waiter: the error has no audience


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


_batcher = MicroBatcher()


def batcher() -> MicroBatcher:
    """The process-wide micro-batcher."""
    return _batcher


# live pending-request gauge: registered like the admission gauges
# (evaluated at export, survives telemetry.reset())
def _register_gauge() -> None:
    try:
        from ..utils import telemetry as _tele

        _tele.gauge_register(
            "serve_pending", lambda: float(_batcher.pending())
        )
    except Exception:  # pragma: no cover - telemetry always importable
        pass


_register_gauge()
