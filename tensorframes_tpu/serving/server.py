"""HTTP front-end: Arrow IPC request/response over the shared endpoint.

Mounts onto the ONE process HTTP server (`utils.telemetry_http` — the
same ThreadingHTTPServer that serves /metrics and /healthz, so the
serving data plane and its observability surface share a port) via the
route-mount hook:

- ``POST /serve/<endpoint>`` — body: Arrow IPC stream bytes of the
  request frame (`io.frame_to_ipc_bytes` framing); response: Arrow IPC
  stream bytes of the outputs-only result frame. Headers:

  - ``X-TFS-Timeout-S`` (request) — per-request budget; enters a
    `deadline_scope`, so everything the request triggers (queueing,
    the coalesced dispatch, the response wait) shares one clock.
    Defaults to ``config.serve_default_timeout_s`` — a serving request
    is NEVER unbounded.
  - ``X-TFS-Request-Id`` (request, optional) — echoed back, stamped as
    the ``request=`` label on every verb span the request triggers
    (batched dispatches carry the joined ids), so `tfs.diagnostics()`
    and Chrome traces attribute work per request.

- ``GET /serve`` — JSON listing: registered endpoints (schemas,
  batchability, warmed rungs) + live batcher accounting.

Error mapping (typed, never a hang):

| raised                      | HTTP | extra                          |
|-----------------------------|------|--------------------------------|
| `OverloadError` (lane full, | 429  | ``Retry-After`` (whole s) from |
|  admission shed)            |      | the live latency histograms    |
| `DeadlineExceeded`          | 504  | budget/elapsed in the body     |
| `Cancelled`                 | 503  |                                |
| unknown endpoint            | 404  |                                |
| schema/body validation      | 400  |                                |
| anything else               | 500  |                                |

Security posture is the telemetry endpoint's: 127.0.0.1 by default, no
auth, exposing it further is a deliberate operator decision.
"""

from __future__ import annotations

import json
import math
import threading
import uuid
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, Optional, Tuple

from ..runtime import deadline as _dl

__all__ = [
    "serve",
    "active",
    "draining",
    "set_draining",
    "ServingHandle",
    "ARROW_CONTENT_TYPE",
    "PREFIX",
]

PREFIX = "/serve"
ARROW_CONTENT_TYPE = "application/vnd.apache.arrow.stream"

_lock = threading.Lock()
_handle: Optional["ServingHandle"] = None

# Rolling-restart readiness (`tfs.serving.drain()`): while set, NEW
# serving requests shed with 503 and /healthz reports ready=false, so
# an external balancer stops routing here while in-flight batcher
# lanes finish. Cleared by serve() (a remount is a fresh replica) and
# serving.reset().
_draining = threading.Event()


def draining() -> bool:
    """True while `tfs.serving.drain()` is shedding new requests."""
    return _draining.is_set()


def set_draining(on: bool) -> None:
    if on:
        _draining.set()
    else:
        _draining.clear()


def _error_body(e: BaseException, **extra) -> bytes:
    payload = {"error": type(e).__name__, "message": str(e)}
    payload.update(extra)
    return json.dumps(payload).encode()


def _json(obj) -> Tuple[int, str, bytes, None]:
    return 200, "application/json", json.dumps(obj).encode(), None


def _handle_run(
    name: str, headers, body: bytes
) -> Tuple[int, str, bytes, Optional[Dict[str, str]]]:
    from .. import config as _config
    from ..io import frame_from_ipc_bytes, frame_to_ipc_bytes
    from ..utils import telemetry as _tele
    from .batcher import batcher as _the_batcher
    from . import registry as _registry

    rid = headers.get("X-TFS-Request-Id") or f"req-{uuid.uuid4().hex[:12]}"
    echo = {"X-TFS-Request-Id": rid}
    if _draining.is_set():
        # rolling restart: shed BEFORE any work — the balancer already
        # sees ready=false on /healthz; stragglers get a typed 503
        return 503, "application/json", json.dumps(
            {
                "error": "Draining",
                "message": (
                    "serving is draining for a rolling restart; retry "
                    "against another replica"
                ),
                "draining": True,
            }
        ).encode(), echo
    try:
        ep = _registry.get(name)
    except KeyError as e:
        return 404, "application/json", _error_body(e), echo
    try:
        timeout_hdr = headers.get("X-TFS-Timeout-S")
        timeout_s = (
            float(timeout_hdr)
            if timeout_hdr
            else float(_config.get().serve_default_timeout_s)
        )
        if not (timeout_s > 0):
            raise ValueError(
                f"X-TFS-Timeout-S must be > 0, got {timeout_s!r}"
            )
        if not body:
            raise ValueError("empty request body (expected Arrow IPC bytes)")
        frame = frame_from_ipc_bytes(body)
    except Exception as e:
        return 400, "application/json", _error_body(e), echo

    try:
        with _tele.request_scope(rid):
            with _dl.deadline_scope(
                timeout_s=timeout_s, verb=f"serve:{name}"
            ) as scope:
                # validates synchronously (a bad request fails alone,
                # before it can join a batch), may shed synchronously
                fut = _the_batcher().submit(ep, frame, request_id=rid)
                rem = scope.remaining()
                try:
                    result = fut.result(timeout=rem)
                except _FutureTimeout:
                    # give up our queue slot if the batch has not
                    # claimed it; the dispatcher drops cancelled work
                    fut.cancel()
                    raise _dl.DeadlineExceeded(
                        f"serve:{name}: request {rid} exceeded its "
                        f"budget ({timeout_s:.3f}s) waiting for dispatch",
                        verb=f"serve:{name}", budget_s=timeout_s,
                    )
        out = frame_to_ipc_bytes(result)
        return 200, ARROW_CONTENT_TYPE, out, echo
    except _dl.OverloadError as e:
        hdrs = dict(echo)
        hdrs["Retry-After"] = str(max(1, math.ceil(e.retry_after_s)))
        _incident(e, name, rid, 429)
        return 429, "application/json", _error_body(
            e,
            retry_after_s=e.retry_after_s,
            queue_depth=e.queue_depth,
            limit=e.limit,
        ), hdrs
    except _dl.DeadlineExceeded as e:
        _incident(e, name, rid, 504)
        return 504, "application/json", _error_body(
            e, budget_s=e.budget_s, elapsed_s=e.elapsed_s
        ), echo
    except _dl.Cancelled as e:
        _incident(e, name, rid, 503)
        return 503, "application/json", _error_body(e), echo
    except ValueError as e:
        return 400, "application/json", _error_body(e), echo
    except Exception as e:
        _incident(e, name, rid, 500)
        return 500, "application/json", _error_body(e), echo


def _incident(e: BaseException, name: str, rid: str, status: int) -> None:
    """Flight-recorder hook for a request mapped to an error status.
    Faults already captured at the verb layer are stamped with
    ``tfs_incident_id`` and dedup to the same bundle; a server-side
    failure (batcher future, IPC encode, a fresh 504 built here) gets
    its first capture with the serving context attached."""
    try:
        from ..runtime import blackbox as _blackbox

        _blackbox.capture(
            "serving", e, verb=f"serve:{name}",
            extra={"endpoint": name, "request_id": rid, "status": status},
        )
    except Exception:
        pass  # the recorder must never turn a 5xx into a crash


def _route(method: str, path: str, headers, body: bytes):
    """The mounted handler (`telemetry_http.mount` signature)."""
    from .batcher import batcher as _the_batcher
    from . import registry as _registry

    sub = path[len(PREFIX):].strip("/")
    if method == "GET":
        if not sub:
            return _json(
                {
                    "service": "tensorframes_tpu serving",
                    "draining": _draining.is_set(),
                    "endpoints": _registry.endpoints(),
                    "batcher": _the_batcher().snapshot(),
                }
            )
        try:
            return _json(_registry.get(sub).describe())
        except KeyError as e:
            return 404, "application/json", _error_body(e), None
    if method == "POST":
        if not sub or "/" in sub:
            return 404, "application/json", _error_body(
                KeyError(f"POST {path!r}: expected {PREFIX}/<endpoint>")
            ), None
        return _handle_run(sub, headers, body)
    return 405, "application/json", _error_body(
        ValueError(f"method {method} not allowed on {path!r}")
    ), None


class ServingHandle:
    """Handle to the mounted serving front-end. ``url`` points at the
    ``/serve`` prefix on the shared process server; ``close()``
    unmounts the routes (the shared server keeps running — stop it with
    ``tfs.telemetry.shutdown()``)."""

    def __init__(self, server):
        self._server = server
        self.host = server.host
        self.port = server.port

    @property
    def url(self) -> str:
        return f"{self._server.url}{PREFIX}"

    @property
    def running(self) -> bool:
        return self._server.running

    def close(self) -> None:
        global _handle
        from ..utils import telemetry_http as _http

        _http.unmount(PREFIX)
        with _lock:
            if _handle is self:
                _handle = None


def serve(
    port: Optional[int] = None, host: Optional[str] = None
) -> ServingHandle:
    """Mount the serving routes on the process HTTP server (starting it
    if none is running — ``port=0`` binds an ephemeral port) and return
    the handle. Registered endpoints become immediately servable; the
    same port keeps serving /metrics, /healthz, /diagnostics, /trace —
    the serving data plane and its autoscaling signals are one
    surface."""
    from ..utils import telemetry_http as _http

    srv = _http.active_server()
    if srv is None or not srv.running:
        srv = _http.serve(port=port if port is not None else 0, host=host)
    elif port not in (None, 0, srv.port):
        raise RuntimeError(
            f"process HTTP server already bound to port {srv.port}; "
            f"cannot serve on {port} (tfs.telemetry.shutdown() first)"
        )
    _http.mount(PREFIX, _route, replace=True)
    _draining.clear()  # a (re)mounted front-end is a ready replica
    handle = ServingHandle(srv)
    global _handle
    with _lock:
        _handle = handle
    from ..utils.log import get_logger

    get_logger("serving").info(
        "serving front-end mounted at %s (POST %s/<endpoint>)",
        handle.url, PREFIX,
    )
    return handle


def active() -> Optional[ServingHandle]:
    """The mounted front-end, if any."""
    with _lock:
        return _handle
