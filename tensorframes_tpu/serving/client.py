"""Stdlib HTTP client for the serving front-end.

Speaks the same wire format as `serving.server` (both ends share the
`io.frame_to_ipc_bytes` / `frame_from_ipc_bytes` helpers, so framing
cannot drift) and re-raises the server's typed errors AS the library's
own types: a 429 becomes `tfs.OverloadError` carrying the server's
retry-after hint, a 504 becomes `tfs.DeadlineExceeded` — remote and
in-process callers handle overload and deadline expiry with the SAME
except clauses. Everything else raises `ServingError` with the status
and decoded body.

Zero dependencies beyond the stdlib + pyarrow (already required by the
io layer): ``http.client`` with one connection per call — boring,
thread-safe, and enough for the paper-scale front-end; a production
deployment fronts this with a real load balancer anyway.
"""

from __future__ import annotations

import json
from http.client import HTTPConnection
from typing import Dict, Optional
from urllib.parse import urlparse

from ..frame import TensorFrame
from ..runtime.deadline import DeadlineExceeded, OverloadError

__all__ = ["ServingClient", "ServingError"]


class ServingError(RuntimeError):
    """Non-typed serving failure: carries ``status`` and the decoded
    ``body`` dict (or raw text) the server returned."""

    # the typed retryable cases already re-raise as OverloadError /
    # DeadlineExceeded; what is left (4xx/5xx bodies) does not improve
    # on a blind re-send — and a relayed server traceback containing a
    # status token must never pattern-match into the transient class
    tfs_fault_class = "deterministic"

    def __init__(self, message: str, status: int, body):
        super().__init__(message)
        self.status = int(status)
        self.body = body


def _decode_error(status: int, raw: bytes):
    try:
        return json.loads(raw.decode())
    except Exception:
        return {"error": "unknown", "message": raw[:200].decode("replace")}


class ServingClient:
    """Client for one serving front-end: ``ServingClient(url)`` (the
    `ServingHandle.url` or the bare ``http://host:port``) or
    ``ServingClient(host=..., port=...)``."""

    def __init__(
        self,
        url: Optional[str] = None,
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        timeout_s: Optional[float] = None,
    ):
        if url is not None:
            u = urlparse(url if "//" in url else f"http://{url}")
            host = u.hostname or host
            port = u.port if u.port is not None else port
        if port is None:
            raise ValueError("ServingClient needs a port (or a full url)")
        self.host = host
        self.port = int(port)
        self.timeout_s = timeout_s  # default per-request budget

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/serve"

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        timeout_s: Optional[float] = None,
    ):
        # socket timeout = the request budget + slack: the server
        # enforces the real deadline and answers 504; the socket bound
        # only protects against a dead server (run() always resolves an
        # explicit budget, so the bound always exceeds it)
        sock_timeout = (timeout_s if timeout_s is not None else 30.0) + 10.0
        conn = HTTPConnection(self.host, self.port, timeout=sock_timeout)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    # -- the verbs ------------------------------------------------------
    def run(
        self,
        endpoint: str,
        data,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> TensorFrame:
        """Evaluate ``endpoint`` on ``data`` (a `TensorFrame` or a dict
        of column arrays) and return the outputs-only response frame.
        Raises `OverloadError` (shed — back off by ``retry_after_s``),
        `DeadlineExceeded` (budget blown) or `ServingError`."""
        from .. import config as _config
        from ..io import frame_from_ipc_bytes, frame_to_ipc_bytes

        if not isinstance(data, TensorFrame):
            data = TensorFrame.from_dict(dict(data))
        if timeout_s is None:
            timeout_s = self.timeout_s
        if timeout_s is None:
            # resolve the budget CLIENT-side and state it explicitly, so
            # the socket bound below always exceeds the server's actual
            # budget — a remote server with a raised default can never
            # outlive our socket and turn a typed 504 into a raw
            # socket.timeout
            timeout_s = float(_config.get().serve_default_timeout_s)
        headers = {
            "Content-Type": "application/vnd.apache.arrow.stream",
            "X-TFS-Timeout-S": repr(float(timeout_s)),
        }
        if request_id is not None:
            headers["X-TFS-Request-Id"] = str(request_id)
        status, hdrs, raw = self._request(
            "POST",
            f"/serve/{endpoint}",
            body=frame_to_ipc_bytes(data),
            headers=headers,
            timeout_s=timeout_s,
        )
        if status == 200:
            return frame_from_ipc_bytes(raw)
        body = _decode_error(status, raw)
        msg = body.get("message", f"HTTP {status}")
        if status == 429:
            raise OverloadError(
                msg,
                queue_depth=int(body.get("queue_depth", 0)),
                limit=int(body.get("limit", 0)),
                retry_after_s=float(
                    body.get(
                        "retry_after_s", hdrs.get("Retry-After", 1.0)
                    )
                ),
            )
        if status == 504:
            raise DeadlineExceeded(
                msg,
                verb=f"serve:{endpoint}",
                budget_s=body.get("budget_s"),
                elapsed_s=body.get("elapsed_s"),
            )
        raise ServingError(
            f"endpoint {endpoint!r}: HTTP {status}: {msg}", status, body
        )

    def endpoints(self) -> dict:
        """The server's GET /serve listing (endpoints + batcher
        accounting)."""
        status, _hdrs, raw = self._request("GET", "/serve")
        if status != 200:
            raise ServingError(
                f"GET /serve: HTTP {status}", status, _decode_error(status, raw)
            )
        return json.loads(raw.decode())
