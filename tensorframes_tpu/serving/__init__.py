"""Multi-tenant serving runtime: the piece that turns the library into
a service.

The four modules compose the heavy-traffic north star out of machinery
earlier PRs built — the PR 3 shape ladder makes cross-request batching
shape-compatible by construction, the PR 5 scheduler places the
coalesced dispatch, PR 9's admission control and deadlines bound load
and latency, and the PR 8 endpoint is the shared HTTP surface:

- `registry` — named endpoints: schema-validated programs, warm-
  compiled across every bucket-ladder rung (zero steady-state
  compiles).
- `batcher` — cross-request micro-batching: concurrent small requests
  coalesce into ONE bucketed dispatch, results scatter back through
  futures, bit-identical to unbatched execution.
- `server` / `client` — Arrow IPC over HTTP on the shared process
  endpoint, with typed overload (429 + Retry-After) and deadline (504)
  mapping.

Quick start::

    import tensorframes_tpu as tfs

    fetch = ...  # dsl tensor / Graph / GraphDef / LazyFrame
    tfs.serving.register("score", fetch, {"x": "float32"})
    handle = tfs.serving.serve(port=0)

    client = tfs.serving.ServingClient(handle.url)
    out = client.run("score", {"x": np.arange(8, dtype=np.float32)})
"""

from __future__ import annotations

from .batcher import MicroBatcher, batcher
from .client import ServingClient, ServingError
from .registry import (
    Endpoint,
    endpoints,
    get,
    register,
    unregister,
)
from .server import ServingHandle, active, serve

__all__ = [
    "Endpoint",
    "register",
    "unregister",
    "get",
    "endpoints",
    "MicroBatcher",
    "batcher",
    "serve",
    "active",
    "drain",
    "ServingHandle",
    "ServingClient",
    "ServingError",
    "reset",
]


def drain(timeout_s=30.0, stop_server: bool = True) -> dict:
    """Graceful rolling-restart drain — the serving readiness story an
    external load balancer rolls replicas with:

    1. flips the readiness flag: ``/healthz`` reports
       ``ready: false`` / ``status: "draining"`` and every NEW
       ``POST /serve/<endpoint>`` sheds with a typed 503 (the balancer
       stops routing here; stragglers retry another replica);
    2. lets in-flight batcher lanes finish: waits (up to ``timeout_s``
       seconds; ``None`` = unbounded) for every queued request to
       dispatch, then stops the lanes through the existing
       `MicroBatcher.shutdown()` (which itself drains each lane
       through one final dispatch);
    3. unmounts the front-end and — with ``stop_server=True``, the
       default — stops the shared process HTTP server via the
       existing `telemetry_http.shutdown()`, so the port frees for
       the replacement replica.

    Endpoint registrations survive (a restart re-serves them with one
    `serve()` call, which also clears the draining flag). Idempotent.
    Returns accounting: ``{"drained": all lanes empty before shutdown,
    "waited_s": ..., "stopped_server": ...}``."""
    import time as _time

    from . import server as _server
    from .batcher import batcher as _the_batcher

    _server.set_draining(True)
    t0 = _time.monotonic()
    b = _the_batcher()
    while b.pending() > 0:
        if timeout_s is not None and _time.monotonic() - t0 >= timeout_s:
            break
        _time.sleep(0.005)
    drained = b.pending() == 0
    b.shutdown()
    handle = _server.active()
    if handle is not None:
        handle.close()
    stopped = False
    if stop_server:
        from ..utils import telemetry_http as _http

        stopped = _http.shutdown()
    from ..utils.log import get_logger

    get_logger("serving").info(
        "serving drained in %.3fs (lanes empty: %s, server stopped: %s)",
        _time.monotonic() - t0, drained, stopped,
    )
    return {
        "drained": drained,
        "waited_s": _time.monotonic() - t0,
        "stopped_server": stopped,
    }


def reset() -> None:
    """Test hook: unmount the front-end, stop every batching lane,
    forget every endpoint — the serving analogue of
    `telemetry.reset()`."""
    from . import server as _server

    _server.set_draining(False)
    handle = _server.active()
    if handle is not None:
        handle.close()  # unmounts AND clears the active-handle global
    else:
        from ..utils import telemetry_http as _http

        _http.unmount(_server.PREFIX)
    from . import registry as _registry

    _registry.reset()
