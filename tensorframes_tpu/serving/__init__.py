"""Multi-tenant serving runtime: the piece that turns the library into
a service.

The four modules compose the heavy-traffic north star out of machinery
earlier PRs built — the PR 3 shape ladder makes cross-request batching
shape-compatible by construction, the PR 5 scheduler places the
coalesced dispatch, PR 9's admission control and deadlines bound load
and latency, and the PR 8 endpoint is the shared HTTP surface:

- `registry` — named endpoints: schema-validated programs, warm-
  compiled across every bucket-ladder rung (zero steady-state
  compiles).
- `batcher` — cross-request micro-batching: concurrent small requests
  coalesce into ONE bucketed dispatch, results scatter back through
  futures, bit-identical to unbatched execution.
- `server` / `client` — Arrow IPC over HTTP on the shared process
  endpoint, with typed overload (429 + Retry-After) and deadline (504)
  mapping.

Quick start::

    import tensorframes_tpu as tfs

    fetch = ...  # dsl tensor / Graph / GraphDef / LazyFrame
    tfs.serving.register("score", fetch, {"x": "float32"})
    handle = tfs.serving.serve(port=0)

    client = tfs.serving.ServingClient(handle.url)
    out = client.run("score", {"x": np.arange(8, dtype=np.float32)})
"""

from __future__ import annotations

from .batcher import MicroBatcher, batcher
from .client import ServingClient, ServingError
from .registry import (
    Endpoint,
    endpoints,
    get,
    register,
    unregister,
)
from .server import ServingHandle, active, serve

__all__ = [
    "Endpoint",
    "register",
    "unregister",
    "get",
    "endpoints",
    "MicroBatcher",
    "batcher",
    "serve",
    "active",
    "ServingHandle",
    "ServingClient",
    "ServingError",
    "reset",
]


def reset() -> None:
    """Test hook: unmount the front-end, stop every batching lane,
    forget every endpoint — the serving analogue of
    `telemetry.reset()`."""
    from . import server as _server

    handle = _server.active()
    if handle is not None:
        handle.close()  # unmounts AND clears the active-handle global
    else:
        from ..utils import telemetry_http as _http

        _http.unmount(_server.PREFIX)
    from . import registry as _registry

    _registry.reset()
