"""GlobalFrame: one sharded-array frame, one SPMD dispatch per verb.

The block scheduler (`runtime/scheduler.py`) and ``mesh=``
(`parallel/verbs.py`) were two disjoint multi-device stories: the
scheduler commits one dispatch PER BLOCK onto a chosen device —
O(blocks) Python round-trips per verb — while ``mesh=`` shard_maps a
separate code path most verbs, streaming and serving never take. The
"TensorFlow Doing HPC" observation (PAPERS.md) is that expressing
distribution as ONE compiled program over a device mesh, with
reductions as in-program collectives, is what makes throughput
hardware-bound rather than dispatch-bound. This module is that model:

- A `GlobalFrame`'s dense columns are single `jax.Array`s sharded over
  a 1-D data `Mesh` with ``PartitionSpec(("data",))`` on the lead dim
  (`parallel.mesh` / SNIPPETS.md batch-dim sharding). The lead dim is
  padded (last-row replication, `shape_policy.pad_lead`) up to
  ``data_size x rung`` where ``rung`` buckets the PER-SHARD row count
  on the ordinary ladder — so a drifting global row count compiles
  O(log max-shard-rows) programs, the same warm-compile story as
  per-block bucketing. The true row count (``nrows``) rides alongside;
  `collect`/`to_frame` slice the pad rows back off.

- ``map_blocks``/``map_rows`` on it compile to ONE jit program whose
  committed input shardings make XLA (GSPMD) partition the work:
  row-local graphs run shard-local with ZERO cross-device traffic and
  outputs stay sharded, so chained maps never leave the mesh.

- Classified reduces (`aggregate._chunk_combiners` monoids over
  row-local transforms) lower through the SAME masked-reduce recipe as
  the bucket ladder (`shape_policy.build_masked_reduce`): pad rows
  mask to the reduction identity, the lead-axis reduction partitions
  into per-shard reduces plus ONE in-program all-reduce
  (psum/min/max) over ICI — no host-side partial gather+combine.
  min/max and integer sums are bit-identical to the block-scheduler
  path (any grouping of an idempotent/exact monoid agrees); float
  sum/mean carry the repo's documented reassociation tolerance.

- Everything the SPMD model cannot express exactly (non-row-local
  maps, unclassified reduces, fn-front-end fetches, bindings,
  ``trim``) FALLS BACK to the eager verb over `to_frame()` — counted
  in the fallback ledger so diagnostics can say why a workload is not
  on the fast path. ``reduce_rows`` (a left fold in row order) and
  keyed ``aggregate`` (host key factorization) always take the local
  path by contract.

Routing: ``config.block_scheduler = "global"`` (env
``TFS_BLOCK_SCHEDULER=global``) auto-routes eligible graph verbs on
plain `TensorFrame`s through this path when the frame carries at least
``config.global_frame_min_rows`` rows; below that — or for any
ineligible dispatch — the verb falls back to ordinary per-block
scheduling. An explicit `GlobalFrame` (via `TensorFrame.to_global`)
always dispatches here; ``devices=``/``mesh=`` on its verbs are
rejected loudly (the frame owns its mesh — one placement story, not
three). Circuit-open devices shrink the mesh loudly
(`scheduler.global_device_set`).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .frame import Column, TensorFrame
from .graph.analysis import analyze_graph
from .graph.ir import base_name as _base
from .ops.lowering import build_callable
from .schema import FrameInfo, ScalarType

# late-bound: api imports this module inside verb bodies only, so by
# the time any function here runs, api is fully initialized (same
# pattern as streaming.py)
from . import api as _api
from . import config as _config
from . import shape_policy as _sp

__all__ = ["GlobalFrame", "resolve_global_mesh", "state", "reset_state"]


# ---------------------------------------------------------------------------
# global-frame accounting (the diagnostics section + always-live counters)
# ---------------------------------------------------------------------------

_state_lock = threading.Lock()
_stats: Dict = {
    "frames": 0,            # GlobalFrames built (to_global / auto-route)
    "dispatches": 0,        # single-program SPMD dispatches issued
    "collectives": 0,       # in-program all-reduces lowered (1/reduce fetch)
    "pad_rows": 0,          # synthetic rows on sharded lead dims
    "fallbacks": {},        # reason -> count (why a dispatch left the path)
    "last_shards": None,    # data-axis size of the most recent mesh
    "stream_folds": 0,      # eager double-buffer folds on streaming reduces
}


def _note_frame(shards: int, pad_rows: int) -> None:
    from .utils import telemetry as _tele

    with _state_lock:
        _stats["frames"] += 1
        _stats["pad_rows"] += int(pad_rows)
        _stats["last_shards"] = int(shards)
    if pad_rows:
        _tele.counter_inc("global_pad_rows", float(pad_rows))


def _note_dispatch(verb: str, collectives: int = 0) -> None:
    from .utils import telemetry as _tele

    with _state_lock:
        _stats["dispatches"] += 1
        _stats["collectives"] += int(collectives)
    _tele.counter_inc("global_dispatches", 1.0, verb=verb)
    if collectives:
        _tele.counter_inc("global_collectives", float(collectives))


def _note_stream_fold() -> None:
    """One eager fold of `reduce_blocks_stream`'s double-buffered
    accumulator (a single SPMD combine dispatch that overlapped the
    next chunk's sharded H2D transfer)."""
    from .utils import telemetry as _tele

    with _state_lock:
        _stats["stream_folds"] += 1
    _tele.counter_inc("global_stream_folds", 1.0)


def _note_fallback(reason: str) -> None:
    from .utils import telemetry as _tele

    with _state_lock:
        _stats["fallbacks"][reason] = _stats["fallbacks"].get(reason, 0) + 1
    _tele.counter_inc("global_fallbacks", 1.0, reason=reason)


_route_tls = threading.local()


@contextlib.contextmanager
def _suppress_route():
    """An explicit-GlobalFrame fallback re-enters the verb layer over
    `to_frame()`; under ``block_scheduler="global"`` the auto-route
    must not probe (and count a second fallback for) the very dispatch
    that IS the fallback."""
    prev = getattr(_route_tls, "suppressed", False)
    _route_tls.suppressed = True
    try:
        yield
    finally:
        _route_tls.suppressed = prev


def state() -> Dict:
    """Snapshot for `tfs.diagnostics()`: shard count, dispatch and
    collective counts, pad waste on the sharded lead dim, fallback
    reasons."""
    with _state_lock:
        return {
            "frames": _stats["frames"],
            "dispatches": _stats["dispatches"],
            "collectives": _stats["collectives"],
            "pad_rows": _stats["pad_rows"],
            "fallbacks": dict(_stats["fallbacks"]),
            "shards": _stats["last_shards"],
            "stream_folds": _stats["stream_folds"],
        }


def reset_state() -> None:
    with _state_lock:
        _stats.update(
            frames=0, dispatches=0, collectives=0, pad_rows=0,
            fallbacks={}, last_shards=None, stream_folds=0,
        )
        _filter_fns.clear()


# jitted predicate-mask programs, keyed by (canonical predicate
# fingerprint, feed column tuple) — these have no Graph so they cannot
# ride the executor's `cached()`; cleared by `reset_state`
_filter_fns: Dict = {}


# ---------------------------------------------------------------------------
# mesh resolution
# ---------------------------------------------------------------------------


def resolve_global_mesh():
    """The data mesh a new `GlobalFrame` shards over: a 1-D ``data``
    mesh spanning every HEALTHY local device
    (`scheduler.global_device_set` — circuit-open devices shrink it
    loudly). Memoized on the device-label tuple so repeated verbs reuse
    one `Mesh` object (jit's sharding cache keys on mesh equality).

    The mesh is built directly from `jax.sharding` rather than through
    `parallel.data_mesh`: the `parallel` package __init__ pulls in
    shard_map-dependent modules this path never needs."""
    from jax.sharding import Mesh
    from .runtime import scheduler as _rs

    devs = _rs.global_device_set()
    if not devs:
        return None
    key = tuple(_rs.device_label(d) for d in devs)
    with _state_lock:
        cached = _stats.get("_mesh_cache")
        if cached is not None and cached[0] == key:
            return cached[1]
    mesh = Mesh(np.asarray(devs), ("data",))
    with _state_lock:
        _stats["_mesh_cache"] = (key, mesh)
    return mesh


def _padded_rows_for(nrows: int, ndata: int) -> int:
    """Sharded lead dim for ``nrows`` over ``ndata`` shards: the bucket
    ladder applies to the PER-SHARD row count (`bucket_for`), so the
    warm-compile story of per-block bucketing carries over — a
    drifting global row count hits O(log max-shard-rows) compiled
    shapes. With bucketing off, pad only to divisibility."""
    per_shard = -(-nrows // ndata)
    if _sp.enabled():
        per_shard = _sp.bucket_for(per_shard)
    return per_shard * ndata


# ---------------------------------------------------------------------------
# the frame
# ---------------------------------------------------------------------------


class GlobalFrame:
    """A frame whose dense columns are single sharded `jax.Array`s.

    Logically ONE block spanning the whole mesh (``num_blocks == 1``);
    the padded lead dim (``padded_rows = data_size x shard_rows``) is
    an execution detail — `nrows` is the truth, and every host-visible
    export slices back to it. Construct via `TensorFrame.to_global()`
    or `GlobalFrame.from_frame`; verbs dispatch through `api` exactly
    like TensorFrames (fluent methods installed below)."""

    def __init__(self, columns: Sequence[Column], mesh, nrows: int):
        if not columns:
            raise ValueError("a GlobalFrame needs at least one column")
        self._cols: Dict[str, Column] = {}
        padded = None
        for c in columns:
            if padded is None:
                padded = len(c)
            elif len(c) != padded:
                raise ValueError(
                    f"column {c.name!r} has {len(c)} padded rows, "
                    f"expected {padded}"
                )
            if c.name in self._cols:
                raise ValueError(f"duplicate column {c.name!r}")
            self._cols[c.name] = c
        self.mesh = mesh
        self.nrows = int(nrows)
        self.padded_rows = int(padded)
        self._local: Optional[TensorFrame] = None
        self.data_size = int(mesh.shape["data"])
        if self.padded_rows % self.data_size:
            raise ValueError(
                f"padded lead dim {self.padded_rows} is not divisible by "
                f"the data-axis size {self.data_size}"
            )
        self.shard_rows = self.padded_rows // self.data_size

    # -- construction ---------------------------------------------------
    @classmethod
    def from_frame(
        cls, frame: TensorFrame, mesh=None, columns: Optional[Sequence[str]] = None
    ) -> "GlobalFrame":
        """Shard ``frame``'s dense columns over the mesh's ``data``
        axis. Ragged and string columns cannot go to device and are
        rejected loudly (select the dense columns first, or stay on the
        per-block path). ``columns`` restricts the conversion (the
        auto-route converts only the columns a graph actually feeds)."""
        if isinstance(frame, GlobalFrame):
            return frame
        if frame.nrows == 0:
            raise ValueError("to_global on an empty frame")
        if mesh is None:
            mesh = resolve_global_mesh()
        if mesh is None or "data" not in mesh.shape:
            raise ValueError(
                "to_global needs a mesh with a 'data' axis (none could "
                "be resolved from the local devices)"
            )
        names = list(columns) if columns is not None else frame.columns
        for n in names:
            c = frame.column(n)
            if not c.is_dense or c.dtype is ScalarType.string:
                raise ValueError(
                    f"to_global: column {n!r} is "
                    f"{'ragged' if not c.is_dense else 'a bytes column'}; "
                    "global frames hold dense device-shardable columns "
                    "only — select() the dense columns or use the "
                    "per-block path"
                )
        ndata = int(mesh.shape["data"])
        padded = _padded_rows_for(frame.nrows, ndata)
        from .utils import telemetry as _tele

        h2d_bytes = 0
        new_cols: List[Column] = []
        # transfer span: the sharded device_put issue window (async —
        # per-shard H2D copies to different devices overlap)
        with _tele.span(
            "to_global", kind="transfer", sharding=f"data:{ndata}"
        ):
            for n in names:
                c = frame.column(n)
                vals = _sp.pad_lead(c.values, frame.nrows, padded)
                if isinstance(vals, np.ndarray):
                    h2d_bytes += vals.nbytes
                spec = P("data", *([None] * (vals.ndim - 1)))
                arr = jax.device_put(vals, NamedSharding(mesh, spec))
                nc = Column(n, arr, c.dtype)
                nc.cell_shape = c.cell_shape
                new_cols.append(nc)
        if h2d_bytes and _tele.enabled():
            _tele.histogram_observe("h2d_bytes", float(h2d_bytes))
        _sp.observe_fill(frame.nrows, padded, verb="to_global")
        _note_frame(ndata, padded - frame.nrows)
        return cls(new_cols, mesh, frame.nrows)

    # -- frame-shaped surface -------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._cols.keys())

    @property
    def info(self) -> FrameInfo:
        return FrameInfo([c.info for c in self._cols.values()])

    def column(self, name: str) -> Column:
        if name not in self._cols:
            raise KeyError(
                f"no column {name!r}; available: {self.columns}"
            )
        return self._cols[name]

    def __getitem__(self, name: str) -> Column:
        return self.column(name)

    @property
    def num_blocks(self) -> int:
        return 1

    @property
    def offsets(self) -> List[int]:
        return [0, self.nrows]

    def block_sizes(self) -> List[int]:
        return [self.nrows]

    @property
    def pad_rows(self) -> int:
        return self.padded_rows - self.nrows

    def __repr__(self) -> str:
        return (
            f"GlobalFrame[{self.nrows} rows x {len(self._cols)} cols, "
            f"data:{self.data_size} sharded, {self.shard_rows} rows/shard"
            f"{f', +{self.pad_rows} pad' if self.pad_rows else ''}]"
        )

    # -- boundaries -----------------------------------------------------
    def to_frame(self) -> TensorFrame:
        """The sharded -> local boundary: one single-block `TensorFrame`
        whose columns are the valid-row slices of the sharded arrays
        (lazy device slices — nothing is host-fetched here). The
        fallback target of every dispatch the SPMD model cannot
        express. Memoized: the frame is immutable, and a fresh Column
        per call would discard the Column-level host cache and re-pay
        the D2H transfer on every collect()/to_pandas()."""
        if self._local is None:
            cols = []
            for c in self._cols.values():
                vals = (
                    c.values[: self.nrows]
                    if self.padded_rows != self.nrows
                    else c.values
                )
                nc = Column(c.name, vals, c.dtype)
                nc.cell_shape = c.cell_shape
                cols.append(nc)
            self._local = TensorFrame(cols, [0, self.nrows])
        return self._local

    def collect(self):
        return self.to_frame().collect()

    def to_pandas(self):
        return self.to_frame().to_pandas()

    def host_values(self, name: str) -> np.ndarray:
        return self.to_frame().host_values(name)

    def select(self, names: Sequence[str]) -> "GlobalFrame":
        return GlobalFrame(
            [self.column(n) for n in names], self.mesh, self.nrows
        )

    def lazy(self):
        """Wrap into a `LazyFrame` over this global base: deferred map
        chains force as ONE fused SPMD dispatch, and a fused reduce
        terminal lowers its collectives in-program (see `lazy.py`)."""
        from .lazy import LazyFrame

        return LazyFrame(self)

    def print_schema(self) -> None:
        print(self.info.explain())


# ---------------------------------------------------------------------------
# the one-dispatch core
# ---------------------------------------------------------------------------


def _reject_overrides(verb: str, mesh, devices) -> None:
    """A GlobalFrame owns its mesh: per-call placement overrides are
    rejected loudly rather than silently ignored (three placement
    stories collapsing into one is the point)."""
    if mesh is not None:
        raise ValueError(
            f"{verb}: mesh= is not accepted on a GlobalFrame — the "
            "frame is already sharded over its own mesh; collect() "
            "first to re-place"
        )
    if devices is not None:
        raise ValueError(
            f"{verb}: devices= is not accepted on a GlobalFrame — the "
            "global SPMD path owns placement (the frame's data mesh); "
            "collect() first, or drop the devices= pin"
        )


def _dispatch_one(
    span_name: str, verb: str, fn, valid: Optional[int], gf: GlobalFrame,
    feeds: Sequence, fp: str, collectives: int = 0,
):
    """THE single SPMD dispatch: one compiled program over the whole
    mesh, under the verb's deadline (cooperative check at the
    boundary), classified fault handling (transient retries — there is
    no per-block schedule to fail over, and no row range to split: a
    resource error records its forensic snapshot and re-raises), and a
    dispatch span labeled ``sharding=data:N`` plus the padded lead as
    its ``bucket`` (pad-waste accounting rides the usual span join)."""
    from .runtime import deadline as _dl
    from .runtime import faults as _flt
    from .utils import telemetry as _tele

    _dl.check(verb)
    fscope = _flt.scope(verb)

    def _thunk():
        with _tele.dispatch_span(
            span_name, program=fp, rows=gf.nrows, bucket=gf.padded_rows,
            sharding=f"data:{gf.data_size}",
            masked=(valid is not None) or None,
        ):
            if valid is None:
                return fn(*feeds)
            return fn(np.int32(valid), *feeds)

    try:
        outs = fscope.dispatch(
            _thunk, what=f"{verb} global frame rows [0:{gf.nrows})"
        )
    except Exception as e:
        if _flt.classify(e) == _flt.RESOURCE:
            _flt.record_oom(
                verb, fp, gf.nrows, 0, "reraise:global-frame", e,
                bucket=gf.padded_rows,
            )
        raise
    _note_dispatch(verb, collectives=collectives)
    return tuple(outs)


def _analyze(graph, fetch_list, gf, feed_dict, block_level: bool):
    overrides = _api._ph_overrides(
        graph, gf, feed_dict, block_level=block_level
    )
    summary = analyze_graph(
        graph, fetch_list, placeholder_shapes=overrides
    )
    mapping = _api._match_columns(
        summary, gf, feed_dict, block_level=block_level
    )
    return summary, mapping


def _spmd_capable(ex) -> bool:
    """The SPMD path device_puts sharded in-process jax arrays, so it
    needs the same opt-in as the block scheduler (the native executor
    owns its own PJRT host and must never see them)."""
    return getattr(ex, "supports_scheduling", False)


def _map_dispatch(graph, fetch_list, gf: GlobalFrame, mapping, ex,
                  vmap: bool):
    """One shard-local map dispatch — the recipe shared by the
    explicit-GlobalFrame verbs and the "global"-mode auto-route (they
    differ only in output assembly): cached program build, the single
    SPMD dispatch, numerics check, and the lead-dim preservation check
    the row-local gate promised."""
    from .runtime.faults import maybe_check_numerics

    verb = "map_rows" if vmap else "map_blocks"
    feed_names = sorted(mapping)
    if vmap:
        build = lambda: jax.jit(  # noqa: E731
            jax.vmap(build_callable(graph, fetch_list, feed_names))
        )
    else:
        build = lambda: jax.jit(  # noqa: E731
            build_callable(graph, fetch_list, feed_names)
        )
    fn = ex.cached(
        "global-vmap-rows" if vmap else "global-map",
        graph, fetch_list, feed_names, build,
    )
    feeds = [gf.column(mapping[n]).values for n in feed_names]
    outs = _dispatch_one(
        f"{verb}.global", verb, fn, None, gf, feeds, graph.fingerprint()
    )
    maybe_check_numerics(fetch_list, outs, f"{verb} (global)")
    for f, o in zip(fetch_list, outs):
        if getattr(o, "ndim", 0) == 0 or o.shape[0] != gf.padded_rows:
            raise ValueError(
                f"{verb}: output {_base(f)!r} does not preserve the "
                "sharded lead dim; row-count-changing graphs cannot "
                "run on the global SPMD path (the per-block path with "
                "trim=True handles row-count-changing maps)"
            )
    return outs


def _reduce_dispatch(graph, fetch_list, gf: GlobalFrame, mapping, plan,
                     ex):
    """One masked SPMD reduce dispatch (per-shard reduces + in-program
    collectives) — shared by `reduce_blocks_global` and the
    auto-route."""
    from .runtime.faults import maybe_check_numerics

    feed_names = sorted(mapping)
    fn = ex.cached(
        "global-reduce", graph, fetch_list, feed_names,
        lambda: jax.jit(_sp.build_masked_reduce(graph, plan, feed_names)),
    )
    feeds = [gf.column(mapping[n]).values for n in feed_names]
    outs = _dispatch_one(
        "reduce_blocks.global", "reduce_blocks", fn, gf.nrows, gf, feeds,
        graph.fingerprint(), collectives=len(fetch_list),
    )
    maybe_check_numerics(fetch_list, outs, "reduce_blocks (global)")
    if len(fetch_list) == 1:
        return outs[0]
    return {_base(f): v for f, v in zip(fetch_list, outs)}


def _output_global(
    gf: GlobalFrame, fetch_list: Sequence[str], outs: Sequence
) -> GlobalFrame:
    """Assemble a map verb's output GlobalFrame: graph outputs first,
    sorted by name, then passthrough input columns — the same ordering
    as the eager `_output_frame` (lead dims already validated by
    `_map_dispatch`)."""
    out_cols = [Column(_base(f), o) for f, o in zip(fetch_list, outs)]
    out_cols.sort(key=lambda c: c.name)
    shadow = {c.name for c in out_cols}
    cols = out_cols + [
        gf.column(n) for n in gf.columns if n not in shadow
    ]
    return GlobalFrame(cols, gf.mesh, gf.nrows)


def _fallback_map(fetches, gf, feed_dict, trim, fetch_names, executor,
                  bindings, reason: str) -> GlobalFrame:
    """Run the eager verb over the local boundary and re-globalize the
    result onto the SAME mesh, so explicit-GlobalFrame chains keep
    their type across an ineligible stage. Counted: diagnostics must
    be able to say why a workload left the fast path."""
    _note_fallback(reason)
    with _suppress_route():
        out = _api.map_blocks(
            fetches, gf.to_frame(), feed_dict, trim, fetch_names, executor,
            bindings=bindings,
        )
    return GlobalFrame.from_frame(out, mesh=gf.mesh)


# ---------------------------------------------------------------------------
# verbs on an explicit GlobalFrame
# ---------------------------------------------------------------------------


def map_blocks_global(
    fetches, gf: GlobalFrame, feed_dict=None, trim=False, fetch_names=None,
    executor=None, mesh=None, bindings=None, devices=None,
) -> GlobalFrame:
    _reject_overrides("map_blocks", mesh, devices)
    if trim:
        raise ValueError(
            "map_blocks(trim=True) is not supported on a GlobalFrame: "
            "trimmed maps change the row count under the sharded lead "
            "dim; collect() first"
        )
    from .runtime.executor import default_executor

    ex = executor or default_executor()
    if callable(fetches) and not isinstance(fetches, _api.dsl.Tensor):
        return _fallback_map(
            fetches, gf, feed_dict, trim, fetch_names, executor, bindings,
            "fn-frontend",
        )
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    if bindings:
        return _fallback_map(
            graph, gf, feed_dict, trim, fetch_list, executor, bindings,
            "bindings",
        )
    if any(
        ph.dtype_attr is ScalarType.string for ph in graph.placeholders()
    ):
        return _fallback_map(
            graph, gf, feed_dict, trim, fetch_list, executor, None,
            "bytes-passthrough",
        )
    if not _spmd_capable(ex):
        return _fallback_map(
            graph, gf, feed_dict, trim, fetch_list, executor, None,
            "executor",
        )
    summary, mapping = _analyze(graph, fetch_list, gf, feed_dict, True)
    if not _sp.rowwise_fetches(
        graph, fetch_list,
        {p: ph.shape.rank for p, ph in summary.inputs.items()},
    ):
        # a non-row-local map over a sharded lead dim would see the pad
        # rows (and XLA would insert collectives mid-map); it runs on
        # the exact local boundary instead
        return _fallback_map(
            graph, gf, feed_dict, trim, fetch_list, executor, None,
            "not-row-local",
        )
    outs = _map_dispatch(graph, fetch_list, gf, mapping, ex, vmap=False)
    return _output_global(gf, fetch_list, outs)


def map_rows_global(
    fetches, gf: GlobalFrame, feed_dict=None, fetch_names=None,
    executor=None, mesh=None, bindings=None, devices=None,
) -> GlobalFrame:
    _reject_overrides("map_rows", mesh, devices)
    from .runtime.executor import default_executor

    ex = executor or default_executor()

    def fallback(fs, names, reason):
        _note_fallback(reason)
        with _suppress_route():
            out = _api.map_rows(
                fs, gf.to_frame(), feed_dict, names, executor,
                bindings=bindings,
            )
        return GlobalFrame.from_frame(out, mesh=gf.mesh)

    if callable(fetches) and not isinstance(fetches, _api.dsl.Tensor):
        return fallback(fetches, fetch_names, "fn-frontend")
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    if bindings:
        return fallback(graph, fetch_list, "bindings")
    if any(
        ph.dtype_attr is ScalarType.string for ph in graph.placeholders()
    ):
        return fallback(graph, fetch_list, "bytes-passthrough")
    if not _spmd_capable(ex):
        return fallback(graph, fetch_list, "executor")
    summary, mapping = _analyze(graph, fetch_list, gf, feed_dict, False)
    # the vmapped per-row program is row-local BY CONSTRUCTION: one
    # batched program over the sharded lead dim, zero communication
    outs = _map_dispatch(graph, fetch_list, gf, mapping, ex, vmap=True)
    return _output_global(gf, fetch_list, outs)


def stream_reduce_eligible(graph, fetch_list, gf, feed_dict,
                           executor=None) -> bool:
    """True when `reduce_blocks` on this GlobalFrame lowers to the
    one-dispatch masked-collective program. The ingest stream checks
    ONCE, on its first sharded chunk, and stops sharding when the
    answer is no — an unclassifiable reduce graph is fixed for the
    stream's lifetime, so paying a sharded H2D plus a local-boundary
    fallback re-gather on EVERY chunk would be pure waste."""
    from .runtime.executor import default_executor

    ex = executor or default_executor()
    if not _spmd_capable(ex):
        return False
    try:
        summary, _ = _analyze(graph, fetch_list, gf, feed_dict, True)
        return (
            _sp.masked_reduce_plan(graph, fetch_list, summary) is not None
        )
    except Exception:
        return False


def reduce_blocks_global(
    fetches, gf: GlobalFrame, feed_dict=None, fetch_names=None,
    executor=None, mesh=None, devices=None,
):
    _reject_overrides("reduce_blocks", mesh, devices)
    from .runtime.executor import default_executor

    ex = executor or default_executor()
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    summary, mapping = _analyze(graph, fetch_list, gf, feed_dict, True)
    _api._validate_reduce_blocks(summary, fetch_list)
    plan = (
        _sp.masked_reduce_plan(graph, fetch_list, summary)
        if _spmd_capable(ex)
        else None
    )
    if plan is None:
        # unclassified reduce: no monoid structure to lower as an
        # in-program collective — run the exact eager verb on the local
        # boundary (still one dispatch: the frame is one block)
        _note_fallback(
            "unclassified-reduce" if _spmd_capable(ex) else "executor"
        )
        with _suppress_route():
            return _api.reduce_blocks(
                graph, gf.to_frame(), feed_dict, fetch_list, executor
            )
    return _reduce_dispatch(graph, fetch_list, gf, mapping, plan, ex)


# ---------------------------------------------------------------------------
# "global" scheduler-mode auto-routing (plain TensorFrame verbs)
# ---------------------------------------------------------------------------

# sentinel: the verb was NOT routed — the eager path must continue (a
# routed reduce may legitimately return any value, including arrays)
SKIP = object()


def _route_eligible(frame, ex, devices) -> bool:
    from .runtime import scheduler as _rs

    if getattr(_route_tls, "suppressed", False):
        return False
    cfg = _config.get()
    return (
        devices is None
        and _rs.global_mode()
        and isinstance(frame, TensorFrame)
        and frame.nrows >= max(1, cfg.global_frame_min_rows)
        and _spmd_capable(ex)
    )


def _try_match(graph, fetch_list, frame, feed_dict, block_level):
    """Analysis + matching for the auto-route, swallowing errors: a
    mismatch must surface from the EAGER path (the canonical error
    messages), not from the routing probe."""
    try:
        overrides = _api._ph_overrides(
            graph, frame, feed_dict, block_level=block_level
        )
        summary = analyze_graph(
            graph, fetch_list, placeholder_shapes=overrides
        )
        mapping = _api._match_columns(
            summary, frame, feed_dict, block_level=block_level
        )
    except Exception:
        return None, None
    return _routable(summary, mapping, frame)


def _routable(summary, mapping, frame):
    """Column-level routing gate, shared with callers that hand in an
    already-computed analysis (`maybe_map_rows(pre=)`)."""
    used = sorted(set(mapping.values()))
    if not used:
        return None, None  # const-only graph: nothing to shard
    for c in used:
        col = frame.column(c)
        if not col.is_dense or col.dtype is ScalarType.string:
            return None, None
    return summary, mapping


def maybe_map_blocks(graph, fetch_list, frame, feed_dict, executor, devices):
    """Auto-route an eager `map_blocks` (graph path, no trim/bindings/
    mesh) through one SPMD dispatch under ``block_scheduler="global"``.
    Returns a `TensorFrame` with the INPUT's offsets (blocks are index
    ranges; the values are the valid-row slices of the sharded
    outputs), or `SKIP` when ineligible — the eager per-block path then
    runs exactly as under "auto"."""
    from .runtime.executor import default_executor

    ex = executor or default_executor()
    if not _route_eligible(frame, ex, devices):
        return SKIP
    summary, mapping = _try_match(graph, fetch_list, frame, feed_dict, True)
    if summary is None:
        return SKIP
    if not _sp.rowwise_fetches(
        graph, fetch_list,
        {p: ph.shape.rank for p, ph in summary.inputs.items()},
    ):
        _note_fallback("not-row-local")
        return SKIP
    gf = GlobalFrame.from_frame(
        frame, mesh=None, columns=sorted(set(mapping.values()))
    )
    outs = _map_dispatch(graph, fetch_list, gf, mapping, ex, vmap=False)
    out_cols = [
        Column(_base(f), o[: frame.nrows])
        for f, o in zip(fetch_list, outs)
    ]
    return _api._output_frame(frame, out_cols, append_input=True)


def maybe_map_rows(graph, fetch_list, frame, feed_dict, executor, devices,
                   pre=None):
    """`maybe_map_blocks`'s per-row sibling: one vmapped SPMD dispatch
    instead of one per block. ``pre`` hands in the (summary, mapping)
    the eager verb already computed — `map_rows` analyzes before it
    probes, so the route must not pay that analysis twice."""
    from .runtime.executor import default_executor

    ex = executor or default_executor()
    if not _route_eligible(frame, ex, devices):
        return SKIP
    if pre is not None:
        summary, mapping = _routable(pre[0], pre[1], frame)
    else:
        summary, mapping = _try_match(
            graph, fetch_list, frame, feed_dict, False
        )
    if summary is None:
        return SKIP
    gf = GlobalFrame.from_frame(
        frame, mesh=None, columns=sorted(set(mapping.values()))
    )
    outs = _map_dispatch(graph, fetch_list, gf, mapping, ex, vmap=True)
    out_cols = [
        Column(_base(f), o[: frame.nrows])
        for f, o in zip(fetch_list, outs)
    ]
    return _api._output_frame(frame, out_cols, append_input=True)


def maybe_reduce_blocks(graph, fetch_list, frame, feed_dict, executor,
                        devices):
    """Auto-route an eager `reduce_blocks` through one masked SPMD
    dispatch with in-program collectives — classified monoid reduces
    only (the bit-identity/tolerance contract is exactly the masked
    bucketed program's). Returns the reduce result, or `SKIP`."""
    from .runtime.executor import default_executor

    ex = executor or default_executor()
    if not _route_eligible(frame, ex, devices):
        return SKIP
    summary, mapping = _try_match(graph, fetch_list, frame, feed_dict, True)
    if summary is None:
        return SKIP
    try:
        _api._validate_reduce_blocks(summary, fetch_list)
    except Exception:
        return SKIP  # the eager path owns the canonical error
    plan = _sp.masked_reduce_plan(graph, fetch_list, summary)
    if plan is None:
        _note_fallback("unclassified-reduce")
        return SKIP
    gf = GlobalFrame.from_frame(
        frame, mesh=None, columns=sorted(set(mapping.values()))
    )
    return _reduce_dispatch(graph, fetch_list, gf, mapping, plan, ex)


# ---------------------------------------------------------------------------
# fused lazy plans over a GlobalFrame base (lazy.py calls these)
# ---------------------------------------------------------------------------


def force_fused_global(
    lf, gf: GlobalFrame, ex, fetch_edges: List[str], out_names: List[str],
    feed_names: List[str],
):
    """Force a fused lazy map chain over a GlobalFrame base as ONE SPMD
    dispatch. Returns the concrete `TensorFrame` (valid-row slices +
    passthrough), or None when the fused chain is not row-local /
    the executor cannot take sharded arrays — the caller then runs the
    ordinary single-block loop on the duck-typed frame."""
    from .runtime.faults import maybe_check_numerics
    from .utils import telemetry as _tele

    graph = lf._graph
    feed_map = lf._feed_map
    if not _spmd_capable(ex):
        _note_fallback("executor")
        return None
    if not _sp.rowwise_fetches(
        graph, fetch_edges,
        {
            ph: gf.info[col].block_shape.rank
            for ph, col in feed_map.items()
        },
    ):
        _note_fallback("lazy-not-row-local")
        return None
    fn = ex.cached(
        "global-map", graph, fetch_edges, feed_names,
        lambda: jax.jit(build_callable(graph, fetch_edges, feed_names)),
    )
    feeds = [gf.column(feed_map[n]).values for n in feed_names]
    with _tele.span(
        "lazy.force.blocks", kind="stage", program=graph.fingerprint()
    ):
        outs = _dispatch_one(
            "lazy.force.global", "lazy.force", fn, None, gf, feeds,
            graph.fingerprint(),
        )
    maybe_check_numerics(out_names, outs, "lazy fused (global)")
    with _tele.span("lazy.force.collect", kind="stage"):
        out_cols = []
        for n, o in zip(out_names, outs):
            if getattr(o, "ndim", 0) == 0 or o.shape[0] != gf.padded_rows:
                raise ValueError(
                    f"lazy plan output {n!r} does not preserve the "
                    "sharded lead dim; trimmed/reducing stages cannot "
                    "be part of a lazy map plan"
                )
            out_cols.append(Column(n, o[: gf.nrows]))
        shadow = set(out_names)
        base_local = gf.to_frame()
        cols = out_cols + [
            base_local.column(c) for c in gf.columns if c not in shadow
        ]
    return TensorFrame(cols, [0, gf.nrows])


def fused_reduce_global(
    fused, fused_fetches: List[str], feed_map: Dict[str, str],
    feed_names: List[str], gf: GlobalFrame, fused_plan, ex,
) -> Optional[Tuple]:
    """One masked SPMD dispatch for a fused lazy map->reduce chain over
    a GlobalFrame base: the whole pending chain plus the masked monoid
    reduce compile into one program whose reductions lower to
    in-program collectives. None (caller falls back to the ordinary
    single-block loop) when the fused chain did not classify."""
    if fused_plan is None or not _spmd_capable(ex):
        _note_fallback(
            "unclassified-reduce" if _spmd_capable(ex) else "executor"
        )
        return None
    fn = ex.cached(
        "global-reduce", fused, fused_fetches, feed_names,
        lambda: jax.jit(
            _sp.build_masked_reduce(fused, fused_plan, feed_names)
        ),
    )
    feeds = [gf.column(feed_map[n]).values for n in feed_names]
    return _dispatch_one(
        "reduce_blocks.fused.global", "reduce_blocks.fused", fn, gf.nrows,
        gf, feeds, fused.fingerprint(), collectives=len(fused_fetches),
    )


def filter_global(pred, gf: GlobalFrame, executor=None):
    """Relational filter on the SPMD path: ONE mask dispatch (the
    predicate plus the valid-row guard compile into a single program
    over the whole mesh), then a host compact of the survivors and a
    re-globalize. Returns the filtered `GlobalFrame` — or ``None``
    when the plan cannot stay on the SPMD path (executor cannot take
    sharded arrays, predicate reads a missing / non-scalar column);
    the caller then falls back, counted, to the local block path."""
    from .runtime.executor import default_executor

    ex = executor or default_executor()
    if not _spmd_capable(ex):
        return None
    cols = sorted(pred.columns())
    for c in cols:
        if c not in gf.columns:
            return None  # surface the clear missing-column error locally
        if gf.info[c].block_shape.rank != 1:
            return None  # predicate over tensor cells: not expressible
    key = (pred.fingerprint(), tuple(cols))
    with _state_lock:
        fn = _filter_fns.get(key)
    if fn is None:
        import jax.numpy as jnp

        def _mask_fn(valid, *arrs):
            lookup = dict(zip(cols, arrs))
            m = pred.mask(lambda n: lookup[n])
            return (m & (jnp.arange(arrs[0].shape[0]) < valid),)

        fn = jax.jit(_mask_fn)
        with _state_lock:
            fn = _filter_fns.setdefault(key, fn)
    feeds = [gf.column(c).values for c in cols]
    outs = _dispatch_one(
        "plan.filter.mask", "filter", fn, gf.nrows, gf, feeds,
        f"plan-filter:{pred.fingerprint()}",
    )
    take = np.flatnonzero(np.asarray(outs[0]))
    base = gf.to_frame()
    data = {n: np.asarray(base.host_values(n))[take] for n in gf.columns}
    local = TensorFrame.from_dict(data)
    if take.size == 0:
        return local  # nothing to shard; downstream stages stay local
    return GlobalFrame.from_frame(local, mesh=gf.mesh)


# ---------------------------------------------------------------------------
# fluent methods (mirror TensorFrame's: gf.map_blocks(...) etc.)
# ---------------------------------------------------------------------------


def _install_fluent_methods() -> None:
    def _map_blocks(self, fetches, **kw):
        return _api.map_blocks(fetches, self, **kw)

    def _map_rows(self, fetches, **kw):
        return _api.map_rows(fetches, self, **kw)

    def _reduce_blocks(self, fetches, **kw):
        return _api.reduce_blocks(fetches, self, **kw)

    def _reduce_rows(self, fetches, **kw):
        return _api.reduce_rows(fetches, self, **kw)

    def _group_by(self, *keys):
        return _api.GroupedFrame(self, keys)

    # relational verbs: defer as plan-DAG nodes over this GlobalFrame
    # (filter lowers to the one-dispatch mask+compact above; groupby to
    # the segment recipe; sort/join fall back counted)
    def _filter(self, pred, selectivity=None):
        return self.lazy().filter(pred, selectivity=selectivity)

    def _sort_by(self, *keys, descending=False):
        return self.lazy().sort_by(*keys, descending=descending)

    def _join(self, other, on, how="inner"):
        return self.lazy().join(other, on, how=how)

    GlobalFrame.map_blocks = _map_blocks
    GlobalFrame.map_rows = _map_rows
    GlobalFrame.reduce_blocks = _reduce_blocks
    GlobalFrame.reduce_rows = _reduce_rows
    GlobalFrame.group_by = _group_by
    GlobalFrame.filter = _filter
    GlobalFrame.sort_by = _sort_by
    GlobalFrame.join = _join


_install_fluent_methods()
