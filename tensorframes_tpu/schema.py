"""Schema core: scalar types, tensor shapes with unknown dims, column metadata.

This is the TPU-native re-design of the reference's schema layer
(`Shape.scala`, `ColumnInformation.scala`, `MetadataConstants.scala`,
`DataFrameInfo.scala` in org/tensorframes). Semantics preserved:

- shapes carry ``None`` ("unknown") dims, and a block column always has an
  unknown lead dim (the block size), matching `Shape.scala:16-84`;
- precision comparison ``check_more_precise_than`` follows
  `Shape.scala:54-59`: a shape is at least as precise as another when every
  dim is either equal or the other's dim is unknown;
- shape merging widens mismatched dims to unknown, matching the analyze
  machinery in `ExperimentalOperations.scala:168-178`.

Unlike the reference (which embedded metadata into Spark StructField
metadata under `org.spartf.shape` / `org.sparktf.type`,
`MetadataConstants.scala:19,27`), column metadata here is a first-class
Python object attached to the columnar frame.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ScalarType",
    "Shape",
    "Unknown",
    "ColumnInfo",
    "FrameInfo",
    "UnsupportedTypeError",
]

#: Sentinel for an unknown dimension (the reference uses -1 / Shape.Unknown).
Unknown = None


class UnsupportedTypeError(TypeError):
    """Raised when a dtype outside the supported scalar set is used."""

    # a schema/dtype rejection never succeeds on retry
    tfs_fault_class = "deterministic"


class ScalarType(enum.Enum):
    """Supported cell scalar types.

    The reference supports Double, Float, Int(32), Long, Binary
    (`datatypes.scala:265-267`). TPU-native additions: bool, bfloat16,
    float16, int8, int16, uint8, uint32, uint64 — first-class on TPU and in
    XLA. ``string`` mirrors the reference's Binary column support (host-only:
    strings never land on the accelerator).
    """

    float64 = "float64"
    float32 = "float32"
    bfloat16 = "bfloat16"
    float16 = "float16"
    int64 = "int64"
    int32 = "int32"
    int16 = "int16"
    int8 = "int8"
    uint8 = "uint8"
    uint32 = "uint32"
    uint64 = "uint64"
    bool_ = "bool"
    string = "string"

    # ---- numpy interop -------------------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        if self is ScalarType.bfloat16:
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        if self is ScalarType.string:
            return np.dtype(object)
        return np.dtype(self.value)

    @classmethod
    def from_np_dtype(cls, dt) -> "ScalarType":
        dt = np.dtype(dt)
        if dt.kind in ("U", "S", "O"):
            return cls.string
        name = dt.name
        if name == "bfloat16":
            return cls.bfloat16
        if name == "bool":
            return cls.bool_
        try:
            return cls(name)
        except ValueError as e:
            raise UnsupportedTypeError(f"unsupported dtype {dt!r}") from e

    # ---- TF proto DataType interop ------------------------------------
    # Wire-compatible with tensorflow/core/framework/types.proto enum values.
    @property
    def tf_datatype(self) -> int:
        return _SCALAR_TO_TF[self]

    @classmethod
    def from_tf_datatype(cls, value: int) -> "ScalarType":
        # TF marks reference dtypes as value + 100 (DT_*_REF); normalize.
        value = value % 100
        try:
            return _TF_TO_SCALAR[value]
        except KeyError as e:
            raise UnsupportedTypeError(f"unsupported DataType enum {value}") from e

    @property
    def is_floating(self) -> bool:
        return self in (
            ScalarType.float64,
            ScalarType.float32,
            ScalarType.bfloat16,
            ScalarType.float16,
        )

    @property
    def is_integer(self) -> bool:
        return self in (
            ScalarType.int64,
            ScalarType.int32,
            ScalarType.int16,
            ScalarType.int8,
            ScalarType.uint8,
            ScalarType.uint32,
            ScalarType.uint64,
        )

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"ScalarType.{self.name}"


# tensorflow/core/framework/types.proto (public wire contract)
_SCALAR_TO_TF = {
    ScalarType.float32: 1,
    ScalarType.float64: 2,
    ScalarType.int32: 3,
    ScalarType.uint8: 4,
    ScalarType.int16: 5,
    ScalarType.int8: 6,
    ScalarType.string: 7,
    ScalarType.int64: 9,
    ScalarType.bool_: 10,
    ScalarType.bfloat16: 14,
    ScalarType.float16: 19,
    ScalarType.uint32: 22,
    ScalarType.uint64: 23,
}
_TF_TO_SCALAR = {v: k for k, v in _SCALAR_TO_TF.items()}


@dataclass(frozen=True)
class Shape:
    """A tensor shape whose dims may be unknown (``None``).

    Re-design of `Shape.scala`. Dims are stored as a tuple of
    ``int | None``; ``None`` is an unknown dim (the reference's ``-1``).
    """

    dims: Tuple[Optional[int], ...]

    # ---- constructors --------------------------------------------------
    def __init__(self, dims: Iterable[Optional[int]]):
        norm = []
        for d in dims:
            if d is None or (isinstance(d, (int, np.integer)) and int(d) < 0):
                norm.append(None)
            elif isinstance(d, (int, np.integer)):
                norm.append(int(d))
            else:
                raise TypeError(f"bad dim {d!r}")
        object.__setattr__(self, "dims", tuple(norm))

    @classmethod
    def scalar(cls) -> "Shape":
        return cls(())

    @classmethod
    def of_array(cls, arr: np.ndarray) -> "Shape":
        return cls(arr.shape)

    # ---- structure -----------------------------------------------------
    @property
    def rank(self) -> int:
        return len(self.dims)

    @property
    def is_scalar(self) -> bool:
        return self.rank == 0

    @property
    def has_unknown(self) -> bool:
        return any(d is None for d in self.dims)

    @property
    def num_elements(self) -> Optional[int]:
        """Element count, or None if any dim is unknown."""
        if self.has_unknown:
            return None
        n = 1
        for d in self.dims:
            n *= d  # type: ignore[operator]
        return n

    def prepend(self, dim: Optional[int]) -> "Shape":
        """`Shape.prepend` — add a lead dim (None = unknown block size)."""
        return Shape((dim,) + self.dims)

    @property
    def tail(self) -> "Shape":
        """`Shape.tail` — drop the lead dim (block shape -> cell shape)."""
        if self.rank == 0:
            raise ValueError("cannot take tail of a scalar shape")
        return Shape(self.dims[1:])

    def drop_inner(self) -> "Shape":
        """`Shape.dropInner` — drop the innermost dim."""
        if self.rank == 0:
            raise ValueError("cannot drop inner dim of a scalar shape")
        return Shape(self.dims[:-1])

    # ---- precision lattice (Shape.scala:54-59) -------------------------
    def check_more_precise_than(self, other: "Shape") -> bool:
        """True iff self is compatible with, and at least as precise as, other.

        Each dim of ``self`` must equal the corresponding dim of ``other``,
        or ``other``'s dim must be unknown. Ranks must match.
        """
        if self.rank != other.rank:
            return False
        for mine, theirs in zip(self.dims, other.dims):
            if theirs is not None and mine != theirs:
                return False
        return True

    def merge(self, other: "Shape") -> Optional["Shape"]:
        """Widening merge used by analyze (`ExperimentalOperations.scala:168-178`).

        Mismatched dims widen to unknown; mismatched ranks return None
        (incompatible — reference raises in that case).
        """
        if self.rank != other.rank:
            return None
        return Shape(
            a if a == b else None for a, b in zip(self.dims, other.dims)
        )

    # ---- concrete-shape helpers ---------------------------------------
    def assert_concrete(self) -> Tuple[int, ...]:
        if self.has_unknown:
            raise ValueError(f"shape {self} still has unknown dims")
        return self.dims  # type: ignore[return-value]

    def __repr__(self) -> str:
        inner = ",".join("?" if d is None else str(d) for d in self.dims)
        return f"[{inner}]"


@dataclass(frozen=True)
class ColumnInfo:
    """Tensor metadata for one frame column.

    Mirrors `ColumnInformation` + `SparkTFColInfo`: a scalar type and the
    *cell* shape (shape of one row's value). The block shape is the cell
    shape with an unknown lead dim prepended (`ColumnInformation`'s shapes
    always carry an Unknown lead — `DebugRowOps.scala:449-451`).
    """

    name: str
    dtype: ScalarType
    cell_shape: Shape

    @property
    def block_shape(self) -> Shape:
        return self.cell_shape.prepend(Unknown)

    def with_name(self, name: str) -> "ColumnInfo":
        return ColumnInfo(name, self.dtype, self.cell_shape)

    def __repr__(self) -> str:
        return f"{self.name}: {self.dtype.name}{self.cell_shape}"


class FrameInfo:
    """All column metadata for a frame (`DataFrameInfo.scala`)."""

    def __init__(self, cols: Sequence[ColumnInfo]):
        self.cols = list(cols)
        self._by_name = {c.name: c for c in self.cols}
        if len(self._by_name) != len(self.cols):
            raise ValueError("duplicate column names")

    def __getitem__(self, name: str) -> ColumnInfo:
        return self._by_name[name]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __iter__(self):
        return iter(self.cols)

    def __len__(self) -> int:
        return len(self.cols)

    @property
    def names(self):
        return [c.name for c in self.cols]

    def explain(self) -> str:
        """Pretty-printer matching the spirit of `DataFrameInfo.explain`."""
        lines = [f"root"]
        for c in self.cols:
            lines.append(f" |-- {c.name}: {c.dtype.name} {c.cell_shape}")
        return "\n".join(lines)

    def __repr__(self) -> str:
        return f"FrameInfo({', '.join(map(repr, self.cols))})"
