"""tensorframes_tpu: a TPU-native framework for manipulating columnar
DataFrames with tensor computation graphs.

Brand-new design with the capabilities of the reference (TensorFrames,
Spark + libtensorflow): five execution verbs — ``map_rows``, ``map_blocks``
(+trimmed), ``reduce_rows``, ``reduce_blocks``, keyed ``aggregate`` — plus
shape analysis (``analyze`` / ``print_schema`` / ``append_shape``) and
placeholder inference (``block`` / ``row``). Graphs come from Python
tracing, a builder DSL, or imported TF GraphDef protos; they are lowered to
XLA via JAX, compiled once per (graph, block-shape) and sharded over a
`jax.sharding.Mesh` — ICI collectives replace the reference's
driver-funneled Spark reduces.
"""

__version__ = "0.1.0"

from .frame import Column, TensorFrame
from .schema import ColumnInfo, FrameInfo, ScalarType, Shape, Unknown

__all__ = [
    "Column",
    "TensorFrame",
    "ColumnInfo",
    "FrameInfo",
    "ScalarType",
    "Shape",
    "Unknown",
]
