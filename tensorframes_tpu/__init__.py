"""tensorframes_tpu: a TPU-native framework for manipulating columnar
DataFrames with tensor computation graphs.

Brand-new design with the capabilities of the reference (TensorFrames,
Spark + libtensorflow): five execution verbs — ``map_rows``, ``map_blocks``
(+trimmed), ``reduce_rows``, ``reduce_blocks``, keyed ``aggregate`` — plus
shape analysis (``analyze`` / ``print_schema`` / ``append_shape``) and
placeholder inference (``block`` / ``row``). Graphs come from Python
tracing, a builder DSL, or imported TF GraphDef protos; they are lowered to
XLA via JAX, compiled once per (graph, block-shape) and sharded over a
`jax.sharding.Mesh` — ICI collectives replace the reference's
driver-funneled Spark reduces.
"""

__version__ = "0.1.0"

# The reference's primary scalar type is float64 (`datatypes.scala:328+`);
# JAX silently downcasts to float32 unless x64 is enabled. TPU execution
# paths should still prefer float32/bfloat16 columns (the MXU's native
# types) — x64 here is about *correctness parity* for double columns.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from .frame import Column, TensorFrame
from .schema import ColumnInfo, FrameInfo, ScalarType, Shape, Unknown
from .api import (
    GroupedFrame,
    LazyFrame,
    aggregate,
    analyze,
    append_shape,
    block,
    block_to_row,
    explain,
    cost_analysis,
    executor_stats,
    explain_hlo,
    explain_detailed,
    group_by,
    lazy,
    map_blocks,
    map_rows,
    print_schema,
    reduce_blocks,
    reduce_blocks_stream,
    reduce_rows,
    row,
    scan,
)
from .lazy import RelationalFrame, explain_analyze
from .graph.plan import col
from .globalframe import GlobalFrame
from .graph import Graph, ShapeHints
from .graph import builder as dsl
from .runtime import Executor
from .runtime.deadline import (
    Cancelled,
    DeadlineExceeded,
    OverloadError,
    deadline_scope,
)
from .runtime.checkpoint import CheckpointError
from . import config
from . import io
from . import ingest
from . import serving
from .io import stream_dataset
from . import utils
from .utils import telemetry
from .utils.telemetry import diagnostics

# the persistent workload-profile surface: tfs.profile.snapshot() /
# .load() / WorkloadProfile.save/merge/diff (runtime/profiler.py)
from .runtime import profiler as profile

# the closed-loop autotuner: tfs.autotune() is the one-shot pass
# (recommend from a live or saved WorkloadProfile, apply through the
# pin-respecting tuned-config layer); the background loop rides
# config.autotune / TFS_AUTOTUNE below
from .runtime.autotune import autotune
from .runtime import autotune as _autotune_mod

# the incident flight recorder: importing arms the /incidents routes +
# incident_bytes gauge; tfs.incidents() lists/loads postmortem bundles
from .runtime.blackbox import incidents

# Live telemetry endpoint auto-start: serve /metrics /healthz
# /diagnostics /trace IFF the operator set TFS_TELEMETRY_PORT /
# config.telemetry_port (off by default — `maybe_serve` is a no-op
# then, and never raises).
telemetry.maybe_serve()

# Closed-loop autotuner auto-start: spin the background tuning loop
# IFF config.autotune / TFS_AUTOTUNE is on (off by default — a strict
# no-op then: no thread starts and no knob is ever mutated).
_autotune_mod.maybe_start()

__all__ = [
    "Column",
    "TensorFrame",
    "ColumnInfo",
    "FrameInfo",
    "ScalarType",
    "Shape",
    "Unknown",
    "GlobalFrame",
    "GroupedFrame",
    "LazyFrame",
    "lazy",
    "aggregate",
    "analyze",
    "append_shape",
    "block",
    "block_to_row",
    "explain",
    "explain_analyze",
    "cost_analysis",
    "executor_stats",
    "explain_hlo",
    "explain_detailed",
    "group_by",
    "map_blocks",
    "map_rows",
    "print_schema",
    "reduce_blocks",
    "reduce_blocks_stream",
    "reduce_rows",
    "row",
    "scan",
    "col",
    "RelationalFrame",
    "ingest",
    "serving",
    "stream_dataset",
    "Graph",
    "ShapeHints",
    "dsl",
    "Executor",
    "Cancelled",
    "CheckpointError",
    "DeadlineExceeded",
    "OverloadError",
    "deadline_scope",
    "telemetry",
    "diagnostics",
    "profile",
    "autotune",
    "incidents",
]
