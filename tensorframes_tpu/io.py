"""Arrow IPC file ingest/egress — the framework's data-loader edge.

The reference's data plane is Spark's: partitions of JVM rows reach the
TF runtime through boxed row⇄buffer copy loops (`datatypes.scala`,
`DataOps.scala` hot loops, SURVEY §2.1). Here the on-disk/interchange
format is Arrow IPC: record batches map to frame blocks, dense columns
go zero-copy into numpy and straight to device buffers, and the
streaming reader yields one frame per batch group so `reduce_blocks_stream`
folds files far larger than host memory (the north-star 1B-row ingest
path) with background prefetch overlapping device execution.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Iterator, Optional

from .frame import TensorFrame

__all__ = [
    "write_arrow_ipc",
    "read_arrow_ipc",
    "stream_arrow_ipc",
    "frame_to_ipc_bytes",
    "frame_from_ipc_bytes",
    "write_parquet",
    "read_parquet",
    "stream_parquet",
    "stream_dataset",
]


def _is_multi_path(path) -> bool:
    """A list/tuple, a directory, or a glob pattern routes to the
    multi-file dataset pipeline; a single file keeps the lightweight
    one-handle reader below."""
    if not isinstance(path, (str, os.PathLike)):
        return True
    p = os.fspath(path)
    return os.path.isdir(p) or _glob.has_magic(p)


def write_arrow_ipc(frame: TensorFrame, path: str) -> None:
    """Write a frame to an Arrow IPC (Feather v2) file, one record batch
    per block so the block structure survives the round trip."""
    import pyarrow as pa

    table = frame.to_arrow()
    with pa.OSFile(path, "wb") as sink:
        with pa.ipc.new_file(sink, table.schema) as writer:
            for bi in range(frame.num_blocks):
                lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
                # zero-row batches keep empty blocks through the round trip
                writer.write_batch(
                    pa.RecordBatch.from_struct_array(
                        table.slice(lo, hi - lo).to_struct_array().combine_chunks()
                    )
                )


def _frame_with_offsets(table, row_counts, num_blocks):
    """Shared tail of the file readers: ``num_blocks`` repartitions;
    otherwise the file's own chunking (record batches / row groups)
    becomes the block structure when it accounts for every row."""
    if num_blocks is not None:
        return TensorFrame.from_arrow(table, num_blocks=num_blocks)
    frame = TensorFrame.from_arrow(table)
    offsets = [0]
    for n in row_counts:
        offsets.append(offsets[-1] + n)
    if offsets[-1] == frame.nrows and len(offsets) > 2:
        frame.offsets = offsets
    return frame


def read_arrow_ipc(path: str, num_blocks: Optional[int] = None) -> TensorFrame:
    """Read a whole Arrow IPC file into one frame (record batches become
    blocks unless ``num_blocks`` repartitions)."""
    import pyarrow as pa

    with pa.OSFile(path, "rb") as source:
        reader = pa.ipc.open_file(source)
        batches = [
            reader.get_batch(bi) for bi in range(reader.num_record_batches)
        ]
        table = pa.Table.from_batches(batches, schema=reader.schema)
        batch_rows = [b.num_rows for b in batches]
    return _frame_with_offsets(table, batch_rows, num_blocks)


def stream_arrow_ipc(
    path, batches_per_frame: int = 1
) -> Iterator[TensorFrame]:
    """Lazily yield one frame per ``batches_per_frame`` record batches —
    bounded host memory regardless of file size. Feed directly to
    `reduce_blocks_stream`, whose prefetch thread overlaps the next
    read with the current device reduction.

    ``path`` may also be a directory, a glob, or a sequence of paths —
    a multi-file dataset, routed through the pipelined ingest engine
    (`stream_dataset`: deterministic shard order, parallel decode).

    The file handle closes via try/finally the moment the stream ends,
    errors, or the consumer ``close()``s / abandons the generator (the
    pipeline runtime closes its source deterministically) — never
    "whenever GC gets to it", which on a pipeline thread could be long
    after the stream died."""
    if _is_multi_path(path):
        return stream_dataset(
            path, format="ipc", chunk_groups=batches_per_frame
        )
    return _stream_arrow_ipc_single(os.fspath(path), batches_per_frame)


def _stream_arrow_ipc_single(
    path: str, batches_per_frame: int
) -> Iterator[TensorFrame]:
    import pyarrow as pa

    if batches_per_frame < 1:
        raise ValueError("batches_per_frame must be >= 1")
    source = pa.OSFile(path, "rb")
    try:
        reader = pa.ipc.open_file(source)
        n = reader.num_record_batches
        for start in range(0, n, batches_per_frame):
            group = [
                reader.get_batch(bi)
                for bi in range(start, min(start + batches_per_frame, n))
            ]
            yield TensorFrame.from_arrow(pa.Table.from_batches(group))
    finally:
        source.close()


# ---------------------------------------------------------------------------
# In-memory Arrow IPC — the serving runtime's wire format (server and
# client bodies both go through these two helpers, so request/response
# framing cannot drift between the two ends).
# ---------------------------------------------------------------------------


def frame_to_ipc_bytes(frame: TensorFrame) -> bytes:
    """Serialize a frame to Arrow IPC STREAM bytes, one record batch per
    block (block structure survives the round trip like
    `write_arrow_ipc`, without touching the filesystem)."""
    import pyarrow as pa

    table = frame.to_arrow()
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        for bi in range(frame.num_blocks):
            lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
            writer.write_batch(
                pa.RecordBatch.from_struct_array(
                    table.slice(lo, hi - lo).to_struct_array().combine_chunks()
                )
            )
    return sink.getvalue().to_pybytes()


def frame_from_ipc_bytes(data: bytes) -> TensorFrame:
    """Rebuild a frame from `frame_to_ipc_bytes` output (record batches
    become blocks when they account for every row, exactly like the file
    reader). Shared by the serving wire path AND the durable-stream
    checkpoint payload (`runtime.checkpoint`) — one framing, two
    consumers. Empty input is refused explicitly (a truncated body /
    payload would otherwise surface as a cryptic Arrow internal
    error)."""
    import pyarrow as pa

    if not data:
        raise ValueError(
            "frame_from_ipc_bytes: empty byte string (expected an Arrow "
            "IPC stream)"
        )

    with pa.ipc.open_stream(pa.BufferReader(data)) as reader:
        batches = [b for b in reader]
        schema = reader.schema
    table = pa.Table.from_batches(batches, schema=schema)
    return _frame_with_offsets(
        table, [b.num_rows for b in batches], None
    )


# ---------------------------------------------------------------------------
# Parquet — the lake format Spark pipelines actually store (the reference
# read its DataFrames from whatever Spark loaded, commonly Parquet); row
# groups map to frame blocks the way IPC record batches do.
# ---------------------------------------------------------------------------


def write_parquet(frame: TensorFrame, path: str) -> None:
    """Write a frame as Parquet, one row group per block so the block
    structure survives the round trip (zero-row blocks cannot: Parquet
    forbids empty row groups). ``row_group_size`` pins each group to the
    block's full row count — without it pyarrow splits blocks larger
    than its 1Mi-row default into several groups."""
    import pyarrow.parquet as pq

    table = frame.to_arrow()
    writer = pq.ParquetWriter(path, table.schema)
    try:
        for bi in range(frame.num_blocks):
            lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
            if hi > lo:
                writer.write_table(
                    table.slice(lo, hi - lo), row_group_size=hi - lo
                )
    finally:
        writer.close()


def read_parquet(path: str, num_blocks: Optional[int] = None) -> TensorFrame:
    """Read a whole Parquet file into one frame (row groups become
    blocks unless ``num_blocks`` repartitions)."""
    import pyarrow.parquet as pq

    with pq.ParquetFile(path) as pf:
        table = pf.read()
        # row counts come from metadata — no per-group decode needed
        group_rows = [
            pf.metadata.row_group(i).num_rows
            for i in range(pf.metadata.num_row_groups)
        ]
    return _frame_with_offsets(table, group_rows, num_blocks)


def stream_parquet(
    path, row_groups_per_frame: int = 1
) -> Iterator[TensorFrame]:
    """Lazily yield one frame per ``row_groups_per_frame`` row groups —
    bounded host memory regardless of file size, the Parquet twin of
    `stream_arrow_ipc` (feed to `reduce_blocks_stream`).

    Multi-file datasets (directory / glob / sequence of paths) route
    through the pipelined ingest engine (`stream_dataset`); the file
    handle closes via try/finally on end, error, or consumer abandon —
    see `stream_arrow_ipc`."""
    if _is_multi_path(path):
        return stream_dataset(
            path, format="parquet", chunk_groups=row_groups_per_frame
        )
    return _stream_parquet_single(os.fspath(path), row_groups_per_frame)


def _stream_parquet_single(
    path: str, row_groups_per_frame: int
) -> Iterator[TensorFrame]:
    import pyarrow.parquet as pq

    if row_groups_per_frame < 1:
        raise ValueError("row_groups_per_frame must be >= 1")
    pf = pq.ParquetFile(path)
    try:
        n = pf.num_row_groups
        for start in range(0, n, row_groups_per_frame):
            idx = list(range(start, min(start + row_groups_per_frame, n)))
            yield TensorFrame.from_arrow(pf.read_row_groups(idx))
    finally:
        pf.close()


def stream_dataset(paths, format: str = "auto", chunk_groups: int = 1,
                   decode_workers: Optional[int] = None,
                   depth: Optional[int] = None):
    """Stream a MULTI-FILE dataset (directory / glob / explicit list of
    Parquet or Arrow IPC shards) as frames through the pipelined ingest
    engine: deterministic shard discovery -> parallel decode
    (``decode_workers`` threads) -> in-order delivery under the shared
    buffering budget. Feed to `reduce_blocks_stream`, which composes
    its H2D transfer stage and the multi-device rotation into the same
    stage graph. See `ingest.dataset.stream_dataset`."""
    from .ingest.dataset import stream_dataset as _sd

    return _sd(
        paths, format=format, chunk_groups=chunk_groups,
        decode_workers=decode_workers, depth=depth,
    )
