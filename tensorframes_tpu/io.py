"""Arrow IPC file ingest/egress — the framework's data-loader edge.

The reference's data plane is Spark's: partitions of JVM rows reach the
TF runtime through boxed row⇄buffer copy loops (`datatypes.scala`,
`DataOps.scala` hot loops, SURVEY §2.1). Here the on-disk/interchange
format is Arrow IPC: record batches map to frame blocks, dense columns
go zero-copy into numpy and straight to device buffers, and the
streaming reader yields one frame per batch group so `reduce_blocks_stream`
folds files far larger than host memory (the north-star 1B-row ingest
path) with background prefetch overlapping device execution.
"""

from __future__ import annotations

from typing import Iterator, Optional

from .frame import TensorFrame

__all__ = [
    "write_arrow_ipc",
    "read_arrow_ipc",
    "stream_arrow_ipc",
    "write_parquet",
    "read_parquet",
    "stream_parquet",
]


def write_arrow_ipc(frame: TensorFrame, path: str) -> None:
    """Write a frame to an Arrow IPC (Feather v2) file, one record batch
    per block so the block structure survives the round trip."""
    import pyarrow as pa

    table = frame.to_arrow()
    with pa.OSFile(path, "wb") as sink:
        with pa.ipc.new_file(sink, table.schema) as writer:
            for bi in range(frame.num_blocks):
                lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
                # zero-row batches keep empty blocks through the round trip
                writer.write_batch(
                    pa.RecordBatch.from_struct_array(
                        table.slice(lo, hi - lo).to_struct_array().combine_chunks()
                    )
                )


def _frame_with_offsets(table, row_counts, num_blocks):
    """Shared tail of the file readers: ``num_blocks`` repartitions;
    otherwise the file's own chunking (record batches / row groups)
    becomes the block structure when it accounts for every row."""
    if num_blocks is not None:
        return TensorFrame.from_arrow(table, num_blocks=num_blocks)
    frame = TensorFrame.from_arrow(table)
    offsets = [0]
    for n in row_counts:
        offsets.append(offsets[-1] + n)
    if offsets[-1] == frame.nrows and len(offsets) > 2:
        frame.offsets = offsets
    return frame


def read_arrow_ipc(path: str, num_blocks: Optional[int] = None) -> TensorFrame:
    """Read a whole Arrow IPC file into one frame (record batches become
    blocks unless ``num_blocks`` repartitions)."""
    import pyarrow as pa

    with pa.OSFile(path, "rb") as source:
        reader = pa.ipc.open_file(source)
        batches = [
            reader.get_batch(bi) for bi in range(reader.num_record_batches)
        ]
        table = pa.Table.from_batches(batches, schema=reader.schema)
        batch_rows = [b.num_rows for b in batches]
    return _frame_with_offsets(table, batch_rows, num_blocks)


def stream_arrow_ipc(
    path: str, batches_per_frame: int = 1
) -> Iterator[TensorFrame]:
    """Lazily yield one frame per ``batches_per_frame`` record batches —
    bounded host memory regardless of file size. Feed directly to
    `reduce_blocks_stream`, whose prefetch thread overlaps the next
    read with the current device reduction."""
    import pyarrow as pa

    if batches_per_frame < 1:
        raise ValueError("batches_per_frame must be >= 1")
    with pa.OSFile(path, "rb") as source:
        reader = pa.ipc.open_file(source)
        n = reader.num_record_batches
        for start in range(0, n, batches_per_frame):
            group = [
                reader.get_batch(bi)
                for bi in range(start, min(start + batches_per_frame, n))
            ]
            yield TensorFrame.from_arrow(pa.Table.from_batches(group))


# ---------------------------------------------------------------------------
# Parquet — the lake format Spark pipelines actually store (the reference
# read its DataFrames from whatever Spark loaded, commonly Parquet); row
# groups map to frame blocks the way IPC record batches do.
# ---------------------------------------------------------------------------


def write_parquet(frame: TensorFrame, path: str) -> None:
    """Write a frame as Parquet, one row group per block so the block
    structure survives the round trip (zero-row blocks cannot: Parquet
    forbids empty row groups). ``row_group_size`` pins each group to the
    block's full row count — without it pyarrow splits blocks larger
    than its 1Mi-row default into several groups."""
    import pyarrow.parquet as pq

    table = frame.to_arrow()
    writer = pq.ParquetWriter(path, table.schema)
    try:
        for bi in range(frame.num_blocks):
            lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
            if hi > lo:
                writer.write_table(
                    table.slice(lo, hi - lo), row_group_size=hi - lo
                )
    finally:
        writer.close()


def read_parquet(path: str, num_blocks: Optional[int] = None) -> TensorFrame:
    """Read a whole Parquet file into one frame (row groups become
    blocks unless ``num_blocks`` repartitions)."""
    import pyarrow.parquet as pq

    with pq.ParquetFile(path) as pf:
        table = pf.read()
        # row counts come from metadata — no per-group decode needed
        group_rows = [
            pf.metadata.row_group(i).num_rows
            for i in range(pf.metadata.num_row_groups)
        ]
    return _frame_with_offsets(table, group_rows, num_blocks)


def stream_parquet(
    path: str, row_groups_per_frame: int = 1
) -> Iterator[TensorFrame]:
    """Lazily yield one frame per ``row_groups_per_frame`` row groups —
    bounded host memory regardless of file size, the Parquet twin of
    `stream_arrow_ipc` (feed to `reduce_blocks_stream`)."""
    import pyarrow.parquet as pq

    if row_groups_per_frame < 1:
        raise ValueError("row_groups_per_frame must be >= 1")
    with pq.ParquetFile(path) as pf:
        n = pf.num_row_groups
        for start in range(0, n, row_groups_per_frame):
            idx = list(range(start, min(start + row_groups_per_frame, n)))
            yield TensorFrame.from_arrow(pf.read_row_groups(idx))
