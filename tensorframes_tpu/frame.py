"""TensorFrame: a columnar, block-partitioned DataFrame for tensor compute.

TPU-native replacement for the reference's Spark DataFrame substrate. Where
the reference stored data as Spark `Row` objects and paid a boxed
row->NIO-buffer conversion on every task (`DataOps.scala:63-81`,
`datatypes.scala:114-127`), a TensorFrame stores each column as a dense
numpy array of shape ``(nrows, *cell_shape)`` — already in tensor layout, so
feeding a block to the accelerator is a zero-copy (or single-copy H2D) view.

Ragged columns (rows with varying cell shapes — the reference supports these
via per-row conversion, `TFDataOps.scala:90-103`) are stored as object
arrays of per-row numpy cells; `analyze` merges their shapes with
unknown-widening exactly like `ExperimentalOperations.scala:140-178`.

Partitioning: a frame carries block boundaries (`offsets`). A *block* plays
the role of a Spark partition: `map_blocks` applies the graph once per
block, and distributed execution shards blocks across the device mesh.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from .schema import ColumnInfo, FrameInfo, ScalarType, Shape, Unknown

__all__ = ["TensorFrame", "Column"]

ArrayLike = Union[np.ndarray, Sequence]


def _is_device_array(data) -> bool:
    import jax

    return isinstance(data, jax.Array)


class Column:
    """One column: dense array (lead dim = rows) or ragged object array.

    Dense values may be host numpy OR a `jax.Array` already resident in
    device HBM (possibly sharded over a mesh) — the north-star design:
    blocks live on the accelerator, and verbs keep them there
    (BASELINE.json: "converters bypass the JVM heap and write device
    buffers")."""

    def __init__(self, name: str, data: ArrayLike, dtype: Optional[ScalarType] = None):
        self.name = name
        self._host = None  # lazy host_values() cache for device columns
        if (
            isinstance(data, np.ndarray) and data.dtype != object
        ) or _is_device_array(data):
            self.values = data
            self.ragged: Optional[List[np.ndarray]] = None
            self.dtype = dtype or ScalarType.from_np_dtype(np.dtype(data.dtype))
            # Dense storage: the cell shape is fully known.
            self.cell_shape = Shape(data.shape[1:])
        else:
            # Bulk fast path: ONE np.asarray over the whole column beats
            # a million per-cell conversions (the reference's boxed
            # row-by-row copy loop was its recorded hot spot,
            # `DataOps.scala:63-81`; this is the columnar answer).
            # Truly ragged/string/object data falls through to the
            # per-cell path below.
            # list/tuple only: np.asarray over those always COPIES, so
            # the frame can never alias caller memory (a pandas Series
            # would share its buffer), and generators still reach the
            # consuming per-cell path below.
            bulk = None
            if (
                isinstance(data, (list, tuple))
                and len(data) > 0
                and dtype is not ScalarType.string
            ):
                try:
                    bulk = np.asarray(data)
                except (ValueError, TypeError):
                    bulk = None
            if (
                bulk is not None
                and bulk.dtype != object
                and bulk.dtype.kind not in ("U", "S")
            ):
                target = dtype or ScalarType.from_np_dtype(bulk.dtype)
                self.values = bulk.astype(target.np_dtype, copy=False)
                self.ragged = None
                self.dtype = target
                self.cell_shape = Shape(self.values.shape[1:])
                return
            cells = [np.asarray(x) for x in data]
            if dtype is None:
                if not cells:
                    raise ValueError(f"empty ragged column {name!r} needs a dtype")
                if cells[0].dtype.kind in ("U", "S", "O"):
                    dtype = ScalarType.string
                else:
                    dtype = ScalarType.from_np_dtype(
                        np.result_type(*[c.dtype for c in cells])
                    )
            self.dtype = dtype
            if dtype is not ScalarType.string:
                cells = [c.astype(dtype.np_dtype) for c in cells]
            self.ragged = cells
            # Without a scan we only know the rank (mirrors the reference:
            # an ArrayType column has shape [Unknown,...] until analyzed,
            # `ColumnInformation.scala:94-111`).
            rank = cells[0].ndim if cells else 0
            if any(c.ndim != rank for c in cells):
                raise ValueError(f"column {name!r}: rows disagree on rank")
            self.cell_shape = Shape((Unknown,) * rank)
            self.values = None  # type: ignore[assignment]
            self._try_densify()

    def _try_densify(self) -> None:
        """Promote a ragged column whose cells all share one shape to dense."""
        if self.ragged is None or self.dtype is ScalarType.string:
            return
        if not self.ragged:
            return
        s0 = self.ragged[0].shape
        if all(c.shape == s0 for c in self.ragged):
            self.values = np.stack(self.ragged) if s0 else np.asarray(
                [c[()] for c in self.ragged], dtype=self.dtype.np_dtype
            )
            self.values = self.values.astype(self.dtype.np_dtype)
            self.cell_shape = Shape(s0)
            self.ragged = None

    # ------------------------------------------------------------------
    @property
    def is_dense(self) -> bool:
        return self.ragged is None

    def __len__(self) -> int:
        return len(self.values) if self.is_dense else len(self.ragged)  # type: ignore[arg-type]

    @property
    def info(self) -> ColumnInfo:
        return ColumnInfo(self.name, self.dtype, self.cell_shape)

    def slice(self, start: int, stop: int) -> "Column":
        if self.is_dense:
            return Column(self.name, self.values[start:stop], self.dtype)
        return Column(self.name, self.ragged[start:stop], self.dtype)  # type: ignore[index]

    def row(self, i: int) -> np.ndarray:
        return self.values[i] if self.is_dense else self.ragged[i]  # type: ignore[index]

    def rows(self) -> Iterable[np.ndarray]:
        return iter(self.values) if self.is_dense else iter(self.ragged)  # type: ignore[arg-type]

    def host_values(self) -> np.ndarray:
        """One host numpy array of all cells — THE device->host boundary.

        Verbs keep dense columns device-resident end to end; this is the
        single explicit point where a column crosses to the host (group
        keys, pandas/Arrow export, user materialization). The copy is
        lazy and cached: the first call on a device column blocks on the
        async pipeline and pays one D2H transfer (counted in the
        ``host_sync`` profiling stat); later calls return the cached
        array. Host-numpy columns return their array as-is. Scalar
        string/object columns — which never densify because they cannot
        go to device — assemble an object vector (the reference grouped
        by ANY Catalyst column type, so string group keys must work)."""
        if self.is_dense:
            if isinstance(self.values, np.ndarray):
                return self.values
            if self._host is None:
                from .utils import telemetry as _tele
                from .utils.profiling import count

                count("host_sync")
                # host-sync leaf span: blocks on the async pipeline and
                # pays the D2H copy; attributed to the enclosing
                # dispatch's program when one is active
                with _tele.span(
                    "host_sync", kind="host_sync", column=self.name,
                    program=_tele.current_program(),
                ):
                    self._host = np.asarray(self.values)
                if _tele.enabled():
                    _tele.histogram_observe(
                        "d2h_bytes", float(self._host.nbytes)
                    )
            return self._host
        if not self.cell_shape.is_scalar:
            raise ValueError(
                f"column {self.name!r} is ragged; no single host array"
            )
        out = np.empty(len(self.ragged), dtype=object)  # type: ignore[arg-type]
        for i, c in enumerate(self.ragged):  # type: ignore[union-attr]
            out[i] = np.asarray(c)[()]  # ragged cells are 0-d ndarrays here
        return out

    def analyzed_cell_shape(self) -> Shape:
        """Scan all cells and merge shapes with unknown-widening
        (`ExperimentalOperations.scala:140-178`)."""
        if self.is_dense:
            return self.cell_shape
        merged: Optional[Shape] = None
        for c in self.ragged:  # type: ignore[union-attr]
            s = Shape(c.shape)
            if merged is None:
                merged = s
            else:
                m = merged.merge(s)
                if m is None:
                    raise ValueError(
                        f"column {self.name!r}: rows disagree on rank "
                        f"({merged} vs {s})"
                    )
                merged = m
        return merged if merged is not None else self.cell_shape

    def with_info(self, info: ColumnInfo) -> "Column":
        c = Column.__new__(Column)
        c.name = info.name
        c.values = self.values
        c.ragged = self.ragged
        c.dtype = info.dtype
        c.cell_shape = info.cell_shape
        c._host = self._host  # same buffer, so the host cache carries over
        return c


class TensorFrame:
    """Columnar, block-partitioned frame.

    ``offsets`` are block boundaries: block i covers rows
    ``offsets[i]:offsets[i+1]``. Blocks correspond to the reference's Spark
    partitions (each `map_blocks` graph application sees one block,
    `DebugRowOps.scala:384-398`).
    """

    def __init__(
        self,
        columns: Sequence[Column],
        offsets: Optional[Sequence[int]] = None,
    ):
        if not columns:
            raise ValueError("a TensorFrame needs at least one column")
        self._cols: Dict[str, Column] = {}
        n = len(columns[0])
        for c in columns:
            if len(c) != n:
                raise ValueError(
                    f"column {c.name!r} has {len(c)} rows, expected {n}"
                )
            if c.name in self._cols:
                raise ValueError(f"duplicate column {c.name!r}")
            self._cols[c.name] = c
        self.nrows = n
        if offsets is None:
            offsets = [0, n]
        offsets = list(offsets)
        if offsets[0] != 0 or offsets[-1] != n or any(
            offsets[i] > offsets[i + 1] for i in range(len(offsets) - 1)
        ):
            raise ValueError(f"bad block offsets {offsets} for {n} rows")
        self.offsets = offsets

    # ---- constructors --------------------------------------------------
    @classmethod
    def from_dict(
        cls,
        data: Dict[str, ArrayLike],
        num_blocks: Optional[int] = None,
        dtypes: Optional[Dict[str, ScalarType]] = None,
    ) -> "TensorFrame":
        cols = [
            Column(name, values, (dtypes or {}).get(name))
            for name, values in data.items()
        ]
        tf = cls(cols)
        if num_blocks is not None:
            tf = tf.repartition(num_blocks)
        return tf

    @classmethod
    def from_pandas(cls, pdf, num_blocks: Optional[int] = None) -> "TensorFrame":
        data = {}
        for name in pdf.columns:
            series = pdf[name]
            if series.dtype == object:
                data[name] = list(series)
            else:
                data[name] = series.to_numpy()
        return cls.from_dict(data, num_blocks=num_blocks)

    @classmethod
    def from_arrow(cls, table, num_blocks: Optional[int] = None) -> "TensorFrame":
        """Build from a pyarrow Table: primitive columns become dense,
        fixed-size-list columns dense vectors, list columns ragged. This
        is the interchange path for Spark-style ingestion (Arrow IPC from
        executor partitions; SURVEY.md §7.7's bridge)."""
        import pyarrow as pa

        data: Dict[str, ArrayLike] = {}
        for name in table.column_names:
            col = table.column(name).combine_chunks()
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                flat = col.values.to_numpy(zero_copy_only=False)
                data[name] = flat.reshape(-1, width)
            elif pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
                data[name] = [
                    np.asarray(x) for x in col.to_pylist()
                ]
            else:
                data[name] = col.to_numpy(zero_copy_only=False)
        return cls.from_dict(data, num_blocks=num_blocks)

    def to_arrow(self):
        """Export to a pyarrow Table (dense vectors as fixed-size lists,
        ragged as lists)."""
        import pyarrow as pa

        arrays = []
        names = []
        for name in self.columns:
            c = self.column(name)
            names.append(name)
            if c.is_dense and c.cell_shape.is_scalar:
                arrays.append(pa.array(np.asarray(c.values)))
            elif c.is_dense and c.cell_shape.rank == 1:
                vals = np.asarray(c.values)
                width = vals.shape[1]
                arrays.append(
                    pa.FixedSizeListArray.from_arrays(
                        pa.array(vals.ravel()), width
                    )
                )
            else:
                arrays.append(
                    pa.array([np.asarray(r).tolist() for r in c.rows()])
                )
        return pa.table(dict(zip(names, arrays)))

    def pad_ragged(self, col_name: str, length_col: Optional[str] = None) -> "TensorFrame":
        """Materialize a ragged rank-1 column as a zero-padded dense column
        plus a length column — the masked-execution bridge for block-level
        ops over variable-length rows (the reference ran these per-row,
        `TFDataOps.scala:90-103`; padding + masks is the XLA-native way).
        Uses the native pack kernel when built."""
        c = self.column(col_name)
        if c.is_dense:
            return self
        if c.cell_shape.rank != 1:
            raise ValueError("pad_ragged supports rank-1 ragged columns")
        from .native import pack_ragged

        cells = [np.asarray(r) for r in c.rows()]
        packed = pack_ragged(cells)
        if packed is None:  # pure-python fallback
            max_len = max(x.size for x in cells)
            out = np.zeros((len(cells), max_len), dtype=cells[0].dtype)
            lens = np.empty(len(cells), np.int32)
            for i, x in enumerate(cells):
                out[i, : x.size] = x
                lens[i] = x.size
        else:
            out, lens = packed
        new_cols = [
            Column(col_name, out, c.dtype),
            Column(length_col or f"{col_name}_len", lens),
        ]
        return self.with_columns(new_cols)

    @classmethod
    def from_rows(
        cls,
        rows: Sequence[Dict[str, ArrayLike]],
        num_blocks: Optional[int] = None,
    ) -> "TensorFrame":
        if not rows:
            raise ValueError("from_rows needs at least one row")
        names = list(rows[0].keys())
        data = {n: [r[n] for r in rows] for n in names}
        return cls.from_dict(data, num_blocks=num_blocks)

    # ---- basic accessors ----------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._cols.keys())

    @property
    def info(self) -> FrameInfo:
        return FrameInfo([c.info for c in self._cols.values()])

    def column(self, name: str) -> Column:
        if name not in self._cols:
            raise KeyError(
                f"no column {name!r}; available: {self.columns}"
            )
        return self._cols[name]

    def __getitem__(self, name: str) -> Column:
        return self.column(name)

    @property
    def num_blocks(self) -> int:
        return len(self.offsets) - 1

    def block_sizes(self) -> List[int]:
        return [
            self.offsets[i + 1] - self.offsets[i]
            for i in range(self.num_blocks)
        ]

    def bucketed_block_sizes(self) -> List[int]:
        """The block-lead shapes dispatch will actually compile for this
        frame under the current shape policy: the bucket-ladder rung per
        block (`shape_policy.bucket_for`) with ``config.shape_bucketing``
        on, the raw `block_sizes` with it off — so ``len(set(...))`` is
        an honest compiled-shape budget either way (the introspection
        surface `benchmarks/bucketing_bench.py` and the bucketing tests
        assert against). Empty blocks map to 0 (never dispatched).
        Per-dispatch eligibility (non-row-local maps, unclassified
        reduces) can still keep individual programs on the raw sizes."""
        from . import config as _config
        from .shape_policy import bucket_for

        if not _config.get().shape_bucketing:
            return self.block_sizes()
        return [bucket_for(n) for n in self.block_sizes()]

    def block(self, i: int) -> "TensorFrame":
        lo, hi = self.offsets[i], self.offsets[i + 1]
        return TensorFrame([c.slice(lo, hi) for c in self._cols.values()])

    def blocks(self) -> Iterable["TensorFrame"]:
        for i in range(self.num_blocks):
            yield self.block(i)

    # ---- restructuring -------------------------------------------------
    def repartition(self, num_blocks: int) -> "TensorFrame":
        """Split into ``num_blocks`` near-equal blocks (like df.repartition)."""
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        edges = np.linspace(0, self.nrows, num_blocks + 1).astype(int)
        return TensorFrame(list(self._cols.values()), list(edges))

    def select(self, names: Sequence[str]) -> "TensorFrame":
        return TensorFrame([self.column(n) for n in names], self.offsets)

    def with_columns(self, cols: Sequence[Column]) -> "TensorFrame":
        merged = dict(self._cols)
        for c in cols:
            merged[c.name] = c
        return TensorFrame(list(merged.values()), self.offsets)

    # ---- schema ops (analyze / append_shape) ---------------------------
    def analyze(self) -> "TensorFrame":
        """Scan data, refine every column's cell shape
        (`ExperimentalOperations.analyze`, `ExperimentalOperations.scala:39-51`)."""
        new_cols = []
        for c in self._cols.values():
            info = ColumnInfo(c.name, c.dtype, c.analyzed_cell_shape())
            new_cols.append(c.with_info(info))
        return TensorFrame(new_cols, self.offsets)

    def append_shape(self, name: str, cell_shape: Shape) -> "TensorFrame":
        """Manually attach a cell shape (`ExperimentalOperations.scala:53-68`)."""
        c = self.column(name)
        info = ColumnInfo(name, c.dtype, cell_shape)
        cols = [
            c.with_info(info) if cn == name else col
            for cn, col in self._cols.items()
        ]
        return TensorFrame(cols, self.offsets)

    # ---- device placement ----------------------------------------------
    def to_device(self, mesh=None, device=None) -> "TensorFrame":
        """Move dense columns into device HBM (sharded over the mesh's
        ``data`` axis when a mesh is given; committed onto ``device``
        when one is given — the block scheduler's streaming prefetch
        targets each chunk's assigned device this way). Ragged/string
        columns stay on host. Verb outputs on a device-resident frame
        stay on device — host materialization happens only at
        `to_pandas`/`collect`."""
        import jax

        from .utils import telemetry as _tele

        if mesh is not None and device is not None:
            raise ValueError(
                "to_device: mesh= and device= are mutually exclusive"
            )
        h2d_bytes = 0
        new_cols = []
        # transfer span: the H2D issue window (device_put is async — the
        # copy itself overlaps downstream compute; this measures what the
        # caller's thread paid to start it)
        with _tele.span("to_device", kind="transfer"):
            for c in self._cols.values():
                if c.is_dense and c.dtype is not ScalarType.string:
                    # shard_to_mesh splits the lead dim over the 'data'
                    # axis only
                    if (
                        mesh is not None
                        and "data" in mesh.shape
                        and len(c) % mesh.shape["data"] == 0
                    ):
                        from .parallel.mesh import shard_to_mesh

                        host = np.asarray(c.values)
                        h2d_bytes += host.nbytes
                        vals = shard_to_mesh(mesh, host)
                    elif (
                        isinstance(c.values, jax.Array)
                        and mesh is None
                        and device is None
                    ):
                        # already device-resident: a device_put here would
                        # round-trip D2H (np.asarray blocks) then re-upload
                        new_cols.append(c)
                        continue
                    elif (
                        isinstance(c.values, jax.Array) and device is not None
                    ):
                        # device->device commit/move: async, never via host
                        vals = jax.device_put(c.values, device)
                    else:
                        host = np.asarray(c.values)
                        h2d_bytes += host.nbytes
                        vals = jax.device_put(host, device)
                    nc = Column(c.name, vals, c.dtype)
                    nc.cell_shape = c.cell_shape
                    new_cols.append(nc)
                else:
                    new_cols.append(c)
        if h2d_bytes and _tele.enabled():
            _tele.histogram_observe("h2d_bytes", float(h2d_bytes))
        return TensorFrame(new_cols, self.offsets)

    def to_global(self, mesh=None) -> "GlobalFrame":  # noqa: F821
        """Shard this frame's dense columns into single `jax.Array`s
        over a data mesh (`globalframe.GlobalFrame`): every verb on the
        result compiles to ONE SPMD program spanning all devices —
        maps run shard-local, classified reduces lower to in-program
        collectives. ``mesh`` defaults to a 1-D data mesh over every
        healthy local device. `GlobalFrame.collect()` is the inverse
        boundary (slices the sharded pad rows back off)."""
        from .globalframe import GlobalFrame

        return GlobalFrame.from_frame(self, mesh=mesh)

    # ---- lazy plans ----------------------------------------------------
    def lazy(self) -> "LazyFrame":  # noqa: F821 — forward ref, see lazy.py
        """Wrap this frame into a `LazyFrame`: subsequent graph-based
        ``map_blocks`` calls defer and fuse into one XLA program per
        block, executed at the first terminal action (`collect` /
        `host_values` / any reduce/aggregate / `.force()`). See
        `tensorframes_tpu.lazy`."""
        from .lazy import LazyFrame

        return LazyFrame(self)

    # ---- export --------------------------------------------------------
    def host_values(self, name: str) -> np.ndarray:
        """Host numpy array of one column — `Column.host_values` through
        the frame: the explicit, cached device->host boundary."""
        return self.column(name).host_values()

    def to_host(self) -> "TensorFrame":
        """Materialize every device-resident column to host numpy (one
        cached D2H copy per column; `to_device`'s inverse). The frame's
        verbs never call this — chained verbs stay on device until the
        USER crosses the boundary here or via `host_values`/`to_pandas`/
        `collect`."""
        new_cols = []
        for c in self._cols.values():
            if c.is_dense and not isinstance(c.values, np.ndarray):
                nc = Column(c.name, c.host_values(), c.dtype)
                nc.cell_shape = c.cell_shape
                new_cols.append(nc)
            else:
                new_cols.append(c)
        return TensorFrame(new_cols, self.offsets)

    def to_pandas(self):
        import pandas as pd

        data = {}
        for c in self._cols.values():
            if c.is_dense and c.cell_shape.is_scalar:
                data[c.name] = c.host_values()
            elif c.is_dense:
                # one cached D2H copy, then host-side row iteration (a
                # device column would sync once per row otherwise)
                data[c.name] = [r.tolist() for r in c.host_values()]
            else:
                data[c.name] = [np.asarray(r).tolist() for r in c.rows()]
        return pd.DataFrame(data)

    def collect(self) -> List[Dict[str, np.ndarray]]:
        # Materialize each dense column once through the cached
        # host_values boundary (a device column would otherwise pay one
        # device->host sync per cell).
        host: Dict[str, Column] = {}
        for n, c in self._cols.items():
            if c.is_dense and not isinstance(c.values, np.ndarray):
                host[n] = Column(n, c.host_values(), c.dtype)
            else:
                host[n] = c
        names = self.columns
        # zip over the arrays directly: C-level row iteration instead of
        # a Python row(i) call per cell
        col_iters = [host[n].rows() for n in names]
        return [dict(zip(names, vals)) for vals in zip(*col_iters)]

    def print_schema(self) -> None:
        print(self.info.explain())

    def __repr__(self) -> str:
        return (
            f"TensorFrame[{self.nrows} rows x {len(self._cols)} cols, "
            f"{self.num_blocks} blocks]({', '.join(map(repr, self.info))})"
        )


def factorize_keys(key_names, key_arrays):
    """Factorize one or more group-key columns into
    (key_out: name -> unique values aligned per group, inverse: row -> gid).

    Multi-key tuples combine per-key codes mixed-radix into one int64 per
    row (np.unique cannot handle 2-D object arrays), the host-side
    analogue of the Catalyst shuffle key (`DebugRowOps.scala:554-599`).
    """
    if len(key_arrays) == 1:
        arr = np.asarray(key_arrays[0])
        try:
            import pandas as pd

            # hash-based O(n) — np.unique's sort dominated keyed
            # aggregation wall time at the 10M-row benchmark scale.
            # sort=True keeps np.unique's sorted-key output contract;
            # use_na_sentinel=False keeps NaN as a real key like
            # np.unique does.
            inverse, uniq = pd.factorize(
                arr, sort=True, use_na_sentinel=False
            )
            return {key_names[0]: np.asarray(uniq)}, inverse.astype(np.int64)
        except (ImportError, TypeError):
            uniq, inverse = np.unique(arr, return_inverse=True)
            return {key_names[0]: uniq}, inverse
    per_key = [np.unique(a, return_inverse=True) for a in key_arrays]
    combo = np.zeros(len(key_arrays[0]), np.int64)
    for u, inv in per_key:
        radix = max(len(u), 1)
        if combo.max(initial=0) > (2**62) // radix:
            raise ValueError(
                "aggregate: combined group-key cardinality overflows"
            )
        combo = combo * radix + inv
    _, first_idx, inverse = np.unique(
        combo, return_index=True, return_inverse=True
    )
    key_out = {
        k: np.asarray(key_arrays[i])[first_idx]
        for i, k in enumerate(key_names)
    }
    return key_out, inverse
