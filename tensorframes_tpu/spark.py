"""First-class Spark adapter: Spark DataFrame in, result out, one call.

The reference's entire user surface was Spark DataFrames — implicit
`df.mapBlocks(...)` enrichment (`dsl/Implicits.scala:25-116`) and a Py4J
builder the Python API drove (`impl/PythonInterface.scala:26-84`); data
never left the JVM. The TPU-native divergence (docs/MIGRATION.md) is
that Spark becomes an INGEST substrate: executors dump their partitions
as Arrow IPC files on shared storage via `mapInArrow`, and the TPU host
streams those files into device memory (`io.stream_arrow_ipc` →
`reduce_blocks_stream` / per-chunk verbs) with prefetch overlapping
device execution. This module packages that recipe — previously prose
plus a test — as df-in/result-out calls:

    import tensorframes_tpu.spark as tfspark
    total = tfspark.reduce_blocks(graph, spark_df, ingest_dir="/mnt/x")
    scored = tfspark.map_blocks(graph, spark_df, fetch_names=["probs"])
    per_key = tfspark.aggregate(graph, spark_df, keys=["k"])

Only `ingest` touches the pyspark API (one `mapInArrow` + `collect` of
file paths, nothing else), so everything downstream of the dump is
exercised by pyarrow-only tests on every CI run; the pyspark half runs
under the `spark` CI extra (`pip install .[spark]`).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import uuid
from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence

import numpy as np

from . import api as _api
from . import io as _io
from .frame import TensorFrame

__all__ = [
    "IngestResult",
    "ingest",
    "map_blocks",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
]


class IngestResult(NamedTuple):
    """One ingest call's partition files plus the per-call directory
    that owns them (removed wholesale after the verb unless
    ``keep_ingest=True``)."""

    paths: List[str]
    directory: str


def _partition_dumper(ingest_dir: str):
    """The function shipped to Spark executors via ``mapInArrow``: write
    this partition's record batches as ONE Arrow IPC file in
    ``ingest_dir`` (shared storage), yield its path back to the driver.
    Pure pyarrow — independently testable without pyspark."""

    def dump(batch_iter):
        import pyarrow as pa

        batches = list(batch_iter)
        if not batches:
            return
        path = os.path.join(ingest_dir, f"part-{uuid.uuid4().hex}.arrow")
        with pa.OSFile(path, "wb") as sink:
            with pa.ipc.new_file(sink, batches[0].schema) as writer:
                for b in batches:
                    writer.write_batch(b)
        yield pa.RecordBatch.from_pydict({"path": [path]})

    return dump


def ingest(spark_df, ingest_dir: Optional[str] = None) -> IngestResult:
    """Dump every partition of ``spark_df`` to Arrow IPC files inside a
    fresh PER-CALL subdirectory of ``ingest_dir`` (or of the system
    temp dir). ``ingest_dir`` must be storage both the executors and
    this host can reach (the temp-dir default is correct only in
    `local[*]` mode, where executors share the driver's filesystem).

    The per-call subdirectory is the cleanup unit: a failed ingest
    removes it — including partitions that finished dumping before
    another executor died, which would otherwise orphan multi-GB files
    on shared storage across retries — and the verbs rmtree it after
    the result is computed."""
    if ingest_dir is not None:
        os.makedirs(ingest_dir, exist_ok=True)
    call_dir = tempfile.mkdtemp(prefix="tfs-spark-ingest-", dir=ingest_dir)
    try:
        rows = spark_df.mapInArrow(
            _partition_dumper(call_dir), "path string"
        ).collect()
    except Exception:
        shutil.rmtree(call_dir, ignore_errors=True)
        raise
    return IngestResult([r.path for r in rows], call_dir)


def _stream_paths(paths: Sequence[str]) -> Iterator[TensorFrame]:
    # one frame per FILE = one block per Spark partition (the
    # reference's partition==block model). Arrow batches inside a file
    # are only the executor's write granularity
    # (spark.sql.execution.arrow.maxRecordsPerBatch), never a block
    # boundary.
    for p in paths:
        yield _io.read_arrow_ipc(p, num_blocks=1)


def _cleanup(result: IngestResult, keep: bool) -> None:
    if not keep:
        shutil.rmtree(result.directory, ignore_errors=True)


def reduce_blocks(
    fetches,
    spark_df,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    ingest_dir: Optional[str] = None,
    keep_ingest: bool = False,
    mesh=None,
    **kw,
):
    """`tfs.reduce_blocks` over a Spark DataFrame: partitions stream
    from the ingest dir and fold on device in bounded host memory
    (`reduce_blocks_stream`), replacing the reference's driver-funneled
    `RDD.reduce` (`DebugRowOps.scala:530-533`)."""
    ing = ingest(spark_df, ingest_dir)
    try:
        return _api.reduce_blocks_stream(
            fetches,
            _stream_paths(ing.paths),
            feed_dict,
            fetch_names=fetch_names,
            mesh=mesh,
            **kw,
        )
    finally:
        _cleanup(ing, keep_ingest)


def _collected_frame(paths: Sequence[str]) -> TensorFrame:
    frames = list(_stream_paths(paths))
    if not frames:
        raise ValueError("spark ingest produced no rows")
    if len(frames) == 1:
        return frames[0]
    cols = {}
    for name in frames[0].columns:
        parts = [f.column(name) for f in frames]
        if all(c.is_dense for c in parts) and (
            len({c.values.shape[1:] for c in parts}) == 1
        ):
            cols[name] = np.concatenate([np.asarray(c.values) for c in parts])
        elif not any(c.cell_shape.rank for c in parts):
            # scalar string/object columns (group keys from Spark arrive
            # as Arrow strings): one assembled host vector
            cols[name] = np.concatenate(
                [np.asarray(c.host_values()) for c in parts]
            )
        else:
            # ragged rows (variable-length Arrow lists, or cell shapes
            # differing across partitions): keep per-row cells — the
            # verbs' ragged paths handle them like the reference's
            # variable-length map_rows (`TFDataOps.scala:90-103`)
            cols[name] = [np.asarray(r) for c in parts for r in c.rows()]
    out = TensorFrame.from_dict(cols)
    # one block per ingested chunk — the Spark partition boundaries
    offsets = [0]
    for f in frames:
        offsets.append(offsets[-1] + f.nrows)
    out.offsets = offsets
    return out


def map_blocks(
    fetches,
    spark_df,
    feed_dict: Optional[Dict[str, str]] = None,
    trim: bool = False,
    fetch_names: Optional[Sequence[str]] = None,
    ingest_dir: Optional[str] = None,
    keep_ingest: bool = False,
    mesh=None,
    **kw,
) -> TensorFrame:
    """`tfs.map_blocks` over a Spark DataFrame; each ingested partition
    is one block (the reference's partition==block model,
    `DebugRowOps.scala:384-398`). Returns the scored TensorFrame on
    this host."""
    ing = ingest(spark_df, ingest_dir)
    try:
        frame = _collected_frame(ing.paths)
        return _api.map_blocks(
            fetches,
            frame,
            feed_dict,
            trim=trim,
            fetch_names=fetch_names,
            mesh=mesh,
            **kw,
        )
    finally:
        _cleanup(ing, keep_ingest)


def map_rows(
    fetches,
    spark_df,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    ingest_dir: Optional[str] = None,
    keep_ingest: bool = False,
    **kw,
) -> TensorFrame:
    """`tfs.map_rows` over a Spark DataFrame (no ``mesh``: row-level
    maps vmap over the block on one device; shard via `map_blocks`)."""
    ing = ingest(spark_df, ingest_dir)
    try:
        return _api.map_rows(
            fetches,
            _collected_frame(ing.paths),
            feed_dict,
            fetch_names=fetch_names,
            **kw,
        )
    finally:
        _cleanup(ing, keep_ingest)


def reduce_rows(
    fetches,
    spark_df,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    ingest_dir: Optional[str] = None,
    keep_ingest: bool = False,
    mesh=None,
    **kw,
):
    ing = ingest(spark_df, ingest_dir)
    try:
        return _api.reduce_rows(
            fetches,
            _collected_frame(ing.paths),
            feed_dict,
            fetch_names=fetch_names,
            mesh=mesh,
            **kw,
        )
    finally:
        _cleanup(ing, keep_ingest)


def aggregate(
    fetches,
    spark_df,
    keys: Sequence[str],
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    ingest_dir: Optional[str] = None,
    keep_ingest: bool = False,
    mesh=None,
    **kw,
) -> TensorFrame:
    """`tfs.aggregate` over a Spark DataFrame grouped by ``keys`` — the
    `df.groupBy(k).agg(tf_output)` surface (`Implicits.scala:105-116`,
    `DebugRowOps.scala:554-599`) without the UDAF buffering machinery:
    the keyed segment plans run on device after ingest."""
    if not keys:
        raise ValueError("aggregate needs at least one key column")
    ing = ingest(spark_df, ingest_dir)
    try:
        frame = _collected_frame(ing.paths)
        return _api.aggregate(
            fetches,
            _api.group_by(frame, *keys),
            feed_dict,
            fetch_names=fetch_names,
            mesh=mesh,
            **kw,
        )
    finally:
        _cleanup(ing, keep_ingest)
