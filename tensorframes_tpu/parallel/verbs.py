"""Distributed verbs: the five operations over a device mesh.

Execution topology vs the reference (SURVEY.md §2.5, §5):

- ``map_blocks``: one block per device via `shard_map` over the ``data``
  axis — each shard applies the graph independently, exactly the
  "every partition runs the same frozen graph" model
  (`DebugRowOps.scala:384-398`) with devices in place of executors.
- ``reduce_blocks`` / ``reduce_rows``: per-shard reduce, then
  `lax.all_gather` of the per-shard partials over ICI and a final
  application of the same graph to the gathered stack — all inside ONE
  jitted program. This replaces the driver-funneled pairwise
  `RDD.reduce` (`DebugRowOps.scala:507,530-533`): no host round-trip, no
  pairwise session churn, and XLA is free to turn gather+reduce into an
  all-reduce tree over ICI.
- ``aggregate``: per-shard segment-sum into a dense (num_keys, ...) table,
  then `psum` across shards — the UDAF + Catalyst-shuffle topology
  (`DebugRowOps.scala:608-702`) becomes two collectives.

Rows are split into `ndev` equal shards; a remainder tail (rows % ndev)
runs as one extra block on a single device and its partial joins the
combine — block boundaries are arbitrary in the reference too (Spark
chose partition sizes), so this changes nothing semantically.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from functools import lru_cache, partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..frame import Column, TensorFrame
from ..graph import builder as dsl
from ..graph.analysis import analyze_graph
from ..graph.ir import Graph, base_name, parse_edge
from ..ops.lowering import build_callable
from .. import api as _api
from ..runtime.executor import Executor, default_executor, lru_get_or_insert
from ..runtime.faults import maybe_check_numerics

__all__ = [
    "map_blocks",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
    "fused_map_blocks",
    "fused_reduce_blocks",
]


_base = base_name


import contextlib


@contextlib.contextmanager
def _mesh_dispatch(name: str, program, rows: int, shards: int):
    """THE mesh-dispatch instrumentation wrapper: a `record()` span
    (``name.calls``/``.seconds``/``.rows`` counters + a ``verb`` span)
    with a nested ``dispatch`` leaf labeled by program fingerprint and
    shard count — mesh dispatches previously bypassed profiling
    entirely (only the api-level verb recorded)."""
    from ..utils import telemetry as _tele
    from ..utils.profiling import record as _rec

    with _rec(name, rows):
        with _tele.dispatch_span(
            name, program=program, rows=rows, shards=shards
        ):
            yield


def _mesh_call(name: str, program, rows: int, shards: int, fn, *args):
    """`_mesh_dispatch` instrumentation + classified transient retries
    (`runtime.faults`): one shard_map program is the mesh path's unit
    of re-execution — a pure function of its feeds, exactly like a
    block dispatch. Deterministic errors surface after one attempt;
    there is no device failover inside a mesh (the mesh OWNS its
    placement — losing a mesh device fails the verb) and no OOM split
    (halving rows would change the shard layout), so resource errors
    surface exactly."""
    from .. import config as _config
    from ..runtime import faults as _faults

    with _mesh_dispatch(name, program, rows, shards):
        return _faults.run_with_retries(
            fn, *args,
            attempts=_config.get().block_retry_attempts,
            what=name, verb=name,
        )


@lru_cache(maxsize=64)
def _mesh_sig(mesh: Mesh) -> str:
    """Cache-key signature of a mesh's concrete device identity. A
    cached shard_map program is bound to the devices it was traced
    over; two meshes with the same device COUNT but different devices
    (or a different topology) must never share an executor-cache entry,
    or the reused program would run on the old mesh's chips.

    Memoized per Mesh (hashable in jax) — on a pod-scale mesh the
    O(ndev) string build would otherwise run on every verb dispatch,
    the same hot-path cost `Graph.fingerprint` memoizes away."""
    shape = "x".join(str(int(n)) for n in mesh.devices.shape)
    # device ids are unique only per backend: cpu:0 and tpu:0 are both
    # id 0, so the platform must disambiguate (virtual-CPU dry run
    # followed by a real TPU run in one process must not share entries)
    ids = ",".join(
        f"{getattr(d, 'platform', '?')}:{int(d.id)}" for d in mesh.devices.flat
    )
    return f"{shape}@{ids}"


def _split(frame: TensorFrame, cols: Sequence[str], ndev: int):
    """(main arrays with lead = s*ndev, tail arrays with lead = r)."""
    n = frame.nrows
    s = n // ndev
    main = {c: frame.column(c).values[: s * ndev] for c in cols}
    tail = {c: frame.column(c).values[s * ndev :] for c in cols}
    return main, tail, s


def _bucketed_or_split(ex, frame, cols_used, ndev, graph, fetches, ph_ranks):
    """THE map-verb mesh bucketing gate (`map_blocks` and
    `fused_map_blocks` share it): when the shape policy is on and the
    graph is row-local, pad the whole frame so every shard is one
    bucket-ladder rung — one static `shard_map` shape per rung, no
    varying-remainder tail program; pad rows replicate the last row and
    are sliced off by the caller. Otherwise the ordinary `_split`.
    Returns ``(main, tail, s, pad_rows)`` with ``pad_rows == 0`` on the
    unbucketed path."""
    from .. import shape_policy as _sp

    if (
        cols_used
        and frame.nrows > 0
        and _sp.enabled(ex)
        and _sp.rowwise_fetches(graph, fetches, ph_ranks)
    ):
        main, tail, s, _ = _sp.pad_mesh_shards(frame, cols_used, ndev)
        return main, tail, s, s * ndev - frame.nrows
    main, tail, s = _split(frame, cols_used, ndev)
    return main, tail, s, 0


def _mesh_in_specs(params, bindings, main, col_of=None):
    """shard_map in_specs shared by every mesh map verb: bound args are
    replicated (P(None...)), column feeds shard their lead dim over the
    ``data`` axis. ``col_of`` maps a placeholder/param name to its frame
    column (identity for the function front-end)."""
    col_of = col_of or (lambda p: p)
    return tuple(
        P(*([None] * bindings[p].ndim))
        if p in bindings
        else P("data", *([None] * (main[col_of(p)].ndim - 1)))
        for p in params
    )


# ---------------------------------------------------------------------------
# map_blocks
# ---------------------------------------------------------------------------


def map_blocks(
    fetches,
    frame: TensorFrame,
    mesh: Mesh,
    feed_dict: Optional[Dict[str, str]] = None,
    trim: bool = False,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
) -> TensorFrame:
    """Distributed map_blocks: one block per device.

    Trimmed maps work too: the same graph on same-shaped shards produces
    the same output row count on every device (XLA static shapes), so the
    shard outputs concatenate cleanly — each device's rows form one block.
    Bound placeholders (``bindings``) are replicated to every device.
    """
    ex = executor or default_executor()
    bindings = {k: np.asarray(v) for k, v in (bindings or {}).items()}
    if callable(fetches) and not isinstance(fetches, dsl.Tensor):
        return _fn_mesh(
            fetches, frame, mesh, trim=trim, bindings=bindings, per_row=False
        )
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    overrides = _api._ph_overrides(
        graph, frame, feed_dict, block_level=True, bindings=bindings
    )
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _api._check_bindings(summary, bindings)
    mapping = _api._match_columns(
        summary, frame, feed_dict, block_level=True, bindings=bindings
    )
    _api._require_dense(frame, list(mapping.values()), "map_blocks")

    feed_names = sorted(summary.inputs)
    col_feeds = [n for n in feed_names if n not in bindings]
    cols_used = [mapping[n] for n in col_feeds]
    ndev = mesh.devices.size
    if trim or bindings:  # trim changes row counts; bindings replicate
        main, tail, s = _split(frame, cols_used, ndev)
        pad_rows = 0
    else:
        main, tail, s, pad_rows = _bucketed_or_split(
            ex, frame, cols_used, ndev, graph, fetch_list,
            {p: ph.shape.rank for p, ph in summary.inputs.items()},
        )

    fn = build_callable(graph, fetch_list, feed_names)
    acc: Dict[str, List] = {_base(f): [] for f in fetch_list}
    block_sizes: List[int] = []

    def _feeds(source: Dict[str, "np.ndarray"]) -> List:
        return [
            bindings[n] if n in bindings else source[mapping[n]]
            for n in feed_names
        ]

    if s > 0:
        in_specs = _mesh_in_specs(
            feed_names, bindings, main, col_of=mapping.__getitem__
        )
        out_specs = P("data")
        # in_specs depend on WHICH placeholders are bound (replicated) and
        # on feed ranks — both must be part of the cache key, or a later
        # call with a different binding set would reuse a shard_map whose
        # specs shard/replicate the wrong arguments.
        spec_sig = ";".join(str(s) for s in in_specs)
        sharded = ex.cached(
            f"shmap-{_mesh_sig(mesh)}-[{spec_sig}]",
            graph,
            fetch_list,
            feed_names,
            lambda: jax.jit(
                shard_map(
                    fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
                )
            ),
        )
        outs = _mesh_call(
            "mesh.map_blocks", graph.fingerprint(), s * ndev, ndev,
            sharded, *_feeds(main),
        )
        maybe_check_numerics(fetch_list, outs, "map_blocks (mesh shards)")
        shard_out = None
        for f, o in zip(fetch_list, outs):
            if not trim and o.shape[0] != s * ndev:
                raise ValueError(
                    f"map_blocks: output {f!r} does not preserve the block "
                    "row count; use trim=True for row-count-changing maps"
                )
            if trim:
                if shard_out is None:
                    shard_out = o.shape[0] // ndev
                elif o.shape[0] // ndev != shard_out:
                    raise ValueError(
                        "map_blocks(trim): outputs disagree on row count"
                    )
            acc[_base(f)].append(o[: frame.nrows] if pad_rows else o)
        block_sizes += [shard_out if trim else s] * ndev
    if cols_used and tail[cols_used[0]].shape[0] > 0:
        tfn = ex.callable_for(graph, fetch_list, feed_names)
        outs = _mesh_call(
            "mesh.map_blocks.tail", graph.fingerprint(),
            tail[cols_used[0]].shape[0], 1, tfn, *_feeds(tail),
        )
        maybe_check_numerics(fetch_list, outs, "map_blocks (mesh tail)")
        tail_out = None
        for f, o in zip(fetch_list, outs):
            if trim:
                tail_out = o.shape[0]
            acc[_base(f)].append(o)
        block_sizes.append(
            tail_out if trim else tail[cols_used[0]].shape[0]
        )

    out_cols = [
        Column(
            _base(f),
            _api._concat_parts(acc[_base(f)])
            if acc[_base(f)]
            else _api._empty_output(summary, _base(f), drop_lead=True),
        )
        for f in fetch_list
    ]
    if trim:
        offsets = list(np.cumsum([0] + (block_sizes or [0])))
        return _api._output_frame(
            frame, out_cols, append_input=False, offsets=offsets
        )
    return _api._output_frame(
        frame, out_cols, append_input=True, offsets=frame.offsets
    )


# ---------------------------------------------------------------------------
# map_rows
# ---------------------------------------------------------------------------


def _ragged_per_shard(
    vfn,
    columns: Sequence[Column],
    nrows: int,
    mesh: Mesh,
    out_names_hint: Optional[List[str]] = None,
):
    """The ragged bucket plan applied PER SHARD, one shard per device.

    Rows split into ``ndev`` contiguous shards; each shard runs the
    bucketed vmap (`api._run_ragged_bucketed`) with its feeds committed
    to that shard's device, so XLA executes shard ``d``'s buckets on
    device ``d`` — the reference's every-executor-runs-its-partition
    model (`DebugRowOps.scala:403-484`) with devices for executors.
    shard_map itself cannot carry ragged cells (XLA static shapes), so
    the spread is by input placement: dispatch is async, and the Python
    loop issues work to all devices before blocking on results.
    """
    devices = list(mesh.devices.flat)
    bounds = np.linspace(0, nrows, len(devices) + 1).astype(int)
    # deferred chunks from EVERY shard are collected before any
    # device->host fetch: the Python loop issues all shards' buckets
    # (async dispatch onto their devices) and _assemble_ragged blocks
    # only once, at the end
    all_chunks: Dict[str, List] = {}
    for d, dev in enumerate(devices):
        lo, hi = int(bounds[d]), int(bounds[d + 1])
        if lo == hi:
            continue

        def dev_vfn(*feeds, _dev=dev):
            return vfn(*[jax.device_put(f, _dev) for f in feeds])

        shard_cols = [
            Column(
                c.name,
                c.values[lo:hi] if c.is_dense else list(c.ragged[lo:hi]),
                c.dtype,
            )
            for c in columns
        ]
        chunks = _api._run_ragged_bucketed(
            dev_vfn, shard_cols, hi - lo,
            out_names_hint=out_names_hint, defer=True,
        )
        for name, pairs in chunks.items():
            all_chunks.setdefault(name, []).extend(
                (idx + lo, o) for idx, o in pairs
            )
    return _api._assemble_ragged(all_chunks, nrows)


def map_rows(
    fetches,
    frame: TensorFrame,
    mesh: Mesh,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
    bindings: Optional[Dict[str, "np.ndarray"]] = None,
) -> TensorFrame:
    """Distributed map_rows: rows shard across the mesh ``data`` axis.

    `DebugRowOps.mapRows` ran over every Spark partition like the other
    verbs (`DebugRowOps.scala:403-484`); here dense columns run as ONE
    ``shard_map(vmap(graph))`` program — per-row vectorization inside
    each shard, shards across devices — with the remainder tail
    (rows % ndev) vmapped on one device exactly like the local verb.
    Ragged columns run the bucket plan per shard (`_ragged_per_shard`).
    Bound placeholders (``bindings``) are replicated to every device.
    """
    ex = executor or default_executor()
    bindings = {k: np.asarray(v) for k, v in (bindings or {}).items()}
    if callable(fetches) and not isinstance(fetches, dsl.Tensor):
        return _fn_mesh(
            fetches, frame, mesh, trim=False, bindings=bindings, per_row=True
        )
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    overrides = _api._ph_overrides(
        graph, frame, feed_dict, block_level=False, bindings=bindings
    )
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _api._check_bindings(summary, bindings)
    mapping = _api._match_columns(
        summary, frame, feed_dict, block_level=False, bindings=bindings
    )
    params = sorted(summary.inputs)
    col_params = [p for p in params if p not in bindings]
    cols_used = [mapping[p] for p in col_params]
    out_names = [_base(f) for f in fetch_list]
    dense = all(frame.column(c).is_dense for c in cols_used)
    # same binding constraints as the local verb (api.map_rows)
    if bindings and not dense:
        raise ValueError(
            "map_rows: bindings are not supported with ragged feed "
            "columns; densify the columns or bake the values as constants"
        )
    if bindings and not col_params:
        raise ValueError(
            "map_rows: every placeholder is bound, so nothing varies per "
            "row; use map_blocks (or run the graph once and broadcast)"
        )
    fn = build_callable(graph, fetch_list, params)

    if not dense:
        vfn = ex.cached(
            "vmap-rows",
            graph,
            fetch_list,
            params,
            lambda: jax.jit(jax.vmap(fn)),
        )
        per_out = _ragged_per_shard(
            vfn,
            [frame.column(c) for c in cols_used],
            frame.nrows,
            mesh,
            out_names_hint=out_names,
        )
        out_cols = [
            Column(
                n,
                per_out[n]
                if n in per_out
                else _api._empty_output(summary, n, drop_lead=False),
            )
            for n in out_names
        ]
        return _api._output_frame(frame, out_cols, append_input=True)

    ndev = mesh.devices.size
    main, tail, s = _split(frame, cols_used, ndev)
    in_axes = tuple(None if p in bindings else 0 for p in params)

    def _feeds(source: Dict[str, "np.ndarray"]) -> List:
        return [
            bindings[p] if p in bindings else source[mapping[p]]
            for p in params
        ]

    acc: Dict[str, List] = {n: [] for n in out_names}
    if s > 0:
        in_specs = _mesh_in_specs(
            params, bindings, main, col_of=mapping.__getitem__
        )
        spec_sig = ";".join(str(sp) for sp in in_specs)
        sharded = ex.cached(
            f"shmap-rows-{_mesh_sig(mesh)}-[{spec_sig}]",
            graph,
            fetch_list,
            params,
            lambda: jax.jit(
                shard_map(
                    jax.vmap(fn, in_axes=in_axes),
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=P("data"),
                )
            ),
        )
        outs = _mesh_call(
            "mesh.map_rows", graph.fingerprint(), s * ndev, ndev,
            sharded, *_feeds(main),
        )
        maybe_check_numerics(fetch_list, outs, "map_rows (mesh shards)")
        for n, o in zip(out_names, outs):
            acc[n].append(o)
    if cols_used and tail[cols_used[0]].shape[0] > 0:
        # same cache key as the local verb: the tail program IS the
        # local vmap program, so the two paths share one executable
        bind_sig = ",".join(sorted(bindings))
        vfn = ex.cached(
            f"vmap-rows-[{bind_sig}]" if bindings else "vmap-rows",
            graph,
            fetch_list,
            params,
            lambda: jax.jit(jax.vmap(fn, in_axes=in_axes)),
        )
        outs = _mesh_call(
            "mesh.map_rows.tail", graph.fingerprint(),
            tail[cols_used[0]].shape[0], 1, vfn, *_feeds(tail),
        )
        maybe_check_numerics(fetch_list, outs, "map_rows (mesh tail)")
        for n, o in zip(out_names, outs):
            acc[n].append(o)
    out_cols = [
        Column(
            n,
            _api._concat_parts(parts)
            if parts
            else _api._empty_output(summary, n, drop_lead=False),
        )
        for n, parts in acc.items()
    ]
    return _api._output_frame(frame, out_cols, append_input=True)


# Compiled-program cache for the function front-end: the graph paths
# key on Graph.fingerprint via ex.cached, but a user function has no
# fingerprint — key on the function OBJECT (same discipline as jax.jit's
# own cache: a fresh lambda per call still recompiles, a named fn
# reused across calls does not).
_FN_MESH_CACHE: "OrderedDict[Tuple, Callable]" = OrderedDict()
_FN_MESH_LOCK = threading.Lock()
_FN_MESH_LIMIT = 64


def _fn_mesh_cached(key: Tuple, make: Callable) -> Callable:
    return lru_get_or_insert(
        _FN_MESH_CACHE, _FN_MESH_LOCK, key, make, _FN_MESH_LIMIT
    )[0]


def _fn_mesh(
    fn,
    frame: TensorFrame,
    mesh: Mesh,
    trim: bool,
    bindings: Dict[str, "np.ndarray"],
    per_row: bool,
) -> TensorFrame:
    """Function front-end for the mesh map verbs (map_blocks/map_rows).

    Mirrors `api._map_blocks_fn` / `api._map_rows_fn` validation, with
    the dense path run as one ``shard_map`` program over the ``data``
    axis (+ single-device tail) and, for per-row ragged columns, the
    bucket plan per shard.
    """
    verb = "map_rows" if per_row else "map_blocks"
    params = _api._fn_feed_columns(fn, frame, bound=set(bindings))
    unknown = sorted(set(bindings) - set(params))
    if unknown:
        raise ValueError(
            f"bindings {unknown} do not match any function parameter "
            f"(parameters: {params})"
        )
    col_params = [p for p in params if p not in bindings]

    def wrapped(*cells):
        return _api._fn_outputs_to_dict(fn(*cells), verb)

    dense = all(frame.column(p).is_dense for p in col_params)
    if per_row:
        if bindings and not col_params:
            raise ValueError(
                f"{verb}: every parameter is bound, so nothing varies per "
                "row; use map_blocks (or call the function directly)"
            )
        if bindings and not dense:
            raise ValueError(
                f"{verb}: bindings are not supported with ragged feed "
                "columns; densify the columns or bake the values as "
                "constants"
            )
        if not dense:
            vfn = _fn_mesh_cached(
                (fn, "vmap-ragged"),
                lambda: jax.jit(jax.vmap(wrapped)),
            )
            per_out = _ragged_per_shard(
                vfn,
                [frame.column(p) for p in col_params],
                frame.nrows,
                mesh,
            )
            out_cols = [Column(n, v) for n, v in per_out.items()]
            return _api._output_frame(frame, out_cols, append_input=True)
    else:
        _api._require_dense(frame, col_params, verb)

    in_axes = tuple(None if p in bindings else 0 for p in params)
    base = jax.vmap(wrapped, in_axes=in_axes) if per_row else wrapped
    ndev = mesh.devices.size
    main, tail, s = _split(frame, col_params, ndev)

    def _feeds(source: Dict[str, "np.ndarray"]) -> List:
        return [
            bindings[p] if p in bindings else source[p] for p in params
        ]

    def _validate(name: str, o, rows: int, expect: Optional[int]):
        """Lead-dim / row-count contract shared with the local verbs."""
        if not per_row:
            if o.ndim == 0:
                raise ValueError(
                    f"{verb}: output {name!r} must have a lead (row) dim"
                    + ("" if trim else "; use trim=True for reductions")
                )
            if not trim and o.shape[0] != rows:
                raise ValueError(
                    f"{verb}: output {name!r} does not preserve the block "
                    "row count; use trim=True"
                )
            if trim and expect is not None and o.shape[0] != expect:
                raise ValueError(
                    f"{verb}(trim): outputs disagree on row count"
                )

    acc: Dict[str, List] = {}
    block_sizes: List[int] = []
    if s > 0:
        in_specs = _mesh_in_specs(params, bindings, main)
        spec_sig = ";".join(str(sp) for sp in in_specs)
        sharded = _fn_mesh_cached(
            (fn, "shard", _mesh_sig(mesh), spec_sig, in_axes, per_row),
            lambda: jax.jit(
                shard_map(
                    base, mesh=mesh, in_specs=in_specs, out_specs=P("data")
                )
            ),
        )
        outs = sharded(*_feeds(main))
        shard_out = None
        for name, o in outs.items():
            _validate(
                name, o, s * ndev,
                None if shard_out is None else shard_out * ndev,
            )
            if trim:
                shard_out = o.shape[0] // ndev
            acc.setdefault(name, []).append(o)
        block_sizes += [shard_out if trim else s] * ndev
    if col_params and tail[col_params[0]].shape[0] > 0:
        jfn = _fn_mesh_cached(
            (fn, "tail", in_axes, per_row), lambda: jax.jit(base)
        )
        outs = jfn(*_feeds(tail))
        tail_rows = tail[col_params[0]].shape[0]
        tail_out = None
        for name, o in outs.items():
            _validate(name, o, tail_rows, tail_out)
            if trim:
                tail_out = o.shape[0]
            acc.setdefault(name, []).append(o)
        block_sizes.append(tail_out if trim else tail_rows)
    if not acc:  # zero rows everywhere: names/dtypes from an abstract trace
        empties = _api._empty_fn_outputs(
            _fn_mesh_cached(
                (fn, "tail", in_axes, per_row), lambda: jax.jit(base)
            ),
            [
                bindings[p] if p in bindings
                else frame.column(p).values[:0]
                for p in params
            ],
        )
        acc = {n: [v] for n, v in empties.items()}
    out_cols = [
        Column(n, _api._concat_parts(parts)) for n, parts in acc.items()
    ]
    if trim:
        offsets = list(np.cumsum([0] + (block_sizes or [0])))
        return _api._output_frame(
            frame, out_cols, append_input=False, offsets=offsets
        )
    return _api._output_frame(
        frame, out_cols, append_input=True, offsets=frame.offsets
    )


# ---------------------------------------------------------------------------
# lazy fusion terminals (LazyFrame.force / LazyFrame.reduce_blocks, mesh=)
# ---------------------------------------------------------------------------


def fused_map_blocks(
    graph: Graph,
    frame: TensorFrame,
    mesh: Mesh,
    feed_map: Dict[str, str],
    fetch_edges: Sequence[str],
    out_names: Sequence[str],
    executor: Optional[Executor] = None,
) -> TensorFrame:
    """Force a lazy map plan on the mesh: the ENTIRE fused chain runs as
    ONE ``shard_map`` program over the ``data`` axis (+ the usual
    single-device remainder tail) — one dispatch where the eager chain
    paid one shard_map program per verb with intermediates materialized
    in HBM between them. ``feed_map`` wires fused-graph placeholders to
    base-frame columns; ``fetch_edges``/``out_names`` are the pending
    fused edges and their output column names (aligned)."""
    ex = executor or default_executor()
    feed_names = sorted(feed_map)
    cols_used = [feed_map[n] for n in feed_names]
    _api._require_dense(frame, cols_used, "lazy.force")
    ndev = mesh.devices.size
    main, tail, s, pad_rows = _bucketed_or_split(
        ex, frame, cols_used, ndev, graph, fetch_edges,
        {
            ph: frame.info[col].block_shape.rank
            for ph, col in feed_map.items()
        },
    )
    fn = build_callable(graph, list(fetch_edges), feed_names)
    acc: Dict[str, List] = {n: [] for n in out_names}
    if s > 0:
        in_specs = _mesh_in_specs(
            feed_names, {}, main, col_of=feed_map.__getitem__
        )
        spec_sig = ";".join(str(sp) for sp in in_specs)
        sharded = ex.cached(
            f"shmap-fused-{_mesh_sig(mesh)}-[{spec_sig}]",
            graph,
            fetch_edges,
            feed_names,
            lambda: jax.jit(
                shard_map(
                    fn, mesh=mesh, in_specs=in_specs, out_specs=P("data")
                )
            ),
        )
        outs = _mesh_call(
            "mesh.lazy.force", graph.fingerprint(), s * ndev, ndev,
            sharded, *[main[c] for c in cols_used],
        )
        maybe_check_numerics(out_names, outs, "lazy fused map (mesh shards)")
        for n, o in zip(out_names, outs):
            if o.shape[0] != s * ndev:
                raise ValueError(
                    f"lazy plan output {n!r} does not preserve the row "
                    "count; trimmed/reducing stages cannot be part of a "
                    "lazy map plan"
                )
            acc[n].append(o[: frame.nrows] if pad_rows else o)
    if cols_used and tail[cols_used[0]].shape[0] > 0:
        tfn = ex.callable_for(graph, fetch_edges, feed_names)
        outs = _mesh_call(
            "mesh.lazy.force.tail", graph.fingerprint(),
            tail[cols_used[0]].shape[0], 1,
            tfn, *[tail[c] for c in cols_used],
        )
        maybe_check_numerics(out_names, outs, "lazy fused map (mesh tail)")
        trows = tail[cols_used[0]].shape[0]
        for n, o in zip(out_names, outs):
            if o.ndim == 0 or o.shape[0] != trows:
                raise ValueError(
                    f"lazy plan output {n!r} does not preserve the row "
                    "count; trimmed/reducing stages cannot be part of a "
                    "lazy map plan"
                )
            acc[n].append(o)
    out_cols = [
        Column(n, _api._concat_parts(acc[n])) for n in out_names if acc[n]
    ]
    shadow = set(out_names)
    cols = out_cols + [
        frame.column(c) for c in frame.columns if c not in shadow
    ]
    return TensorFrame(cols, frame.offsets)


def fused_reduce_blocks(
    fused_graph: Graph,
    fused_fetches: Sequence[str],
    feed_map: Dict[str, str],
    frame: TensorFrame,
    rgraph: Graph,
    rfetch: Sequence[str],
    rfeed_names: Sequence[str],
    feed_src: Sequence[int],
    mesh: Mesh,
    executor: Optional[Executor] = None,
) -> Tuple:
    """Terminal fused reduce on the mesh: shard-local map chain + block
    reduce run as ONE ``shard_map`` program (fused graph), the gathered
    partials re-reduce through the PLAIN reduce graph inside the same
    program — the `reduce_blocks` local_then_gather topology with the
    whole pending pipeline in the local stage. Returns the final fetch
    tuple (in ``rfetch`` order); the caller unwraps."""
    ex = executor or default_executor()
    feed_names = sorted(feed_map)
    cols_used = [feed_map[n] for n in feed_names]
    _api._require_dense(frame, cols_used, "reduce_blocks")
    ndev = mesh.devices.size
    main, tail, s = _split(frame, cols_used, ndev)
    fn = build_callable(fused_graph, list(fused_fetches), feed_names)
    rfn = build_callable(rgraph, list(rfetch), list(rfeed_names))

    partials: List[Tuple] = []
    if s > 0:
        def local_then_gather(*cols):
            part = fn(*cols)
            gathered = [
                lax.all_gather(part[i], "data", axis=0, tiled=False)
                for i in feed_src
            ]
            return tuple(rfn(*gathered))

        in_specs = _mesh_in_specs(
            feed_names, {}, main, col_of=feed_map.__getitem__
        )
        sharded = ex.cached(
            f"shred-fused-{_mesh_sig(mesh)}",
            fused_graph,
            fused_fetches,
            feed_names,
            lambda: jax.jit(
                shard_map(
                    local_then_gather,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=P(),
                    check_vma=False,
                )
            ),
        )
        outs = _mesh_call(
            "mesh.reduce_blocks.fused", fused_graph.fingerprint(),
            s * ndev, ndev, sharded, *[main[c] for c in cols_used],
        )
        partials.append(tuple(outs))
    if cols_used and tail[cols_used[0]].shape[0] > 0:
        tfn = ex.callable_for(fused_graph, fused_fetches, feed_names)
        outs = _mesh_call(
            "mesh.reduce_blocks.fused.tail", fused_graph.fingerprint(),
            tail[cols_used[0]].shape[0], 1,
            tfn, *[tail[c] for c in cols_used],
        )
        partials.append(tuple(outs))
    if not partials:
        raise ValueError("reduce_blocks on an empty frame")
    if len(partials) == 1:
        final = tuple(partials[0])
    else:
        crfn = ex.callable_for(rgraph, rfetch, rfeed_names)
        stacked = [
            _api._stack_parts([p[i] for p in partials]) for i in feed_src
        ]
        final = tuple(crfn(*stacked))
    maybe_check_numerics(list(rfetch), list(final), "reduce_blocks (mesh, fused)")
    return final


# ---------------------------------------------------------------------------
# reduce_blocks
# ---------------------------------------------------------------------------


def reduce_blocks(
    fetches,
    frame: TensorFrame,
    mesh: Mesh,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
):
    """Distributed reduce: shard-local reduce + all-gather combine on ICI."""
    ex = executor or default_executor()
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    overrides = _api._ph_overrides(graph, frame, feed_dict, block_level=True)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _api._validate_reduce_blocks(summary, fetch_list)
    mapping = _api._match_columns(summary, frame, feed_dict, block_level=True)
    _api._require_dense(frame, list(mapping.values()), "reduce_blocks")

    feed_names = sorted(summary.inputs)
    cols_used = [mapping[n] for n in feed_names]
    ndev = mesh.devices.size
    fn = build_callable(graph, fetch_list, feed_names)
    # Both mesh reduce shapes drift with nrows — the sharded main
    # program re-specializes per distinct nrows//ndev and the remainder
    # tail per distinct nrows%ndev. For classified monoid graphs the
    # main shards pad to the bucket ladder with per-shard valid counts
    # masked inside the shard_map program (Mean excluded: regrouping
    # shard boundaries would change the equal-weight partial combine),
    # and the tail routes through the SAME masked bucketed program as
    # the local verb (shared cache entry) — both bounded to the ladder.
    from .. import shape_policy as _sp

    mask_plan = (
        _sp.masked_reduce_plan(graph, fetch_list, summary)
        if _sp.enabled(ex)
        else None
    )
    bucket_shards = (
        mask_plan is not None
        and "mean" not in mask_plan.combiners
        and cols_used
        and frame.nrows > 0
    )
    if bucket_shards and not (
        _sp.mesh_shard_plan(frame.nrows, ndev)[1] > 0
    ).all():
        # An all-pad shard emits the BARE reduction identity, and the
        # gathered combine re-feeds partials through the whole graph —
        # identity values are neutral there only when each reduce
        # consumes its placeholder DIRECTLY (Max(Abs(x)) would turn the
        # -inf identity into +inf). Same reasoning as streaming's
        # require_direct tree-fold gate; indirect graphs fall back to
        # the unbucketed shards + masked tail. Decided on the plan's
        # pure arithmetic, BEFORE paying for any padded column copy.
        bucket_shards = (
            _api._chunk_combiners(
                graph, fetch_list, summary, require_direct=True
            )
            is not None
        )
    if bucket_shards:
        main, tail, s, shard_valids = _sp.pad_mesh_shards(
            frame, cols_used, ndev
        )
    else:
        main, tail, s = _split(frame, cols_used, ndev)
    # Combining partials re-feeds fn: outputs arrive in FETCH order but
    # fn's positional args are the SORTED feed names, and with several
    # fetches those orders differ (x/n fetches sort as n_input, x_input)
    # — feeding positionally would silently swap results between
    # fetches. feed_src[j] = index of the fetch whose partial feeds
    # feed_names[j] (the host path re-keys by name the same way).
    fetch_of_feed = {_base(f) + "_input": i for i, f in enumerate(fetch_list)}
    feed_src = [fetch_of_feed[n] for n in feed_names]

    partials: List[Tuple[np.ndarray, ...]] = []
    if s > 0:
        col_specs = tuple(
            P("data", *([None] * (main[c].ndim - 1))) for c in cols_used
        )
        if bucket_shards:
            def make_masked_sharded():
                mraw = _sp.build_masked_reduce(graph, mask_plan, feed_names)

                def local_then_gather_masked(valid, *cols):
                    # valid arrives as this shard's (1,) slice of the
                    # per-shard counts; build_masked_reduce squeezes it
                    part = mraw(valid, *cols)
                    gathered = [
                        lax.all_gather(part[i], "data", axis=0, tiled=False)
                        for i in feed_src
                    ]
                    return tuple(fn(*gathered))

                return jax.jit(
                    shard_map(
                        local_then_gather_masked,
                        mesh=mesh,
                        in_specs=(P("data"),) + col_specs,
                        out_specs=P(),
                        check_vma=False,
                    )
                )

            sharded = ex.cached(
                f"shred-bkt-{_mesh_sig(mesh)}",
                graph,
                fetch_list,
                feed_names,
                make_masked_sharded,
            )
            outs = _mesh_call(
                "mesh.reduce_blocks", graph.fingerprint(), s * ndev, ndev,
                sharded, shard_valids, *[main[c] for c in cols_used],
            )
        else:
            def local_then_gather(*cols):
                part = fn(*cols)
                gathered = [
                    lax.all_gather(part[i], "data", axis=0, tiled=False)
                    for i in feed_src
                ]
                final = fn(*gathered)
                return tuple(final)

            sharded = ex.cached(
                f"shred-{_mesh_sig(mesh)}",
                graph,
                fetch_list,
                feed_names,
                lambda: jax.jit(
                    shard_map(
                        local_then_gather,
                        mesh=mesh,
                        in_specs=col_specs,
                        out_specs=P(),  # combined result is replicated
                        check_vma=False,
                    )
                ),
            )
            outs = _mesh_call(
                "mesh.reduce_blocks", graph.fingerprint(), s * ndev, ndev,
                sharded, *[main[c] for c in cols_used],
            )
        partials.append(tuple(outs))
    if cols_used and tail[cols_used[0]].shape[0] > 0:
        t = [tail[c] for c in cols_used]
        if mask_plan is not None:
            mfn = _sp.masked_callable(
                ex, graph, fetch_list, feed_names, mask_plan
            )
            outs = _mesh_call(
                "mesh.reduce_blocks.tail", graph.fingerprint(),
                t[0].shape[0], 1,
                _sp.dispatch_masked, mfn, t, t[0].shape[0],
            )
        else:
            tfn = ex.callable_for(graph, fetch_list, feed_names)
            outs = _mesh_call(
                "mesh.reduce_blocks.tail", graph.fingerprint(),
                t[0].shape[0], 1, tfn, *t,
            )
        partials.append(tuple(outs))
    if not partials:
        raise ValueError("reduce_blocks on an empty frame")
    if len(partials) == 1:
        final = tuple(partials[0])
    else:
        # device-resident combine, same discipline as the host path:
        # in-process partials (jax.Array) stack on device and re-reduce
        # without a host round-trip; native-executor partials stay on
        # host (see api._stack_parts on the double-client hazard)
        tfn = ex.callable_for(graph, fetch_list, feed_names)
        stacked = [
            _api._stack_parts([p[i] for p in partials]) for i in feed_src
        ]
        final = tuple(tfn(*stacked))
    maybe_check_numerics(fetch_list, list(final), "reduce_blocks (mesh)")
    if len(fetch_list) == 1:
        return final[0]
    return {_base(f): v for f, v in zip(fetch_list, final)}


# ---------------------------------------------------------------------------
# reduce_rows
# ---------------------------------------------------------------------------


def reduce_rows(
    fetches,
    frame: TensorFrame,
    mesh: Mesh,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
):
    """Distributed pairwise fold: scan per shard, gather, fold partials."""
    ex = executor or default_executor()
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    overrides = _api._ph_overrides(graph, frame, feed_dict, block_level=False)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _api._validate_reduce_rows(summary, fetch_list)
    mapping = _api._match_columns(summary, frame, feed_dict, block_level=False)
    _api._require_dense(frame, list(mapping.values()), "reduce_rows")

    bases = [_base(f) for f in fetch_list]
    feed_names = [b + s for b in bases for s in ("_1", "_2")]
    cols_used = [mapping[b + "_1"] for b in bases]
    ndev = mesh.devices.size
    main, tail, s = _split(frame, cols_used, ndev)
    pair = build_callable(graph, fetch_list, feed_names)

    def fold_rows(cols: Tuple):
        carry0 = tuple(c[0] for c in cols)
        xs = tuple(c[1:] for c in cols)

        def step(carry, xrow):
            feeds = []
            for i in range(len(bases)):
                feeds.extend((carry[i], xrow[i]))
            return tuple(pair(*feeds)), None

        carry, _ = lax.scan(step, carry0, xs)
        return carry

    partials: List[Tuple[np.ndarray, ...]] = []
    if s >= 1 and ndev > 0:
        def shard_fold(*cols):
            # fold_rows handles s == 1 too (zero-length scan returns the
            # carry unchanged) — no size-dependent branch may live in
            # this closure, because the compiled fn is CACHED by
            # (graph, ndev) and a branch captured at first trace would
            # silently misapply to later calls with a different shard
            # size
            local = fold_rows(cols)
            gathered = tuple(
                lax.all_gather(p, "data", axis=0, tiled=False) for p in local
            )
            return fold_rows(gathered)

        in_specs = tuple(
            P("data", *([None] * (main[c].ndim - 1))) for c in cols_used
        )
        sharded = ex.cached(
            f"shfold-{_mesh_sig(mesh)}",
            graph,
            fetch_list,
            feed_names,
            lambda: jax.jit(
                shard_map(
                    shard_fold,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=P(),
                    check_vma=False,
                )
            ),
        )
        outs = _mesh_call(
            "mesh.reduce_rows", graph.fingerprint(), s * ndev, ndev,
            sharded, *[main[c] for c in cols_used],
        )
        partials.append(tuple(np.asarray(o) for o in outs))

    # tail folds + partial combine share ONE cached program (jit
    # re-specializes per lead dim) instead of building a fresh
    # jax.jit closure per call (round-3 verdict: every other mesh
    # program was cached; these two leaked a compile per invocation)
    def _jfold():
        return ex.cached(
            "jfold",
            graph,
            fetch_list,
            feed_names,
            lambda: jax.jit(lambda *cols: fold_rows(cols)),
        )

    if cols_used and tail[cols_used[0]].shape[0] > 0:
        t = [tail[c] for c in cols_used]
        if t[0].shape[0] == 1:
            partials.append(tuple(np.asarray(x[0]) for x in t))
        else:
            partials.append(tuple(np.asarray(o) for o in _jfold()(*t)))
    if not partials:
        raise ValueError("reduce_rows on an empty frame")
    if len(partials) == 1:
        final = partials[0]
    else:
        stacked = [
            np.stack([p[i] for p in partials]) for i in range(len(bases))
        ]
        final = tuple(np.asarray(o) for o in _jfold()(*stacked))
    maybe_check_numerics(bases, list(final), "reduce_rows (mesh)")
    if len(bases) == 1:
        return final[0]
    return dict(zip(bases, final))


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------


# Shared with the host segment path so both overflow the same way.
_gid_dtype = _api._gid_dtype


def aggregate(
    fetches,
    grouped: "_api.GroupedFrame",
    mesh: Mesh,
    feed_dict: Optional[Dict[str, str]] = None,
    fetch_names: Optional[Sequence[str]] = None,
    executor: Optional[Executor] = None,
) -> TensorFrame:
    """Distributed keyed aggregation.

    Fast path for sum-shaped graphs (every fetch = `Sum` over the lead axis
    of its placeholder): shard-local `segment_sum` into a dense
    (num_keys, ...) table + `psum` over ICI — two collectives total,
    replacing the reference's UDAF buffer/compact/shuffle machinery.
    Other graphs classified as `Reduce(rowwise(placeholder), axis=0)`
    run the chunked plan with the chunk stage shard_mapped over the mesh
    (`_aggregate_mesh_general`); anything else falls back to the host
    exact plan.
    """
    frame = grouped.frame
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    if not _all_fetches_are_lead_sums(graph, fetch_list):
        return _aggregate_mesh_general(
            graph, grouped, mesh, feed_dict, fetch_list, executor
        )
    ex = executor or default_executor()
    overrides = _api._ph_overrides(graph, frame, feed_dict, block_level=True)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    _api._validate_reduce_blocks(summary, fetch_list)
    mapping = _api._match_columns(summary, frame, feed_dict, block_level=True)
    _api._require_dense(frame, list(mapping.values()), "aggregate")

    # host: factorize keys once (global key table)
    from ..frame import factorize_keys

    key_arrays = [frame.column(k).host_values() for k in grouped.keys]
    key_out, inverse = factorize_keys(grouped.keys, key_arrays)
    num_keys = len(next(iter(key_out.values())))
    gid = inverse.astype(_gid_dtype(num_keys))

    feed_names = sorted(summary.inputs)
    cols_used = [mapping[n] for n in feed_names]
    ndev = mesh.devices.size
    n = frame.nrows
    s = n // ndev

    # pow2-bucketed segment-table size: a DATA-dependent num_keys in the
    # cache key would mint a permanent compiled program per distinct key
    # cardinality (code-review r4: unbounded growth in a long-lived
    # service whose key count drifts); padding the dense table to the
    # next power of two caps distinct programs at O(log max_keys), and
    # the pad rows (no gid ever points at them) are sliced off below
    padded_keys = 1 << max(0, int(num_keys) - 1).bit_length()

    def seg_psum(gids, *cols):
        outs = []
        for c in cols:
            seg = jax.ops.segment_sum(c, gids, padded_keys)
            outs.append(lax.psum(seg, "data"))
        return tuple(outs)

    results: Dict[str, np.ndarray] = {}
    # seg_psum returns one output per FEED (sorted feed_names order); the
    # base receiving each output is the feed's x_input -> x pairing, NOT
    # fetch_list order (they differ with several fetches)
    bases = [n[: -len("_input")] for n in feed_names]
    main_cols = [frame.column(c).values[: s * ndev] for c in cols_used]
    tail_cols = [frame.column(c).values[s * ndev :] for c in cols_used]
    acc = [np.zeros(0)] * len(bases)
    if s > 0:
        in_specs = (P("data"),) + tuple(
            P("data", *([None] * (c.ndim - 1))) for c in main_cols
        )
        # cached like every other mesh program (round-3 verdict: this
        # closure recompiled on every aggregate(mesh=...) call); the
        # padded table size shapes the program, so it keys the entry
        sharded = ex.cached(
            f"shagg-sum-{_mesh_sig(mesh)}-{padded_keys}",
            graph,
            fetch_list,
            feed_names,
            lambda: jax.jit(
                shard_map(
                    seg_psum,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=P(),
                    check_vma=False,
                )
            ),
        )
        outs = _mesh_call(
            "mesh.aggregate.segment", graph.fingerprint(), s * ndev, ndev,
            sharded, gid[: s * ndev], *main_cols,
        )
        acc = [np.asarray(o)[:num_keys] for o in outs]
    if tail_cols and tail_cols[0].shape[0] > 0:
        touts = [
            np.asarray(jax.ops.segment_sum(jnp.asarray(c), gid[s * ndev :], num_keys))
            for c in tail_cols
        ]
        acc = [a + t if a.size else t for a, t in zip(acc, touts)]
    maybe_check_numerics(bases, acc, "aggregate (mesh segment fast path)")
    for b, a in zip(bases, acc):
        results[b] = a

    cols = [Column(k, v) for k, v in key_out.items()]
    cols += [Column(b, results[b]) for b in sorted(bases)]
    return TensorFrame(cols)


def _aggregate_mesh_general(
    graph: Graph,
    grouped: "_api.GroupedFrame",
    mesh: Mesh,
    feed_dict: Optional[Dict[str, str]],
    fetch_list: List[str],
    executor: Optional[Executor],
) -> TensorFrame:
    """Mesh aggregation for any chunk-safe graph (`api._chunk_combiners`).

    Round 1 only meshed `Sum(x_input, axis=0)` graphs and silently fell
    back to the host path for everything else. Here every fetch
    classified as `Reduce(rowwise(placeholder), axis=0)` — Min/Max/Mean/
    Prod/Sum over arbitrary row-local transforms — runs the pow2
    chunk-decomposition plan (`api._aggregate_chunked`) with the chunk
    stage `shard_map`ped over the mesh's ``data`` axis: per-chunk
    reductions execute devices-wide with zero collectives (chunks are
    independent), and partials combine host-side with the DERIVED monoid
    (size-weighted for Mean), so results are exact. Unclassifiable
    graphs fall back to the host exact plan rather than risking a wrong
    partial-combine — the correctness-first choice the reference makes
    with its driver-funneled reduce.
    """
    ex = executor or default_executor()
    frame = grouped.frame
    overrides = _api._ph_overrides(graph, frame, feed_dict, block_level=True)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    combiners = _api._chunk_combiners(graph, fetch_list, summary)
    if combiners is None:
        return _api.aggregate(
            graph, grouped, feed_dict, fetch_names=fetch_list,
            executor=executor,
        )
    _api._validate_reduce_blocks(summary, fetch_list)
    mapping = _api._match_columns(summary, frame, feed_dict, block_level=True)
    _api._require_dense(frame, list(mapping.values()), "aggregate")

    feed_names = sorted(summary.inputs)
    bases = [_base(f) for f in fetch_list]
    key_out, num_groups, counts, starts, col_data = _api._group_plan(
        grouped, mapping, feed_names
    )

    vfn = jax.vmap(build_callable(graph, fetch_list, feed_names))
    local = ex.cached(
        "vmap-agg", graph, fetch_list, feed_names, lambda: jax.jit(vfn)
    )
    ndev = mesh.devices.size
    # chunk feeds are (n, size, *cell) for every stage, so ONE shard_map
    # over the lead (chunk) axis serves both the chunk and combine stages
    sharded = ex.cached(
        f"shagg-{_mesh_sig(mesh)}",
        graph,
        fetch_list,
        feed_names,
        lambda: jax.jit(
            shard_map(
                vfn,
                mesh=mesh,
                in_specs=tuple(P("data") for _ in feed_names),
                out_specs=tuple(P("data") for _ in fetch_list),
                check_vma=False,
            )
        ),
    )

    def run(feeds):
        # pad_quantum=ndev makes every chunk-stage lead ndev * 2^k, so
        # this always shards on any device count, pow2 or not
        lead = feeds[0].shape[0]
        if lead >= ndev and lead % ndev == 0:
            return _mesh_call(
                "mesh.aggregate.chunk", graph.fingerprint(), lead, ndev,
                sharded, *feeds,
            )
        return local(*feeds)

    results = _api._aggregate_chunked(
        run,
        feed_names,
        col_data,
        counts,
        starts,
        num_groups,
        bases,
        combiners,
        pad_quantum=ndev,
        program=graph.fingerprint(),
    )
    if num_groups == 0:  # empty frame: zero-row outputs from analysis
        results = {
            b: _api._empty_output(summary, b, drop_lead=False) for b in bases
        }
    return _api._keyed_output(key_out, results, bases)


def _all_fetches_are_lead_sums(graph: Graph, fetch_list: List[str]) -> bool:
    """True when every fetch is `Sum(x_input, reduction_indices=[0])` —
    the segment_sum/psum fast-path pattern."""
    for f in fetch_list:
        try:
            node = graph[_base(f)]
        except KeyError:
            return False
        if node.op != "Sum":
            return False
        data_in = node.data_inputs()
        if len(data_in) != 2:
            return False
        src, _ = data_in[0]
        if graph[src].op not in ("Placeholder", "PlaceholderV2"):
            return False
        idx_node = graph[data_in[1][0]]
        if idx_node.op != "Const":
            return False
        axes = idx_node.attrs["value"].value.to_numpy().ravel().tolist()
        if axes != [0]:
            return False
    return True
