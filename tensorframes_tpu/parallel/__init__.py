"""Distributed layer: device mesh + collective verbs (replaces Spark)."""

from .mesh import Mesh, P, data_mesh, mesh_2d, shard_to_mesh
from .pipeline import pipeline_apply
from .ring import full_attention, ring_attention, seq_all_to_all
from . import verbs

__all__ = [
    "Mesh",
    "P",
    "data_mesh",
    "mesh_2d",
    "shard_to_mesh",
    "verbs",
    "ring_attention",
    "full_attention",
    "seq_all_to_all",
    "pipeline_apply",
]
