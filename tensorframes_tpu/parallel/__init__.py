"""Distributed layer: device mesh + collective verbs (replaces Spark)."""

from .mesh import Mesh, P, data_mesh, mesh_2d, shard_to_mesh
from . import verbs

__all__ = ["Mesh", "P", "data_mesh", "mesh_2d", "shard_to_mesh", "verbs"]
