"""Pipeline parallelism: GPipe-style staged execution over a mesh axis.

Each device on the ``stage`` axis holds ONE stage's parameters; a batch
is split into microbatches that flow through the ring of stages with
`lax.ppermute` handing activations to the next stage over ICI. The
steady-state schedule keeps every stage busy: with S stages and M
microbatches the pipeline runs M + S - 1 ticks (the classic bubble).

Built entirely from shard_map + collectives — no per-stage host
processes. Composes with the data axis (run inside an outer shard_map)
and with TP inside a stage.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply"]


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "stage",
    num_microbatches: int,
):
    """Run ``stage_fn`` as a pipeline over the mesh's ``axis``.

    - ``stage_fn(params, h) -> h``: one stage's computation (same
      signature on every stage; heterogeneous behavior goes in params).
    - ``stage_params``: pytree whose leaves have a leading stage axis of
      size = mesh.shape[axis]; leaf s lives on stage s.
    - ``x``: (batch, ...) activations; batch must divide
      ``num_microbatches``.

    Returns stage S-1's outputs for the whole batch.
    """
    n_stage = mesh.shape[axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} must divide num_microbatches {num_microbatches}"
        )
    mb = batch // num_microbatches
    ticks = num_microbatches + n_stage - 1

    def shard_body(params, xs):
        # params: this stage's slice (leading axis stripped by shard_map)
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        perm = [(j, (j + 1) % n_stage) for j in range(n_stage)]

        def tick(carry, t):
            buf, out = carry
            # stage 0 injects microbatch t (while t < num_microbatches)
            inject = jnp.clip(t, 0, num_microbatches - 1)
            fresh = lax.dynamic_slice_in_dim(xs, inject * mb, mb, axis=0)
            h_in = jnp.where(stage == 0, fresh, buf)
            h_out = stage_fn(params, h_in)
            # last stage records its finished microbatch (t - n_stage + 1)
            done_idx = t - (n_stage - 1)
            out = lax.cond(
                done_idx >= 0,
                lambda o: lax.dynamic_update_slice_in_dim(
                    o, h_out, jnp.maximum(done_idx, 0) * mb, axis=0
                ),
                lambda o: o,
                out,
            )
            # hand activations to the next stage around the ring
            buf = lax.ppermute(h_out, axis, perm)
            return (buf, out), None

        buf0 = jnp.zeros((mb,) + xs.shape[1:], xs.dtype)
        out0 = jnp.zeros_like(xs)
        (_, out), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))
        # only the LAST stage's `out` is the real result; broadcast it.
        # psum of (out where last stage else 0) replicates it everywhere.
        is_last = (stage == n_stage - 1).astype(out.dtype)
        return lax.psum(out * is_last, axis)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    return shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(pspec, P()),  # activations replicated in, result out
        out_specs=P(),
        check_vma=False,
    )(stage_params, x)
