"""Sequence/context parallelism: ring attention + all-to-all helpers.

The reference predates transformers (SURVEY.md §5: "long-context /
sequence parallelism: absent"), but this framework treats long-sequence
scale as first-class: sequences shard over the mesh's ``data`` axis and
attention runs BLOCKWISE around the ring —

- each device holds its local Q block and a rotating K/V block;
- at every step it accumulates flash-style online-softmax partials
  (running max + denominator, so numerics match full attention), then
  passes its K/V block to the next device with `lax.ppermute` over ICI;
- after ``ndev`` steps every Q block has attended to the full sequence
  with peak memory O(seq/ndev) per chip and compute/communication
  overlapped by XLA.

This is the standard Ring Attention construction (Liu et al. 2023) built
from XLA collectives. `seq_all_to_all` provides the Ulysses-style
alternative: re-shard between sequence-sharded and head-sharded layouts
with a single `lax.all_to_all`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "full_attention", "seq_all_to_all"]


def _online_step(q, k_blk, v_blk, m, l, o, scale, mask):
    """One blockwise online-softmax accumulation step (flash-style).

    q: (Sq, d); k_blk/v_blk: (Sk, d); m,l: (Sq,); o: (Sq, d).
    mask: (Sq, Sk) boolean, True = attend.
    """
    scores = (q @ k_blk.T) * jnp.float32(scale)  # (Sq, Sk)
    scores = jnp.where(mask, scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)  # (Sq,)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked blocks: exp(-inf - -inf) -> exp(0); weight is 0 anyway
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - safe_m[:, None])  # (Sq, Sk)
    p = jnp.where(mask, p, 0.0)
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = alpha * l + jnp.sum(p, axis=-1)
    o_new = alpha[:, None] * o + p @ v_blk
    return m_new, l_new, o_new


def _ring_shard(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-shard ring attention body (runs under shard_map)."""
    ndev = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    sq, d = q.shape[-2], q.shape[-1]
    sk = k.shape[-2]
    q32 = q.astype(jnp.float32)

    def body(i, carry):
        k_blk, v_blk, m, l, o = carry
        # which shard's K/V we currently hold
        src = (my_idx - i) % ndev
        if causal:
            q_pos = my_idx * sq + jnp.arange(sq)
            k_pos = src * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((sq, sk), dtype=bool)
        m, l, o = _online_step(
            q32, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            m, l, o, scale, mask,
        )
        # rotate K/V around the ring (ICI neighbor exchange)
        perm = [(j, (j + 1) % ndev) for j in range(ndev)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    m0 = jnp.full((sq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((sq,), jnp.float32)
    o0 = jnp.zeros((sq, d), jnp.float32)
    # under VMA tracking the loop carry must enter with the same
    # device-variance it leaves with (it picks up axis variance from the
    # rotating K/V, the axis_index masks, and q itself)
    vma = frozenset({axis_name}).union(
        *(getattr(x.aval, "vma", frozenset()) for x in (q, k, v))
    )
    m0, l0, o0 = (
        lax.pcast(x, tuple(sorted(vma)), to="varying") for x in (m0, l0, o0)
    )
    _, _, m, l, o = lax.fori_loop(0, ndev, body, (k, v, m0, l0, o0))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (shouldn't happen)
    return (o / l[:, None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "data",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over sequence-sharded q/k/v.

    Inputs are (seq, head_dim) arrays (vmap over batch/head axes outside),
    logically full-length; the function shards the sequence over ``axis``,
    runs the blockwise ring, and returns the full-length output with the
    same sharding. Sequence length must divide the axis size.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    fn = functools.partial(
        _ring_shard, axis_name=axis, causal=causal, scale=scale
    )
    spec = P(axis, None)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def full_attention(q, k, v, *, causal=False, scale=None):
    """Reference single-device attention (for conformance tests)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    scores = (q.astype(jnp.float32) @ k.astype(jnp.float32).T) * scale
    if causal:
        n, m = scores.shape
        mask = jnp.arange(n)[:, None] >= jnp.arange(m)[None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return (w @ v.astype(jnp.float32)).astype(q.dtype)


def seq_all_to_all(
    x: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "data",
    seq_axis: int,
    head_axis: int,
) -> jax.Array:
    """Ulysses-style re-shard: move the mesh sharding from the sequence
    axis to the head axis (or back) with one `lax.all_to_all` over ICI.

    x is the logical full array; sharding flips from ``seq_axis`` to
    ``head_axis``. Both axis sizes must divide the mesh axis size.
    """
    ndev = mesh.shape[axis]
    if x.shape[seq_axis] % ndev or x.shape[head_axis] % ndev:
        raise ValueError(
            f"seq axis {x.shape[seq_axis]} and head axis {x.shape[head_axis]}"
            f" must divide mesh axis size {ndev}"
        )

    in_spec = [None] * x.ndim
    in_spec[seq_axis] = axis
    out_spec = [None] * x.ndim
    out_spec[head_axis] = axis

    def shard_fn(xs):
        return lax.all_to_all(
            xs, axis, split_axis=head_axis, concat_axis=seq_axis, tiled=True
        )

    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=P(*in_spec),
        out_specs=P(*out_spec),
        check_vma=False,
    )(x)
