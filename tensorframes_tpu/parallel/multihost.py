"""Multi-host execution: DCN-spanning meshes and per-host data feeding.

Single-host code runs unchanged on a pod: initialize the process group,
build one global mesh over ALL devices, and feed each host its local
rows — XLA routes collectives over ICI within a slice and DCN across
slices (SURVEY.md §2.5's replacement for the reference's Spark substrate;
multi-host here plays the role of Spark's multi-executor cluster).

    from tensorframes_tpu.parallel import multihost as mh
    mh.initialize_distributed()            # env-driven on TPU pods
    mesh = mh.global_data_mesh()
    df = mh.host_local_frame_to_global(local_frame, mesh)
    tfs.reduce_blocks(s, df, mesh=mesh)    # all-reduce spans the pod
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..frame import Column, TensorFrame
from ..schema import ScalarType

__all__ = [
    "initialize_distributed",
    "global_data_mesh",
    "host_local_frame_to_global",
]


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """`jax.distributed.initialize` wrapper; on TPU pods all arguments are
    discovered from the environment. Idempotent, and safe to call in
    single-process runs (tests, one host).

    NOTE: must run before anything touches a JAX backend (first
    `jax.devices()` / computation) — so this function itself must not
    query device or process state before initializing.
    """
    # Already-initialized check WITHOUT touching the backend:
    # jax.process_count() would itself initialize local XLA and make
    # distributed init impossible afterwards.
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return  # jax.distributed.initialize already ran in this process
    except Exception:
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError:
        # No coordinator configured anywhere (args or environment):
        # single-process run, nothing to initialize. A genuinely
        # multi-process call must say so explicitly -> re-raise.
        if num_processes not in (None, 1) or coordinator_address is not None:
            raise
    except RuntimeError as e:
        # Backend already initialized (ordering violation). Swallowing
        # this on a pod would silently degrade every collective to
        # per-host partial results — so re-raise whenever the caller
        # asked for multi-process or a cluster environment is detected;
        # only a plain single-process late call (tests, local runs) is
        # benign.
        explicit = (
            coordinator_address is not None
            or num_processes not in (None, 1)
        )
        if explicit or _cluster_env_detected():
            raise RuntimeError(
                "jax.distributed.initialize failed; call "
                "initialize_distributed() before any JAX computation "
                "(it must run before the local backend is created)"
            ) from e


def _cluster_env_detected() -> bool:
    """True when jax's cluster auto-detection (TPU pod metadata, SLURM,
    etc.) would configure a MULTI-process job. A single-host TPU VM also
    advertises cluster metadata (is_env_present is True on a 1-host
    v5e), so presence alone is not enough — the detected process count
    must exceed one."""
    try:
        from jax._src.clusters import ClusterEnv

        for c in ClusterEnv._cluster_types:
            try:
                if not c.is_env_present():
                    continue
                return int(c.get_process_count()) > 1
            except Exception:
                return True  # present but unreadable: assume a real pod
        return False
    except Exception:
        return False


def global_data_mesh(axes: Sequence[str] = ("data",)) -> Mesh:
    """Mesh over every device in the job (all hosts)."""
    devices = np.asarray(jax.devices())
    if len(axes) == 1:
        return Mesh(devices, tuple(axes))
    raise ValueError("use parallel.mesh_2d for multi-axis meshes")


def host_local_frame_to_global(
    frame: TensorFrame, mesh: Mesh
) -> TensorFrame:
    """Assemble a global device frame from per-host local rows.

    Each process passes ITS shard of the rows; the returned frame's
    columns are global jax Arrays sharded over the mesh's ``data`` axis
    (`jax.make_array_from_process_local_data` — the host-side ring that
    replaces the reference's Spark partition placement).
    """
    new_cols = []
    for name in frame.columns:
        c = frame.column(name)
        if not c.is_dense or c.dtype is ScalarType.string:
            raise ValueError(
                f"multi-host frames need dense numeric columns ({name!r})"
            )
        spec = P("data", *([None] * c.cell_shape.rank))
        sharding = NamedSharding(mesh, spec)
        garr = jax.make_array_from_process_local_data(
            sharding, np.asarray(c.values)
        )
        nc = Column(name, garr, c.dtype)
        nc.cell_shape = c.cell_shape
        new_cols.append(nc)
    return TensorFrame(new_cols)
