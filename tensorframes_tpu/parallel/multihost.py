"""Multi-host execution: DCN-spanning meshes and per-host data feeding.

Single-host code runs unchanged on a pod: initialize the process group,
build one global mesh over ALL devices, and feed each host its local
rows — XLA routes collectives over ICI within a slice and DCN across
slices (SURVEY.md §2.5's replacement for the reference's Spark substrate;
multi-host here plays the role of Spark's multi-executor cluster).

    from tensorframes_tpu.parallel import multihost as mh
    mh.initialize_distributed()            # env-driven on TPU pods
    mesh = mh.global_data_mesh()
    df = mh.host_local_frame_to_global(local_frame, mesh)
    tfs.reduce_blocks(s, df, mesh=mesh)    # all-reduce spans the pod
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..frame import Column, TensorFrame
from ..schema import ScalarType

__all__ = [
    "initialize_distributed",
    "global_data_mesh",
    "host_local_frame_to_global",
    "analyze_global",
    "aggregate_global",
]


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """`jax.distributed.initialize` wrapper; on TPU pods all arguments are
    discovered from the environment. Idempotent, and safe to call in
    single-process runs (tests, one host).

    NOTE: must run before anything touches a JAX backend (first
    `jax.devices()` / computation) — so this function itself must not
    query device or process state before initializing.
    """
    # Already-initialized check WITHOUT touching the backend:
    # jax.process_count() would itself initialize local XLA and make
    # distributed init impossible afterwards.
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return  # jax.distributed.initialize already ran in this process
    except Exception:
        pass  # private jax internals moved: fall through to initialize
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError:
        # No coordinator configured anywhere (args or environment):
        # single-process run, nothing to initialize. A genuinely
        # multi-process call must say so explicitly -> re-raise.
        if num_processes not in (None, 1) or coordinator_address is not None:
            raise
    except RuntimeError as e:
        # Backend already initialized (ordering violation). Swallowing
        # this on a pod would silently degrade every collective to
        # per-host partial results — so re-raise whenever the caller
        # asked for multi-process or a cluster environment is detected;
        # only a plain single-process late call (tests, local runs) is
        # benign.
        explicit = (
            coordinator_address is not None
            or num_processes not in (None, 1)
        )
        if explicit or _cluster_env_detected():
            raise RuntimeError(
                "jax.distributed.initialize failed; call "
                "initialize_distributed() before any JAX computation "
                "(it must run before the local backend is created)"
            ) from e


def _cluster_env_detected() -> bool:
    """True when jax's cluster auto-detection (TPU pod metadata, SLURM,
    etc.) would configure a MULTI-process job. A single-host TPU VM also
    advertises cluster metadata (is_env_present is True on a 1-host
    v5e), so presence alone is not enough — the detected process count
    must exceed one."""
    try:
        from jax._src.clusters import ClusterEnv

        for c in ClusterEnv._cluster_types:
            try:
                if not c.is_env_present():
                    continue
                return int(c.get_process_count()) > 1
            except Exception:
                return True  # present but unreadable: assume a real pod
        return False
    except Exception:
        return False


def global_data_mesh(axes: Sequence[str] = ("data",)) -> Mesh:
    """Mesh over every device in the job (all hosts)."""
    devices = np.asarray(jax.devices())
    if len(axes) == 1:
        return Mesh(devices, tuple(axes))
    raise ValueError("use parallel.mesh_2d for multi-axis meshes")


def analyze_global(frame: TensorFrame) -> TensorFrame:
    """Distributed `analyze`: every process scans ITS local rows, then
    the per-column shapes merge across all processes with the same
    unknown-widening the reference's cluster-wide scan uses
    (`ExperimentalOperations.deepAnalyzeDataFrame`,
    `ExperimentalOperations.scala:89-132`: mapPartitions + reduce-merge).

    ``frame`` is the HOST-LOCAL frame (pre `host_local_frame_to_global`).
    Returns the local frame with globally-merged column metadata. Shapes
    are exchanged as fixed-width int vectors through one
    `process_allgather`; rank mismatches raise, like the reference.
    """
    from ..schema import Shape

    analyzed = frame.analyze()
    infos = [analyzed.info[name] for name in analyzed.columns]
    max_rank = max((i.cell_shape.rank for i in infos), default=0)
    multi = jax.process_count() > 1
    if multi:
        # agree on a global payload width first: ranks may differ across
        # hosts, and allgather needs identical shapes on every process
        from jax.experimental import multihost_utils

        max_rank = int(
            np.max(
                np.asarray(
                    multihost_utils.process_allgather(
                        np.asarray([max_rank], dtype=np.int64)
                    )
                )
            )
        )
    # encode: row per column = [rank, d0.., padded with -2]; unknown = -1
    enc = np.full((len(infos), max_rank + 1), -2, dtype=np.int64)
    for r, info in enumerate(infos):
        enc[r, 0] = info.cell_shape.rank
        for j, d in enumerate(info.cell_shape.dims):
            enc[r, 1 + j] = -1 if d is None else d

    if multi:
        all_enc = np.asarray(multihost_utils.process_allgather(enc))
    else:
        all_enc = enc[None]

    out = analyzed
    for r, info in enumerate(infos):
        merged = None
        for p in range(all_enc.shape[0]):
            rank = int(all_enc[p, r, 0])
            dims = [
                None if int(d) == -1 else int(d)
                for d in all_enc[p, r, 1 : 1 + rank]
            ]
            shape = Shape(dims)
            if merged is None:
                merged = shape
            else:
                m = merged.merge(shape)
                if m is None:
                    raise ValueError(
                        f"analyze_global: column {info.name!r} has rank "
                        f"{merged.rank} on some hosts and {shape.rank} on "
                        "others (incompatible, like the reference's "
                        "analyze rank check)"
                    )
                merged = m
        out = out.append_shape(info.name, merged)
    return out


def aggregate_global(
    fetches,
    grouped,
    feed_dict=None,
    fetch_names=None,
):
    """Distributed keyed aggregation over host-local rows.

    Topology (the Catalyst partial-aggregation shuffle re-imagined for
    hosts, `DebugRowOps.scala:554-599`): every process aggregates ITS
    local rows with the host plan (exact or chunked), the small keyed
    partial tables all-gather across processes, and partials re-combine
    per key with the fetch's derived monoid (`api._chunk_combiners`),
    size-weighted for Mean — so the full data never moves, only
    #local-keys x cell-sized partials ride DCN.

    Requires every fetch to be chunk-classifiable (Sum/Min/Max/Prod,
    float Mean over row-local transforms); anything else raises — a
    global exact plan would need shipping raw rows between hosts.
    """
    from .. import api as _api
    from ..graph.analysis import analyze_graph

    frame = grouped.frame
    graph, fetch_list = _api._as_graph(fetches, fetch_names)
    overrides = _api._ph_overrides(graph, frame, feed_dict, block_level=True)
    summary = analyze_graph(graph, fetch_list, placeholder_shapes=overrides)
    combiners = _api._chunk_combiners(graph, fetch_list, summary)
    if combiners is None:
        raise ValueError(
            "aggregate_global needs Reduce(rowwise(placeholder), axis=0) "
            "fetches (Sum/Min/Max/Prod, float Mean); rewrite the graph or "
            "aggregate host-locally"
        )
    bases = sorted(_api._base(f) for f in fetch_list)

    # 1. local partial aggregation (+ per-group row counts for Mean)
    local = _api.aggregate(graph, grouped, feed_dict, fetch_names=fetch_list)
    key_cols = list(grouped.keys)
    counts = np.bincount(
        _api.factorize_keys(
            key_cols, [frame.column(k).host_values() for k in key_cols]
        )[1]
    )

    if jax.process_count() == 1:
        return local

    # 2. all-gather the keyed partial tables (ragged across processes:
    #    pad to the global max row count, mask by true length)
    from jax.experimental import multihost_utils

    nloc = local.nrows
    lens = np.asarray(
        multihost_utils.process_allgather(np.asarray([nloc], dtype=np.int64))
    ).ravel()
    nmax = int(lens.max())

    def _gather(arr: np.ndarray) -> np.ndarray:
        pad_shape = (nmax - arr.shape[0],) + arr.shape[1:]
        padded = np.concatenate([arr, np.zeros(pad_shape, arr.dtype)])
        return np.asarray(multihost_utils.process_allgather(padded))

    def _gather_ragged(arr: np.ndarray) -> np.ndarray:
        """Gather + unpad one column across processes. String/object key
        columns (allgather moves numbers, not objects) ride as
        fixed-width UCS4 code matrices: pad every process's strings to
        the GLOBAL max character width, view as uint32, gather, decode.
        Pad rows decode to "" but are sliced off by the true lengths."""
        arr = np.asarray(arr)
        if arr.dtype == object or arr.dtype.kind in ("U", "S"):
            # bytes cells (numpy 'S' kind, Arrow binary) must DECODE,
            # not stringify: str(b"abc") is the repr "b'abc'", which
            # would silently corrupt group keys across processes.
            def _cell(x):
                if isinstance(x, bytes):
                    return x.decode("utf-8", "surrogateescape")
                return str(x)

            sarr = np.array([_cell(x) for x in arr], dtype="<U1") \
                if arr.size == 0 else np.array([_cell(x) for x in arr])
            w = max(1, sarr.dtype.itemsize // 4)
            wmax = int(
                np.asarray(
                    multihost_utils.process_allgather(
                        np.asarray([w], dtype=np.int64)
                    )
                ).max()
            )
            sarr = sarr.astype(f"<U{wmax}")
            codes = (
                sarr.view(np.uint32).reshape(len(sarr), wmax)
                if len(sarr)
                else np.zeros((0, wmax), np.uint32)
            )
            g = _gather(codes)
            flat = np.concatenate(
                [g[p, : lens[p]] for p in range(g.shape[0])]
            )
            return np.ascontiguousarray(flat).view(f"<U{wmax}").ravel()
        g = _gather(arr)
        return np.concatenate([g[p, : lens[p]] for p in range(g.shape[0])])

    gathered = {}
    for name in key_cols:
        gathered[name] = _gather_ragged(local.column(name).host_values())
    for name in bases:
        gathered[name] = _gather_ragged(np.asarray(local.column(name).values))
    gcounts = _gather(counts.astype(np.int64))
    weights = np.concatenate(
        [gcounts[p, : lens[p]] for p in range(gcounts.shape[0])]
    ).astype(np.float64)

    # 3. re-combine partials per key with the derived monoids
    key_out, inverse = _api.factorize_keys(
        key_cols, [gathered[k] for k in key_cols]
    )
    num_groups = len(next(iter(key_out.values())))
    order = np.argsort(inverse, kind="stable")
    bounds = np.concatenate(
        [[0], np.cumsum(np.bincount(inverse, minlength=num_groups))[:-1]]
    ).astype(np.int64)
    results = {
        b: _api._monoid_combine(
            gathered[b][order], bounds, combiners[b], weights=weights[order]
        )
        for b in bases
    }
    return _api._keyed_output(key_out, results, bases)


def host_local_frame_to_global(
    frame: TensorFrame, mesh: Mesh
) -> TensorFrame:
    """Assemble a global device frame from per-host local rows.

    Each process passes ITS shard of the rows; the returned frame's
    columns are global jax Arrays sharded over the mesh's ``data`` axis
    (`jax.make_array_from_process_local_data` — the host-side ring that
    replaces the reference's Spark partition placement).
    """
    new_cols = []
    for name in frame.columns:
        c = frame.column(name)
        if not c.is_dense or c.dtype is ScalarType.string:
            raise ValueError(
                f"multi-host frames need dense numeric columns ({name!r})"
            )
        spec = P("data", *([None] * c.cell_shape.rank))
        sharding = NamedSharding(mesh, spec)
        garr = jax.make_array_from_process_local_data(
            sharding, np.asarray(c.values)
        )
        nc = Column(name, garr, c.dtype)
        nc.cell_shape = c.cell_shape
        new_cols.append(nc)
    return TensorFrame(new_cols)
