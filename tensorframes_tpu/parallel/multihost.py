"""Multi-host execution: DCN-spanning meshes and per-host data feeding.

Single-host code runs unchanged on a pod: initialize the process group,
build one global mesh over ALL devices, and feed each host its local
rows — XLA routes collectives over ICI within a slice and DCN across
slices (SURVEY.md §2.5's replacement for the reference's Spark substrate;
multi-host here plays the role of Spark's multi-executor cluster).

    from tensorframes_tpu.parallel import multihost as mh
    mh.initialize_distributed()            # env-driven on TPU pods
    mesh = mh.global_data_mesh()
    df = mh.host_local_frame_to_global(local_frame, mesh)
    tfs.reduce_blocks(s, df, mesh=mesh)    # all-reduce spans the pod
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..frame import Column, TensorFrame
from ..schema import ScalarType

__all__ = [
    "initialize_distributed",
    "global_data_mesh",
    "host_local_frame_to_global",
]


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """`jax.distributed.initialize` wrapper; on TPU pods all arguments are
    discovered from the environment. Idempotent for single-process runs."""
    if jax.process_count() > 1:
        return  # already initialized
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if num_processes not in (None, 1):
            raise
        # single-process (tests / one host): nothing to initialize


def global_data_mesh(axes: Sequence[str] = ("data",)) -> Mesh:
    """Mesh over every device in the job (all hosts)."""
    devices = np.asarray(jax.devices())
    if len(axes) == 1:
        return Mesh(devices, tuple(axes))
    raise ValueError("use parallel.mesh_2d for multi-axis meshes")


def host_local_frame_to_global(
    frame: TensorFrame, mesh: Mesh
) -> TensorFrame:
    """Assemble a global device frame from per-host local rows.

    Each process passes ITS shard of the rows; the returned frame's
    columns are global jax Arrays sharded over the mesh's ``data`` axis
    (`jax.make_array_from_process_local_data` — the host-side ring that
    replaces the reference's Spark partition placement).
    """
    new_cols = []
    for name in frame.columns:
        c = frame.column(name)
        if not c.is_dense or c.dtype is ScalarType.string:
            raise ValueError(
                f"multi-host frames need dense numeric columns ({name!r})"
            )
        spec = P("data", *([None] * c.cell_shape.rank))
        sharding = NamedSharding(mesh, spec)
        garr = jax.make_array_from_process_local_data(
            sharding, np.asarray(c.values)
        )
        nc = Column(name, garr, c.dtype)
        nc.cell_shape = c.cell_shape
        new_cols.append(nc)
    return TensorFrame(new_cols)
