"""Device-mesh runtime: the substrate that replaces Spark (SURVEY.md §2.5).

The reference distributed work by handing partitions to Spark executors and
funneling reductions back to the driver (`DebugRowOps.scala:384-398`,
`:507,530`). Here the substrate is a `jax.sharding.Mesh` over TPU chips:
blocks shard across the ``data`` axis into per-device HBM, XLA collectives
(all-gather/psum) ride ICI within a slice and DCN across slices, and the
compiled program itself is the "broadcast" (replacing
`sc.broadcast(graph bytes)` at `DebugRowOps.scala:383`).

Axis vocabulary (fixed names so verbs and models compose):
- ``data``  — batch/row axis (every verb shards over this)
- ``model`` — tensor-parallel axis (used by models/, optional)

Multi-host: build the mesh from `jax.devices()` after
`jax.distributed.initialize()`; nothing here assumes single-process.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["data_mesh", "mesh_2d", "shard_to_mesh", "P", "Mesh"]


def data_mesh(num_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the ``data`` axis (the default for the verbs)."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), ("data",))


def mesh_2d(data: int, model: int, devices=None) -> Mesh:
    """2-D ``data x model`` mesh for DP+TP execution (models/)."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < data * model:
        raise ValueError(
            f"need {data * model} devices for a {data}x{model} mesh, "
            f"have {len(devices)}"
        )
    arr = np.asarray(devices[: data * model]).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def shard_to_mesh(mesh: Mesh, arr) -> jax.Array:
    """Place an array sharded over the mesh's ``data`` axis (lead dim).

    A lead dim not divisible by the data-axis size is padded up to the
    next multiple by replicating the last valid row
    (`shape_policy.pad_lead` — the same numerically-ordinary padding
    the bucket ladder uses), instead of the hard `device_put` failure
    jax raises on uneven shards. The caller owns slicing the pad rows
    back off (`GlobalFrame` tracks the valid row count and slices on
    `collect`); masked reduces mask them to the reduction identity."""
    ndata = mesh.shape["data"]
    n = arr.shape[0]
    if n % ndata:
        from ..shape_policy import pad_lead

        arr = pad_lead(arr, n, n + (ndata - n % ndata))
    spec = P("data", *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))
