"""Content-keyed materialization cache (bounded host/disk result reuse).

At serving scale the dominant pattern is many users, few distinct
queries: identical (data, program) pairs recompute from scratch on
every request. This module turns those repeats into lookups — a
bounded on-disk cache keyed on

    (data fingerprint, plan fingerprint, config digest)

where the data fingerprint is a content hash of the input frame (or a
``Dataset.fingerprint()``), the plan fingerprint covers the fused
graph's ``Graph.fingerprint()`` plus its feed wiring and output names,
and ``runtime.checkpoint.config_digest()`` folds in every
numerics-relevant knob so a precision/bucketing change can never serve
a stale result.

Entries are whole result frames serialized with
``io.frame_to_ipc_bytes`` (the PR 13 checkpoint payload format) and
committed through `runtime.checkpoint.CheckpointStore` — atomic
temp-file + ``os.replace``, sha256-verified load — so a partially
written entry is never readable. Admission is priced by the cost
ledger: a result is kept only when its recompute cost (the ledger's
modeled seconds for the program via
`runtime.costmodel.modeled_recompute_s`, falling back to the caller's
measured compute wall time) exceeds the measured store+load cost.
Eviction is LRU under ``config.materialize_cache_bytes``; the budget
is a hard bound, checked before every commit.

The cache is OFF by default (``materialize_cache_bytes = 0``): zero
behavior change, no files written. When on, `LazyFrame.force` and
serving `Endpoint.run_frame` consult it transparently; a hit records a
``materialize.load`` stage span (so ``tfs.explain_analyze`` attributes
the plan's wall time to the load, not to phantom compute) and issues
ZERO verb dispatches.

Observability: always-live ``materialize_hits`` / ``materialize_misses``
/ ``materialize_evictions`` counters, a registered ``materialize_bytes``
gauge, a "materialization cache" section in ``tfs.diagnostics()``, and
`state()` / `reset_state()` for tests (the conftest autouse reset calls
the latter).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "enabled",
    "frame_fingerprint",
    "plan_fingerprint",
    "lookup",
    "store",
    "state",
    "reset_state",
]

_SUFFIX = ".tfsmat"

_lock = threading.RLock()
# key -> {"path", "bytes", "last_used"}; insertion order irrelevant —
# LRU order is derived from last_used at eviction time
_index: Dict[str, Dict] = {}
_scanned_dir: List[Optional[str]] = [None]  # the dir the index reflects
_tmp_dir: List[Optional[str]] = [None]  # process-private default dir
_acct: Dict = {
    "hits": 0,
    "misses": 0,
    "stores": 0,
    "evictions": 0,
    "rejected": 0,  # admission pricing or budget said no
    "corrupt_dropped": 0,
    "drift_refusals": 0,
    "last_hit": None,
    "last_store": None,
}


def enabled() -> bool:
    """The cache participates only when a byte budget is configured."""
    from .. import config as _config

    return _config.get().materialize_cache_bytes > 0


def _budget() -> int:
    from .. import config as _config

    return int(_config.get().materialize_cache_bytes)


def _dir() -> str:
    """The active cache directory: ``config.materialize_cache_dir`` when
    set, else a process-private temp directory created on first use
    (entries die with the process)."""
    from .. import config as _config

    d = _config.get().materialize_cache_dir
    if d:
        os.makedirs(d, exist_ok=True)
        return d
    if _tmp_dir[0] is None:
        import tempfile

        _tmp_dir[0] = tempfile.mkdtemp(prefix="tfs-materialize-")
    return _tmp_dir[0]


def _ensure_scanned() -> None:
    """Seed the index from pre-existing entries the first time a
    directory is used (a persistent ``materialize_cache_dir`` shares
    warm results across processes). Must be called under `_lock`."""
    d = _dir()
    if _scanned_dir[0] == d:
        return
    _scanned_dir[0] = d
    _index.clear()
    try:
        names = os.listdir(d)
    except OSError:
        return
    for name in names:
        if not name.endswith(_SUFFIX):
            continue
        path = os.path.join(d, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        _index[name[: -len(_SUFFIX)]] = {
            "path": path,
            "bytes": int(st.st_size),
            "last_used": float(st.st_mtime),
        }


def _total_bytes_locked() -> int:
    return sum(e["bytes"] for e in _index.values())


def _gauge_bytes() -> float:
    with _lock:
        return float(_total_bytes_locked())


# -- fingerprints ------------------------------------------------------------


def frame_fingerprint(frame) -> Optional[str]:
    """Content hash of a HOST-resident frame: dtypes, shapes, block
    offsets and raw column bytes. Returns ``None`` when any column is
    device-resident — fingerprinting it would force a D2H sync, which a
    transparent cache must never do behind the caller's back."""
    h = hashlib.sha256()
    try:
        h.update(np.asarray(frame.offsets, dtype=np.int64).tobytes())
        for name in frame.columns:
            vals = frame.column(name).values
            h.update(name.encode())
            cells = vals if isinstance(vals, list) else [vals]
            for cell in cells:
                if not isinstance(cell, np.ndarray):
                    return None  # device array (or foreign): skip
                c = np.ascontiguousarray(cell)
                h.update(str(c.dtype).encode())
                h.update(str(c.shape).encode())
                if c.dtype.hasobject:
                    for x in c.ravel():
                        h.update(repr(x).encode())
                        h.update(b"\x1f")
                else:
                    h.update(c.tobytes())
    except Exception:
        return None
    return h.hexdigest()[:16]


def plan_fingerprint(graph_fp: str, feed_map=None, outputs=None) -> str:
    """The program half of the cache key: the fused graph's fingerprint
    plus its feed wiring and output names (two plans over one graph with
    different feed columns must never collide)."""
    blob = json.dumps(
        {
            "graph": graph_fp,
            "feeds": sorted((feed_map or {}).items()),
            "outputs": sorted(outputs or []),
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def relational_fingerprint(dag_fp: str) -> str:
    """The program half of the cache key for a RELATIONAL plan: the
    canonical DAG fingerprint (`graph.plan.plan_fingerprint` — already
    commutativity-normalized and rewrite-invariant after optimization),
    namespaced so a relational plan can never collide with a linear
    fused chain that happened to digest identically."""
    return hashlib.sha256(f"relational:{dag_fp}".encode()).hexdigest()[:16]


def _key(data_fp: str, plan_fp: str, cfg: str) -> str:
    return f"{data_fp}-{plan_fp}-{cfg}"


# -- lookup ------------------------------------------------------------------


def lookup(data_fp: str, plan_fp: str):
    """Return the cached result frame for ``(data_fp, plan_fp)`` under
    the current config digest, or ``None`` on a miss. A hit records a
    ``materialize.load`` stage span (honest `explain_analyze`
    attribution) and touches the entry's LRU clock; a corrupt entry is
    dropped and reads as a miss; an entry whose manifest fingerprints
    do not match the key it was filed under is refused loudly with a
    typed `CheckpointError` naming the drifted field."""
    if not enabled() or data_fp is None:
        return None
    from ..utils import telemetry as _tele
    from . import checkpoint as _ckpt

    cfg = _ckpt.config_digest()
    key = _key(data_fp, plan_fp, cfg)
    with _lock:
        _ensure_scanned()
        ent = _index.get(key)
        path = ent["path"] if ent else None
    if path is None or not os.path.exists(path):
        with _lock:
            _index.pop(key, None)
            _acct["misses"] += 1
        _tele.counter_inc("materialize_misses")
        return None
    from ..io import frame_from_ipc_bytes

    store_obj = _ckpt.CheckpointStore(path)
    t_load0 = time.perf_counter()
    try:
        with _tele.span(
            "materialize.load", kind="stage", program=plan_fp, data=data_fp
        ):
            manifest, payload = store_obj.load()
            _check_drift(manifest, data_fp, plan_fp, cfg, path)
            frame = frame_from_ipc_bytes(payload)
    except _ckpt.CheckpointError as e:
        if e.kind == "drift":
            with _lock:
                _acct["drift_refusals"] += 1
            raise
        # corrupt / truncated: drop it and recompute — a cache must
        # never turn bit rot into a user-visible failure
        with _lock:
            _index.pop(key, None)
            _acct["misses"] += 1
            _acct["corrupt_dropped"] += 1
        _tele.counter_inc("materialize_misses")
        try:
            os.unlink(path)
        except OSError:
            pass
        from ..utils.log import get_logger

        get_logger("materialize").warning(
            "dropped corrupt materialization cache entry %r (%s)", path, e
        )
        return None
    now = time.time()
    with _lock:
        ent = _index.get(key)
        if ent is not None:
            ent["last_used"] = now
        _acct["hits"] += 1
        _acct["last_hit"] = {
            "program": plan_fp, "data": data_fp, "bytes": len(payload),
            "load_seconds": time.perf_counter() - t_load0,
        }
    try:
        os.utime(path, (now, now))  # LRU clock survives a process restart
    except OSError:
        pass
    _tele.counter_inc("materialize_hits")
    return frame


def _check_drift(
    manifest: Dict, data_fp: str, plan_fp: str, cfg: str, path: str
) -> None:
    from . import checkpoint as _ckpt

    for field, want in (
        ("dataset_fingerprint", data_fp),
        ("program_fingerprint", plan_fp),
        ("config_digest", cfg),
    ):
        got = manifest.get(field)
        if got != want:
            raise _ckpt.CheckpointError(
                f"materialization cache entry {path!r} refused: drifted "
                f"field {field!r} (committed {got!r}, current {want!r})",
                field=field, path=path, kind="drift",
            )


# -- store -------------------------------------------------------------------


def _priced_out(recompute_s: Optional[float], store_s: float) -> bool:
    """The admission predicate: True when the modeled/measured
    recompute is no more expensive than the store plus its symmetric
    load estimate — such an entry would cost more to serve than to
    recompute, so it is not worth a slot. Unpriceable results (None)
    are never priced out."""
    return recompute_s is not None and recompute_s <= 2.0 * store_s


def store(
    data_fp: str,
    plan_fp: str,
    frame,
    ledger_fp: Optional[str] = None,
    compute_s: Optional[float] = None,
) -> bool:
    """Offer a result frame to the cache. Returns True when admitted.

    Admission pricing: the entry is kept only when the modeled
    recompute cost (`costmodel.modeled_recompute_s(ledger_fp)`, falling
    back to the measured ``compute_s`` wall time) exceeds the measured
    store cost plus the symmetric load estimate. An unpriceable result
    (no ledger entry, no measurement) is admitted — a cache that only
    works when the profiler is warm would be useless on first contact.

    The serialize step is a real D2H sync for device-resident results
    and is accounted as one (``host_sync`` counter + ``d2h_bytes``
    histogram — the shared accounting path of the streaming spill)."""
    if not enabled() or data_fp is None:
        return False
    from ..io import frame_to_ipc_bytes
    from ..utils import telemetry as _tele
    from ..utils.profiling import count as _count
    from . import checkpoint as _ckpt

    synced = any(
        not isinstance(frame.column(c).values, np.ndarray)
        for c in frame.columns
    )
    if synced:
        with _tele.span(
            "materialize.store", kind="host_sync", program=plan_fp
        ):
            payload = frame_to_ipc_bytes(frame)
        _count("host_sync")
        if _tele.enabled():
            _tele.histogram_observe("d2h_bytes", float(len(payload)))
    else:
        payload = frame_to_ipc_bytes(frame)
    budget = _budget()
    if len(payload) > budget:
        with _lock:
            _acct["rejected"] += 1
        return False
    cfg = _ckpt.config_digest()
    key = _key(data_fp, plan_fp, cfg)
    with _lock:
        _ensure_scanned()
        if key in _index:
            return True  # racing identical store: first writer wins
        path = os.path.join(_dir(), key + _SUFFIX)
    manifest = {
        "dataset_fingerprint": data_fp,
        "program_fingerprint": plan_fp,
        "config_digest": cfg,
        "columns": list(frame.columns),
        "nrows": int(frame.nrows),
    }
    t0 = time.perf_counter()
    try:
        _ckpt.CheckpointStore(path).commit(manifest, payload)
    except _ckpt.CheckpointError:
        return False  # unwritable dir: the cache degrades to a no-op
    store_s = time.perf_counter() - t0
    # price the admission: recompute vs store + (symmetric) load
    recompute_s = None
    if ledger_fp is not None:
        from . import costmodel as _cm

        try:
            recompute_s = _cm.modeled_recompute_s(ledger_fp)
        except Exception:
            recompute_s = None
    if recompute_s is None:
        recompute_s = compute_s
    if _priced_out(recompute_s, store_s):
        try:
            os.unlink(path)
        except OSError:
            pass
        with _lock:
            _acct["rejected"] += 1
        return False
    now = time.time()
    evicted: List[str] = []
    with _lock:
        _index[key] = {
            "path": path, "bytes": len(payload), "last_used": now,
        }
        # LRU eviction: the byte budget is a hard bound
        while _total_bytes_locked() > budget and len(_index) > 1:
            victim = min(
                (k for k in _index if k != key),
                key=lambda k: _index[k]["last_used"],
            )
            evicted.append(_index.pop(victim)["path"])
            _acct["evictions"] += 1
        _acct["stores"] += 1
        _acct["last_store"] = {
            "program": plan_fp, "data": data_fp, "bytes": len(payload),
            "store_seconds": store_s,
            "recompute_seconds": recompute_s,
        }
    for p in evicted:
        try:
            os.unlink(p)
        except OSError:
            pass
    if evicted:
        _tele.counter_inc("materialize_evictions", float(len(evicted)))
    return True


# -- accounting --------------------------------------------------------------


def state() -> Dict:
    """Materialization-cache accounting for ``tfs.diagnostics()`` and
    tests: hit/miss/store/eviction totals, live entry count and bytes,
    the active directory and budget."""
    with _lock:
        out = dict(_acct)
        out["last_hit"] = dict(_acct["last_hit"]) if _acct["last_hit"] else None
        out["last_store"] = (
            dict(_acct["last_store"]) if _acct["last_store"] else None
        )
        out["entries"] = len(_index)
        out["bytes"] = _total_bytes_locked()
    out["budget_bytes"] = _budget()
    out["enabled"] = enabled()
    return out


def reset_state() -> None:
    """Test hook: forget the accounting and the index, and delete the
    process-private cache directory's entries (a user-configured
    ``materialize_cache_dir`` keeps its files — only the index is
    dropped, and a later use rescans it)."""
    with _lock:
        _acct.update(
            hits=0, misses=0, stores=0, evictions=0, rejected=0,
            corrupt_dropped=0, drift_refusals=0,
            last_hit=None, last_store=None,
        )
        _index.clear()
        _scanned_dir[0] = None
        d = _tmp_dir[0]
    if d is not None:
        try:
            for name in os.listdir(d):
                if name.endswith(_SUFFIX):
                    try:
                        os.unlink(os.path.join(d, name))
                    except OSError:
                        pass
        except OSError:
            pass


def _register_gauge() -> None:
    from ..utils import telemetry as _tele

    _tele.gauge_register("materialize_bytes", _gauge_bytes)


_register_gauge()
