"""Persistent workload profiles: measurements that outlive the process.

Every prior observability layer dies with the interpreter: the span
ring is a bounded in-memory deque, the cost ledger and metrics registry
are process globals, and `tfs.diagnostics()` renders a moment. But the
decisions the ROADMAP points at next — pricing alternative plans with
the cost ledger, autotuning the bucket ladder / decode workers / batch
window from observed distributions — need *evidence across runs*:
yesterday's production profile vs today's canary, a TPU capture vs the
CPU smoke, profile-before vs profile-after a knob change. Production
TF treated profiles as durable artifacts driving placement and tuning
(PAPERS.md, "TensorFlow: A system for large-scale machine learning");
this module is that substrate.

A `WorkloadProfile` is a compact, JSON-serializable rollup of the
process's live observability state (`snapshot()` reads; it never
mutates and never raises — sections that fail to collect are simply
absent):

- **verbs** — per-verb call/second/row totals plus the verb latency
  histogram (fixed buckets, so two profiles merge exactly);
- **programs** — the cost ledger per fingerprint: kinds, exec counts,
  the set of dispatched bucket rungs, and per-shape modeled
  flops/bytes. Programs+rungs are the profile's *structural identity*:
  two runs of the same workload must agree on them even when every
  timing differs;
- **bucketing** — pad-waste counters and per-verb ``bucket_fill``
  fill-fraction histograms (the ladder autotuner's objective);
- **serving** — per-endpoint request/batch/shed counts and the batch
  rows / coalesced-size / queue-latency histograms (the batch-window
  autotuner's objective);
- **ingest** — per-stage busy/starvation rollups (the decode-worker /
  prefetch-depth signal);
- **admission** — admitted/shed totals, peak in-flight, queued-wait
  seconds, per-verb deadline expiries;
- **residuals** — the cost-model accuracy join
  (`costmodel.residuals`): per-program achieved-vs-predicted ratios,
  the fitted effective throughput, and the roofline saturation rollup
  (``peak_ratio_max``) the admission autotuner reads;
- **autotune** — the closed-loop tuner's state (`runtime.autotune
  .state()`): currently tuned knobs, the pin set, per-endpoint batch
  windows, recent decisions.

Operations: ``save(path)`` / ``load(path)`` (versioned JSON),
``merge(other)`` (counter sums, exact histogram merges — mismatched
bucket boundaries refuse loudly rather than blending incomparable
ladders), ``diff(other)`` (STRUCTURAL drift — program/rung/verb/
endpoint/stage set changes — separated from TIMING deltas, so "same
workload, different speed" reads as zero structural drift with timing
deltas only). ``tools/profile_report.py`` renders and diffs saved
profiles offline; the telemetry HTTP server serves a live snapshot at
``/profile``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

__all__ = ["PROFILE_SCHEMA", "WorkloadProfile", "snapshot", "load"]

PROFILE_SCHEMA = 1

# histogram dict shape used throughout:
#   {"buckets": [...], "counts": [... len(buckets)+1 ...],
#    "sum": float, "count": int}


def _hist_from_snapshot(entry) -> Dict:
    buckets, counts, hsum, hcount = entry
    return {
        "buckets": [float(b) for b in buckets],
        "counts": [int(c) for c in counts],
        "sum": float(hsum),
        "count": int(hcount),
    }


def _merge_hist(a: Optional[Dict], b: Optional[Dict], what: str):
    if a is None:
        return None if b is None else dict(b)
    if b is None:
        return dict(a)
    if list(a["buckets"]) != list(b["buckets"]):
        raise ValueError(
            f"cannot merge profiles: histogram {what!r} bucket "
            f"boundaries differ ({a['buckets']} vs {b['buckets']}); "
            "profiles captured under different config.histogram_buckets "
            "are not mergeable"
        )
    return {
        "buckets": list(a["buckets"]),
        "counts": [x + y for x, y in zip(a["counts"], b["counts"])],
        "sum": a["sum"] + b["sum"],
        "count": a["count"] + b["count"],
    }


def _labels(label_items) -> Dict[str, str]:
    return dict(label_items)


class WorkloadProfile:
    """A saved/loadable workload measurement rollup (see module doc).

    Thin wrapper over a plain JSON-able dict (``.data``) so save→load
    round trips are exact by construction: everything `save` writes is
    everything the constructor holds."""

    def __init__(self, data: Dict):
        if not isinstance(data, dict):
            raise TypeError(f"WorkloadProfile wants a dict, got {type(data)}")
        schema = data.get("schema")
        if schema != PROFILE_SCHEMA:
            raise ValueError(
                f"unsupported profile schema {schema!r} (this build "
                f"reads schema {PROFILE_SCHEMA})"
            )
        self.data = data

    # -- accessors ------------------------------------------------------
    @property
    def meta(self) -> Dict:
        return self.data.get("meta", {})

    @property
    def verbs(self) -> Dict:
        return self.data.get("verbs", {})

    @property
    def programs(self) -> Dict:
        return self.data.get("programs", {})

    def to_dict(self) -> Dict:
        return self.data

    def __repr__(self) -> str:
        return (
            f"WorkloadProfile({len(self.verbs)} verb(s), "
            f"{len(self.programs)} program(s), "
            f"created={self.meta.get('created_unix')})"
        )

    # -- persistence ----------------------------------------------------
    def save(self, path: str) -> str:
        """Write the profile as versioned JSON. Returns ``path``."""
        with open(path, "w") as f:
            json.dump(self.data, f, indent=1, sort_keys=True)
        return path

    @classmethod
    def from_dict(cls, data: Dict) -> "WorkloadProfile":
        return cls(data)

    # -- merge ----------------------------------------------------------
    def merge(self, other: "WorkloadProfile") -> "WorkloadProfile":
        """Combine two profiles of the SAME workload family into one:
        counters sum, fixed-bucket histograms merge exactly, program
        shape entries merge by (kind, rows) with exec counts summed and
        modeled costs kept from whichever side captured them, rung sets
        union. Histograms with different bucket boundaries raise — a
        blended ladder would silently misreport every quantile."""
        a, b = self.data, other.data
        out: Dict = {"schema": PROFILE_SCHEMA}
        ma, mb = a.get("meta", {}), b.get("meta", {})

        def _same(key):
            va, vb = ma.get(key), mb.get(key)
            return va if va == vb else None

        created = [
            t for t in (ma.get("created_unix"), mb.get("created_unix"))
            if t is not None
        ]
        # provenance survives the merge: shared fields carry over,
        # differing ones read None (never silently pick a side), the
        # full per-side metas ride along in merged_from
        out["meta"] = {
            "created_unix": min(created) if created else None,
            "host": _same("host"),
            "pid": _same("pid"),
            "device_kind": _same("device_kind"),
            "device_count": _same("device_count"),
            "note": "merged",
            "merged_from": [ma, mb],
        }
        # verbs ---------------------------------------------------------
        verbs: Dict = {}
        for name in sorted(set(a.get("verbs", {})) | set(b.get("verbs", {}))):
            va = a.get("verbs", {}).get(name)
            vb = b.get("verbs", {}).get(name)
            if va is None or vb is None:
                verbs[name] = dict(va or vb)
                continue
            verbs[name] = {
                "calls": va["calls"] + vb["calls"],
                "seconds": va["seconds"] + vb["seconds"],
                "rows": va["rows"] + vb["rows"],
                "latency": _merge_hist(
                    va.get("latency"), vb.get("latency"),
                    f"verb_seconds{{verb={name}}}",
                ),
            }
        out["verbs"] = verbs
        # programs ------------------------------------------------------
        progs: Dict = {}
        for fp in sorted(
            set(a.get("programs", {})) | set(b.get("programs", {}))
        ):
            pa = a.get("programs", {}).get(fp)
            pb = b.get("programs", {}).get(fp)
            if pa is None or pb is None:
                progs[fp] = json.loads(json.dumps(pa or pb))
                continue
            by_shape: Dict[Tuple, Dict] = {}
            for src in (pa, pb):
                for sh in src.get("shapes", []):
                    key = (sh.get("kind"), sh.get("rows"))
                    cur = by_shape.get(key)
                    if cur is None:
                        by_shape[key] = dict(sh)
                    else:
                        cur["execs"] = cur.get("execs", 0) + sh.get(
                            "execs", 0
                        )
                        for k in (
                            "flops", "bytes_accessed", "arg_bytes",
                            "out_bytes", "temp_bytes",
                        ):
                            if cur.get(k) is None:
                                cur[k] = sh.get(k)
            progs[fp] = {
                "kinds": sorted(
                    set(pa.get("kinds", [])) | set(pb.get("kinds", []))
                ),
                "execs": pa.get("execs", 0) + pb.get("execs", 0),
                "rungs": sorted(
                    set(pa.get("rungs", [])) | set(pb.get("rungs", []))
                ),
                "shapes": [
                    by_shape[k] for k in sorted(
                        by_shape, key=lambda k: (str(k[0]), k[1] or 0)
                    )
                ],
            }
        out["programs"] = progs
        # bucketing -----------------------------------------------------
        ba, bb = a.get("bucketing", {}), b.get("bucketing", {})
        fill: Dict = {}
        for verb in sorted(
            set(ba.get("fill", {})) | set(bb.get("fill", {}))
        ):
            fill[verb] = _merge_hist(
                ba.get("fill", {}).get(verb),
                bb.get("fill", {}).get(verb),
                f"bucket_fill{{verb={verb}}}",
            )
        out["bucketing"] = {
            "padded_dispatches": ba.get("padded_dispatches", 0)
            + bb.get("padded_dispatches", 0),
            "pad_rows": ba.get("pad_rows", 0) + bb.get("pad_rows", 0),
            "fill": fill,
        }
        # serving -------------------------------------------------------
        sa, sb = a.get("serving", {}), b.get("serving", {})
        eps: Dict = {}
        for name in sorted(
            set(sa.get("endpoints", {})) | set(sb.get("endpoints", {}))
        ):
            ea = sa.get("endpoints", {}).get(name, {})
            eb = sb.get("endpoints", {}).get(name, {})
            eps[name] = {
                k: ea.get(k, 0) + eb.get(k, 0)
                for k in ("requests", "batches", "shed")
            }
        out["serving"] = {
            "endpoints": eps,
            **{
                k: _merge_hist(sa.get(k), sb.get(k), k)
                for k in ("batch_rows", "batch_requests", "queue_seconds")
            },
        }
        # ingest --------------------------------------------------------
        ia, ib = a.get("ingest", {}), b.get("ingest", {})
        out["ingest"] = {
            stage: {
                k: ia.get(stage, {}).get(k, 0.0)
                + ib.get(stage, {}).get(k, 0.0)
                for k in ("chunks", "busy_s", "wait_s")
            }
            for stage in sorted(set(ia) | set(ib))
        }
        # admission -----------------------------------------------------
        aa, ab = a.get("admission", {}), b.get("admission", {})
        out["admission"] = {
            "admitted": aa.get("admitted", 0) + ab.get("admitted", 0),
            "shed": aa.get("shed", 0) + ab.get("shed", 0),
            "peak_in_flight": max(
                aa.get("peak_in_flight", 0), ab.get("peak_in_flight", 0)
            ),
            "wait_seconds": aa.get("wait_seconds", 0.0)
            + ab.get("wait_seconds", 0.0),
            "deadline_exceeded": {
                v: aa.get("deadline_exceeded", {}).get(v, 0)
                + ab.get("deadline_exceeded", {}).get(v, 0)
                for v in sorted(
                    set(aa.get("deadline_exceeded", {}))
                    | set(ab.get("deadline_exceeded", {}))
                )
            },
        }
        # residuals are per-run joins; a merged profile keeps both for
        # the reader instead of inventing a combined fit
        out["residuals"] = {
            "merged_from": [a.get("residuals"), b.get("residuals")]
        }
        return WorkloadProfile(out)

    # -- diff -----------------------------------------------------------
    def diff(self, other: "WorkloadProfile") -> Dict:
        """Compare two profiles of nominally the same workload.

        Returns ``{"structural": [...], "timing": [...],
        "structural_drift": bool}``. *Structural* entries are identity
        changes — programs present in only one run, bucket-rung sets
        that differ for a shared program, verb/endpoint/ingest-stage
        sets that differ — the things that mean "this is not the same
        workload (or the same plan) anymore". *Timing* entries are
        magnitude deltas (seconds, counts) between runs of the same
        structure: the normal run-to-run variation an autotuner
        consumes. Two runs of one workload should diff to zero
        structural drift with timing deltas only."""
        a, b = self.data, other.data
        structural: List[str] = []
        timing: List[Dict] = []

        def _sets(what: str, sa, sb):
            only_a = sorted(set(sa) - set(sb))
            only_b = sorted(set(sb) - set(sa))
            for k in only_a:
                structural.append(f"{what} {k!r} only in A")
            for k in only_b:
                structural.append(f"{what} {k!r} only in B")

        pa, pb = a.get("programs", {}), b.get("programs", {})
        _sets("program", pa, pb)
        for fp in sorted(set(pa) & set(pb)):
            ra = pa[fp].get("rungs", [])
            rb = pb[fp].get("rungs", [])
            if sorted(ra) != sorted(rb):
                structural.append(
                    f"program {fp!r} rungs differ: A={sorted(ra)} "
                    f"B={sorted(rb)}"
                )
            ea, eb = pa[fp].get("execs", 0), pb[fp].get("execs", 0)
            if ea != eb:
                timing.append(
                    {
                        "what": f"program {fp} execs",
                        "a": ea, "b": eb, "delta": eb - ea,
                        "ratio": (eb / ea) if ea else None,
                    }
                )
        va, vb = a.get("verbs", {}), b.get("verbs", {})
        _sets("verb", va, vb)
        for name in sorted(set(va) & set(vb)):
            for field in ("seconds", "calls", "rows"):
                x, y = va[name].get(field, 0), vb[name].get(field, 0)
                if x != y:
                    timing.append(
                        {
                            "what": f"verb {name} {field}",
                            "a": x, "b": y, "delta": y - x,
                            "ratio": (y / x) if x else None,
                        }
                    )
        _sets(
            "serving endpoint",
            a.get("serving", {}).get("endpoints", {}),
            b.get("serving", {}).get("endpoints", {}),
        )
        _sets("ingest stage", a.get("ingest", {}), b.get("ingest", {}))
        aa, ab = a.get("admission", {}), b.get("admission", {})
        for field in ("admitted", "shed"):
            x, y = aa.get(field, 0), ab.get(field, 0)
            if x != y:
                timing.append(
                    {
                        "what": f"admission {field}",
                        "a": x, "b": y, "delta": y - x,
                        "ratio": (y / x) if x else None,
                    }
                )
        return {
            "structural": structural,
            "timing": timing,
            "structural_drift": bool(structural),
        }


# ---------------------------------------------------------------------------
# live capture
# ---------------------------------------------------------------------------


def _capture_verbs(counters, hists) -> Dict:
    verbs: Dict = {}
    for (name, labels), v in counters.items():
        if labels or not name.endswith(".calls"):
            continue
        verb = name[: -len(".calls")]
        if not verb or verb.startswith("telemetry."):
            continue
        verbs[verb] = {
            "calls": int(v),
            "seconds": float(
                counters.get((f"{verb}.seconds", ()), 0.0)
            ),
            "rows": float(counters.get((f"{verb}.rows", ()), 0.0)),
            "latency": None,
        }
    for (name, labels), entry in hists.items():
        if name != "verb_seconds":
            continue
        verb = _labels(labels).get("verb")
        if verb in verbs:
            verbs[verb]["latency"] = _hist_from_snapshot(entry)
    return verbs


def _capture_programs() -> Dict:
    from . import costmodel as _cm

    out: Dict = {}
    costs = _cm.program_costs()
    shapes = _cm.program_shapes()
    for fp, c in costs.items():
        ents = shapes.get(fp, [])
        out[fp] = {
            "kinds": list(c["kinds"]),
            "execs": int(c["execs"]),
            # the structural identity: which bucket rungs (captured
            # lead row counts) this program dispatched at
            "rungs": sorted(
                {
                    int(e["rows"]) for e in ents if e["rows"] is not None
                }
            ),
            "shapes": sorted(
                (
                    {
                        "kind": e["kind"],
                        "rows": e["rows"],
                        "execs": e["execs"],
                        "flops": e["flops"],
                        "bytes_accessed": e["bytes_accessed"],
                        "arg_bytes": e["arg_bytes"],
                        "out_bytes": e["out_bytes"],
                        "temp_bytes": e["temp_bytes"],
                    }
                    for e in ents
                ),
                key=lambda e: (str(e["kind"]), e["rows"] or 0),
            ),
        }
    return out


def _capture_bucketing(counters, hists) -> Dict:
    fill: Dict = {}
    for (name, labels), entry in hists.items():
        if name != "bucket_fill":
            continue
        verb = _labels(labels).get("verb", "unattributed")
        fill[verb] = _hist_from_snapshot(entry)
    return {
        "padded_dispatches": int(
            counters.get(("shape_bucketing.padded_dispatch", ()), 0)
        ),
        "pad_rows": int(counters.get(("shape_bucketing.pad_rows", ()), 0)),
        "fill": fill,
    }


def _capture_serving(counters, hists) -> Dict:
    eps: Dict = {}
    keymap = {
        "serve_requests": "requests",
        "serve_batches": "batches",
        "serve_shed": "shed",
    }
    for (name, labels), v in counters.items():
        field = keymap.get(name)
        if field is None:
            continue
        ep = _labels(labels).get("endpoint", "?")
        eps.setdefault(
            ep, {"requests": 0, "batches": 0, "shed": 0}
        )[field] = int(v)
    histmap = {
        "serve_batch_rows": "batch_rows",
        # serve_batch_fill counts coalesced REQUESTS per batch (see
        # serving/batcher.py) — named honestly here
        "serve_batch_fill": "batch_requests",
        "serve_queue_seconds": "queue_seconds",
    }
    out: Dict = {"endpoints": eps, "batch_rows": None,
                 "batch_requests": None, "queue_seconds": None}
    for (name, labels), entry in hists.items():
        field = histmap.get(name)
        if field is not None and not labels:
            out[field] = _hist_from_snapshot(entry)
    return out


def _capture_ingest(counters) -> Dict:
    stages: Dict = {}
    keymap = {
        "ingest_chunks": "chunks",
        "ingest_stage_busy_seconds": "busy_s",
        "ingest_stage_wait_seconds": "wait_s",
    }
    for (name, labels), v in counters.items():
        field = keymap.get(name)
        if field is None:
            continue
        stage = _labels(labels).get("stage", "?")
        stages.setdefault(
            stage, {"chunks": 0.0, "busy_s": 0.0, "wait_s": 0.0}
        )[field] = float(v)
    return stages


def _capture_admission(counters) -> Dict:
    from .deadline import controller

    snap = controller().snapshot()
    deadline_by_verb = {}
    for (name, labels), v in counters.items():
        if name == "deadline_exceeded":
            verb = _labels(labels).get("verb", "?")
            deadline_by_verb[verb] = int(v)
    return {
        "admitted": int(snap.get("admitted", 0)),
        "shed": int(snap.get("shed", 0)),
        "peak_in_flight": int(snap.get("peak_in_flight", 0)),
        "wait_seconds": float(
            counters.get(("admission_wait_seconds", ()), 0.0)
        ),
        "deadline_exceeded": deadline_by_verb,
    }


def _capture_meta(note: Optional[str]) -> Dict:
    import os
    import platform
    import time

    meta: Dict = {
        "created_unix": time.time(),
        "host": platform.node(),
        "pid": os.getpid(),
        "schema": PROFILE_SCHEMA,
    }
    if note:
        meta["note"] = str(note)
    try:
        import jax

        devs = jax.local_devices()
        meta["device_count"] = len(devs)
        meta["device_kind"] = getattr(devs[0], "device_kind", None) or (
            getattr(devs[0], "platform", None)
        )
    except Exception:
        pass  # no live backend: the profile meta omits device fields
    return meta


def snapshot(note: Optional[str] = None) -> WorkloadProfile:
    """Capture the process's live observability state as a
    `WorkloadProfile`. Read-only and exception-guarded per section —
    a snapshot must never perturb or break the workload it measures;
    a section that fails to collect is recorded as its empty shape."""
    from ..utils import telemetry as _tele

    try:
        counters = _tele.labeled_counters()
    except Exception:
        counters = {}
    try:
        hists = _tele.metrics_snapshot()[2]
    except Exception:
        hists = {}
    data: Dict = {"schema": PROFILE_SCHEMA, "meta": _capture_meta(note)}
    for key, fn in (
        ("verbs", lambda: _capture_verbs(counters, hists)),
        ("programs", _capture_programs),
        ("bucketing", lambda: _capture_bucketing(counters, hists)),
        ("serving", lambda: _capture_serving(counters, hists)),
        ("ingest", lambda: _capture_ingest(counters)),
        ("admission", lambda: _capture_admission(counters)),
    ):
        try:
            data[key] = fn()
        except Exception as e:
            data[key] = {"error": f"{type(e).__name__}: {e}"}
    try:
        from . import costmodel as _cm

        res = _cm.residuals()
        ratios = [
            g["peak_ratio"] for g in res.get("groups", [])
            if g.get("peak_ratio") is not None
        ]
        data["residuals"] = {
            "warn_ratio": res["warn_ratio"],
            "fit": res["fit"],
            "programs": res["programs"],
            # roofline saturation rollup (the admission autotuner's
            # signal): the highest achieved-vs-datasheet-peak ratio any
            # (program x rung) group reached; honest None where no
            # datasheet peak exists (CPU)
            "peak_ratio_max": max(ratios) if ratios else None,
        }
    except Exception as e:
        data["residuals"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        from . import autotune as _at

        data["autotune"] = _at.state()
    except Exception as e:
        data["autotune"] = {"error": f"{type(e).__name__}: {e}"}
    return WorkloadProfile(data)


def load(path: str) -> WorkloadProfile:
    """Read a profile written by `WorkloadProfile.save` (schema
    checked — a profile from an incompatible build refuses loudly)."""
    with open(path) as f:
        return WorkloadProfile(json.load(f))
