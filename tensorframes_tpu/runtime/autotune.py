"""Closed-loop autotuner: the telemetry drives the knobs.

Every performance knob the perf PRs added — the shape-bucket ladder
(PR 3), ingest decode workers / prefetch depth (PR 7), the serving
micro-batch window (PR 10), admission limits (PR 9) — shipped hand-set,
while PR 8/11 built the measurements that should set them: bucket-fill
histograms, per-stage busy/starvation counters, latency-vs-fill
serving histograms, roofline residuals, all rolled up into the
persistent `WorkloadProfile`. This module closes the loop, the dynamic
re-tuning-from-observed-costs idea of "TensorFlow: A system for
large-scale machine learning" applied to the pipelined-execution knobs
of "Extending TensorFlow's Semantics with Pipelined Execution"
(PAPERS.md): a workload should converge onto its own best settings
without a human re-tuning per deployment.

Design rules (each is load-bearing):

- **Policies are pure functions** ``observations -> recommendation``:
  every policy takes a profile snapshot (the `WorkloadProfile` data
  dict) plus the current knob values and returns `Recommendation`s —
  deterministic given its inputs, unit-testable offline, identical
  across processes for the same saved profile.
- **Pins win, always.** Tuned values flow through
  `config.set_tuned()`, which refuses any knob the operator set
  explicitly (`update()` / `override()` / a well-formed ``TFS_*`` env
  var). The tuner can be wrong; the operator cannot be overridden.
- **Hysteresis + bounded steps.** Every policy has a dead band between
  its low and high watermarks (a borderline signal recommends
  nothing), moves at most one bounded step per cycle, and the applied
  value is clamped to a per-knob safety range — the loop converges
  into a dead band instead of oscillating across it. The background
  loop additionally tunes on PER-CYCLE deltas of the cumulative
  telemetry (`profile_delta`), so a bad ancient sample can never drag
  the knob forever.
- **Every decision is observable**: a ``tuning``-kind span plus an
  ``autotune_adjustments{knob=}`` counter per applied change, a
  bounded decision ring surfaced in ``tfs.diagnostics()`` and in the
  ``/profile`` snapshot (`state()`).

Entry points: ``tfs.autotune(profile=...)`` — one-shot tuning from a
live snapshot or a saved `WorkloadProfile` (path or object);
``config.autotune`` / ``TFS_AUTOTUNE`` — the in-process background
loop (off by default: no thread starts, no knob is ever mutated).
``benchmarks/autotune_bench.py`` proves each policy beats the static
default on an adversarial workload.

The four policies:

========================  =============================================
knob(s)                   signal -> move
========================  =============================================
shape_bucket_growth/min   mean ``bucket_fill`` below FILL_LOW with few
                          observed rungs -> shrink growth (pad waste is
                          the bottleneck); many observed rungs with
                          full buckets -> widen growth (compiles are);
                          smallest observed rung far above the ladder
                          min -> raise the min (shorter warm ladders)
ingest_decode_workers /   compute stage starved + decoders busy ->
stream_prefetch_depth     more workers (and depth >= workers); starved
                          but decoders idling -> bursty, deepen the
                          delivery queue; decoders idle and compute
                          saturated -> fewer workers
serve_batch_window_ms     per endpoint: shed or queue p99 near the
(per endpoint)            request budget -> shrink the window;
                          coalescing working with p99 headroom ->
                          widen it
max_concurrent_verbs      roofline-saturated devices -> cap at the
                          observed peak in flight; shedding without
                          saturation -> raise the limit
========================  =============================================
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = [
    "Recommendation",
    "ladder_policy",
    "ingest_policy",
    "serving_policy",
    "admission_policy",
    "recommend",
    "apply",
    "autotune",
    "profile_delta",
    "AutoTuner",
    "maybe_start",
    "stop",
    "reset",
    "state",
    "decisions",
    "SAFETY_BOUNDS",
]


# ---------------------------------------------------------------------------
# tuning constants (watermarks, steps, safety bounds)
# ---------------------------------------------------------------------------

# bucket-ladder policy: act only below FILL_LOW / above FILL_HIGH mean
# fill — the band between is the dead band a borderline workload rests
# in. MIN_FILL_SAMPLES bucketed dispatches of evidence before moving.
FILL_LOW = 0.80
FILL_HIGH = 0.92
MIN_FILL_SAMPLES = 16
# raise shape_bucket_min only when the smallest rung any program
# actually dispatched sits at least this factor above it (a full
# hysteresis band), and by at most x8 per cycle
MIN_RAISE_FACTOR = 4
MIN_RAISE_STEP = 8

# ingest policy watermarks: compute-stage starved fraction and decode
# busy fraction, with a dead band between each pair
STARVED_HIGH = 0.25
STARVED_LOW = 0.05
DECODE_BUSY_HIGH = 0.50
DECODE_BUSY_LOW = 0.15
MIN_INGEST_CHUNKS = 8

# serving policy: shrink under pressure (shed, or queue p99 beyond
# PRESSURE_FRAC of the request budget); widen only with real
# coalescing (>= WIDEN_COALESCE requests/batch) AND p99 headroom
PRESSURE_FRAC = 0.25
HEADROOM_FRAC = 0.05
WIDEN_COALESCE = 1.5
MIN_SERVE_REQUESTS = 16

# admission policy: saturation watermarks on the roofline peak ratio
# (None on peak-less backends -> only the shed-without-saturation rule
# can fire), with MIN_ADMITTED verbs of evidence
SAT_HIGH = 0.50
SAT_LOW = 0.25
MIN_ADMITTED = 32

# hard safety ranges every applied value is clamped into — the tuner
# may only move knobs inside these, whatever a policy proposes
SAFETY_BOUNDS: Dict[str, tuple] = {
    "shape_bucket_growth": (1.05, 4.0),
    "shape_bucket_min": (1, 4096),
    "ingest_decode_workers": (1, 32),
    "stream_prefetch_depth": (1, 8),
    "serve_batch_window_ms": (0.5, 100.0),
    "max_concurrent_verbs": (1, 256),
}


@dataclass(frozen=True)
class Recommendation:
    """One policy's proposed knob move: ``scope`` is ``"config"`` or
    ``"endpoint:<name>"`` (the per-endpoint serving window), ``reason``
    is the human-readable why, ``signals`` the measurements it read."""

    knob: str
    scope: str
    current: object
    proposed: object
    reason: str
    signals: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {
            "knob": self.knob,
            "scope": self.scope,
            "current": self.current,
            "proposed": self.proposed,
            "reason": self.reason,
            "signals": dict(self.signals),
        }


# ---------------------------------------------------------------------------
# profile readers
# ---------------------------------------------------------------------------


def _data(profile) -> Dict:
    """Accept a `WorkloadProfile`, its data dict, or a saved-profile
    path."""
    if isinstance(profile, str):
        from . import profiler as _prof

        return _prof.load(profile).data
    d = getattr(profile, "data", profile)
    if not isinstance(d, dict):
        raise TypeError(
            f"autotune wants a WorkloadProfile / data dict / path, got "
            f"{type(profile)}"
        )
    return d


def _hist_mean(hist: Optional[Dict]):
    """(mean, count) of a profile histogram dict; (None, 0) if empty."""
    if not hist or not hist.get("count"):
        return None, 0
    return hist["sum"] / hist["count"], int(hist["count"])


def _hist_quantile(hist: Optional[Dict], q: float) -> Optional[float]:
    """Upper BOUND of the bucket holding quantile ``q`` — a
    conservative (pessimistic) quantile read off fixed buckets. An
    observation in the +Inf bucket reports the top finite bound (an
    honest floor)."""
    if not hist or not hist.get("count"):
        return None
    n = int(hist["count"])
    target = q * n
    cum = 0
    buckets = hist["buckets"]
    for b, c in zip(buckets, hist["counts"][: len(buckets)]):
        cum += c
        if cum >= target:
            return float(b)
    return float(buckets[-1]) if buckets else None


def _clamp(knob: str, value):
    lo, hi = SAFETY_BOUNDS[knob]
    v = min(max(value, lo), hi)
    if isinstance(lo, int):
        v = int(round(v))
    return v


# ---------------------------------------------------------------------------
# the four policies (pure: profile data + current knobs in,
# recommendations out)
# ---------------------------------------------------------------------------


def ladder_policy(
    profile,
    growth: float,
    min_bucket: int,
    recompile_warn_shapes: int = 16,
) -> List[Recommendation]:
    """Tune the bucket ladder from observed bucket-fill economics and
    the dispatched-rung sets (the recompile-storm table's profile
    form). Growth moves by halving/doubling its EXCESS over 1
    (``1 + (g-1)/2`` / ``1 + (g-1)*2``), so it can never cross 1 and
    every step is bounded; the fill dead band [FILL_LOW, FILL_HIGH]
    is where a tuned workload comes to rest."""
    d = _data(profile)
    fill = d.get("bucketing", {}).get("fill", {}) or {}
    tot_sum = tot_n = 0.0
    for verb, h in fill.items():
        # serving fill (verb="serve:<endpoint>") is a batching-WINDOW
        # question, not ladder geometry: the batcher pads to the rung
        # itself and absorbs the waste, so it must not drive a ladder
        # re-shape that would invalidate every warm-compiled endpoint
        if str(verb).startswith("serve:"):
            continue
        m, n = _hist_mean(h)
        if m is not None:
            tot_sum += h["sum"]
            tot_n += n
    mean_fill = (tot_sum / tot_n) if tot_n else None
    rung_sets = [
        p.get("rungs", []) for p in d.get("programs", {}).values()
    ]
    max_rungs = max((len(r) for r in rung_sets), default=0)
    smallest_rung = min(
        (min(r) for r in rung_sets if r), default=None
    )
    signals = {
        "mean_fill": mean_fill,
        "fill_samples": int(tot_n),
        "max_rungs_per_program": max_rungs,
        "smallest_rung": smallest_rung,
    }
    out: List[Recommendation] = []
    if mean_fill is not None and tot_n >= MIN_FILL_SAMPLES:
        if mean_fill < FILL_LOW and max_rungs <= recompile_warn_shapes:
            proposed = _clamp(
                "shape_bucket_growth", round(1.0 + (growth - 1.0) / 2.0, 4)
            )
            if proposed < growth:
                out.append(Recommendation(
                    "shape_bucket_growth", "config", growth, proposed,
                    f"mean bucket fill {mean_fill:.3f} < {FILL_LOW} over "
                    f"{int(tot_n)} dispatch(es): the ladder pads away "
                    f"{(1 - mean_fill) * 100:.0f}% of dispatched rows — "
                    "shrink the growth toward the observed clustering",
                    signals,
                ))
        elif mean_fill > FILL_HIGH and max_rungs > recompile_warn_shapes:
            proposed = _clamp(
                "shape_bucket_growth", round(1.0 + (growth - 1.0) * 2.0, 4)
            )
            if proposed > growth:
                out.append(Recommendation(
                    "shape_bucket_growth", "config", growth, proposed,
                    f"{max_rungs} dispatched rungs on one program with "
                    f"mean fill {mean_fill:.3f}: compiles, not pad "
                    "waste, are the bottleneck — coarsen the ladder",
                    signals,
                ))
    if (
        smallest_rung is not None
        and tot_n >= MIN_FILL_SAMPLES
        and smallest_rung >= MIN_RAISE_FACTOR * min_bucket
    ):
        proposed = _clamp(
            "shape_bucket_min",
            min(int(smallest_rung), min_bucket * MIN_RAISE_STEP),
        )
        if proposed > min_bucket:
            out.append(Recommendation(
                "shape_bucket_min", "config", min_bucket, proposed,
                f"no program dispatched below rung {smallest_rung} "
                f"(ladder min {min_bucket}): raising the min shortens "
                "every warm-compile ladder without touching a rung "
                "traffic uses",
                signals,
            ))
    return out


def ingest_policy(
    profile,
    decode_workers: int,
    prefetch_depth: int,
    max_workers: Optional[int] = None,
) -> List[Recommendation]:
    """Tune decode workers / prefetch depth from the per-stage
    busy/starvation counters: the compute stage's wait fraction IS
    device starvation (`ingest/pipeline.py`), the decode stage's busy
    fraction says whether decoding is the reason."""
    if max_workers is None:
        max_workers = max(4, 2 * (os.cpu_count() or 1))
    stages = {
        k: v for k, v in (_data(profile).get("ingest", {}) or {}).items()
        if isinstance(v, dict)
    }
    comp = stages.get("compute", {})
    dec = stages.get("decode", {})
    chunks = min(comp.get("chunks", 0.0), dec.get("chunks", 0.0))
    if chunks < MIN_INGEST_CHUNKS:
        return []

    def _frac(st, key):
        busy, wait = st.get("busy_s", 0.0), st.get("wait_s", 0.0)
        tot = busy + wait
        return (st.get(key, 0.0) / tot) if tot > 0 else 0.0

    starved = _frac(comp, "wait_s")
    decode_busy = _frac(dec, "busy_s")
    signals = {
        "compute_starved_frac": round(starved, 4),
        "decode_busy_frac": round(decode_busy, 4),
        "chunks": chunks,
    }
    out: List[Recommendation] = []
    if starved > STARVED_HIGH:
        if decode_busy > DECODE_BUSY_HIGH:
            # compute starves while decoders run flat out: decoding is
            # the bottleneck — widen the pool (and keep the delivery
            # queue at least as deep, so the extra workers have
            # somewhere to put finished chunks)
            w = _clamp(
                "ingest_decode_workers",
                min(decode_workers + 1, max_workers),
            )
            if w > decode_workers:
                out.append(Recommendation(
                    "ingest_decode_workers", "config", decode_workers, w,
                    f"compute starved {starved * 100:.0f}% of its time "
                    f"while decoders were {decode_busy * 100:.0f}% busy "
                    "— the stream is decode-bound, add a worker",
                    signals,
                ))
            dp = _clamp("stream_prefetch_depth", w)
            if dp > prefetch_depth:
                out.append(Recommendation(
                    "stream_prefetch_depth", "config", prefetch_depth,
                    dp,
                    "keep the delivery queue at least as deep as the "
                    "decode pool",
                    signals,
                ))
        else:
            # starved although decoders idle on average: bursty decode
            # — a deeper delivery queue rides the bursts out
            dp = _clamp("stream_prefetch_depth", prefetch_depth + 1)
            if dp > prefetch_depth:
                out.append(Recommendation(
                    "stream_prefetch_depth", "config", prefetch_depth, dp,
                    f"compute starved {starved * 100:.0f}% of its time "
                    f"with decoders only {decode_busy * 100:.0f}% busy "
                    "— bursty decode, deepen the prefetch buffer",
                    signals,
                ))
    elif (
        starved < STARVED_LOW
        and decode_busy < DECODE_BUSY_LOW
        and decode_workers > 1
    ):
        out.append(Recommendation(
            "ingest_decode_workers", "config", decode_workers,
            _clamp("ingest_decode_workers", decode_workers - 1),
            f"decoders {decode_busy * 100:.0f}% busy and compute never "
            "starved: the pool is oversized, shed a worker",
            signals,
        ))
    return out


def serving_policy(
    profile,
    window_ms: float,
    default_timeout_s: float,
    endpoint_windows: Optional[Dict[str, float]] = None,
) -> List[Recommendation]:
    """Per-endpoint batch-window tuning from the latency-vs-fill
    serving histograms: widen while p99 queue headroom exists AND
    coalescing is actually happening; shrink the moment the lane sheds
    or queue p99 eats into the request budget.

    Attribution caveat: the queue-latency / requests-per-batch
    histograms are PROCESS-GLOBAL (the per-endpoint dimensions are the
    request/batch/shed counters), so the p99-pressure shrink is only
    trusted when exactly one endpoint is batching — with several, one
    hot endpoint's p99 must not shrink its healthy neighbors, and only
    each endpoint's OWN shed counter counts as pressure. The global
    p99 still gates widening for everyone: refusing to widen during
    someone else's overload is the safe direction."""
    d = _data(profile)
    srv = d.get("serving", {}) or {}
    eps = srv.get("endpoints", {}) or {}
    coalesce, _ = _hist_mean(srv.get("batch_requests"))
    p99_queue = _hist_quantile(srv.get("queue_seconds"), 0.99)
    batching_eps = [n for n, e in eps.items() if e.get("batches", 0)]
    out: List[Recommendation] = []
    for name in sorted(eps):
        ep = eps[name]
        if ep.get("requests", 0) < MIN_SERVE_REQUESTS:
            continue
        if not ep.get("batches", 0):
            continue  # unbatched endpoint: no window to tune
        cur = float(
            (endpoint_windows or {}).get(name, window_ms)
        )
        signals = {
            "requests": ep.get("requests", 0),
            "batches": ep.get("batches", 0),
            "shed": ep.get("shed", 0),
            "coalesce_mean": coalesce,
            "p99_queue_s": p99_queue,
            "budget_s": default_timeout_s,
        }
        pressure = bool(ep.get("shed", 0)) or (
            len(batching_eps) == 1
            and p99_queue is not None
            and p99_queue > PRESSURE_FRAC * default_timeout_s
        )
        headroom = (
            p99_queue is None
            or p99_queue <= HEADROOM_FRAC * default_timeout_s
        )
        if pressure:
            proposed = _clamp("serve_batch_window_ms", round(cur / 2.0, 3))
            if proposed < cur:
                out.append(Recommendation(
                    "serve_batch_window_ms", f"endpoint:{name}", cur,
                    proposed,
                    f"endpoint {name!r} under deadline pressure "
                    f"(shed={ep.get('shed', 0)}, queue p99="
                    f"{p99_queue}): shrink the coalescing window",
                    signals,
                ))
        elif (
            headroom
            and coalesce is not None
            and coalesce >= WIDEN_COALESCE
        ):
            proposed = _clamp("serve_batch_window_ms", round(cur * 1.5, 3))
            if proposed > cur:
                out.append(Recommendation(
                    "serve_batch_window_ms", f"endpoint:{name}", cur,
                    proposed,
                    f"endpoint {name!r} coalesces {coalesce:.1f} "
                    "request(s)/batch with p99 queue headroom: widen "
                    "the window for fuller batches",
                    signals,
                ))
    return out


def admission_policy(profile, limit: int) -> List[Recommendation]:
    """Tune ``max_concurrent_verbs`` from roofline-measured saturation
    (the residual join's ``peak_ratio_max`` — None on backends without
    datasheet peaks, where only the shed-without-saturation raise can
    fire) plus the admission ledger."""
    d = _data(profile)
    adm = d.get("admission", {}) or {}
    admitted = int(adm.get("admitted", 0))
    if admitted < MIN_ADMITTED:
        return []
    shed = int(adm.get("shed", 0))
    peak = int(adm.get("peak_in_flight", 0))
    res = d.get("residuals", {}) or {}
    sat = res.get("peak_ratio_max")
    signals = {
        "admitted": admitted, "shed": shed, "peak_in_flight": peak,
        "peak_ratio_max": sat,
    }
    if sat is not None and sat >= SAT_HIGH and peak > 0:
        target = max(1, peak)
        if limit <= 0 or limit > target:
            # step bound: halve an existing limit at most; an unlimited
            # gate jumps straight to the observed peak (that IS the
            # bounded move — it admits everything that ever ran at once)
            proposed = target if limit <= 0 else max(target, limit // 2)
            proposed = _clamp("max_concurrent_verbs", proposed)
            if proposed != limit:
                return [Recommendation(
                    "max_concurrent_verbs", "config", limit, proposed,
                    f"roofline saturation {sat:.2f} >= {SAT_HIGH}: "
                    f"admitting more than the observed peak in flight "
                    f"({peak}) only queues work on saturated devices",
                    signals,
                )]
    elif shed > 0 and limit > 0 and (sat is None or sat <= SAT_LOW):
        proposed = _clamp("max_concurrent_verbs", limit * 2)
        if proposed > limit:
            return [Recommendation(
                "max_concurrent_verbs", "config", limit, proposed,
                f"{shed} verb(s) shed with no measured saturation "
                f"(peak ratio {sat}): the limit is tighter than the "
                "hardware — raise it",
                signals,
            )]
    return []


# ---------------------------------------------------------------------------
# recommend: resolve current knobs, run every policy
# ---------------------------------------------------------------------------


def _effective_decode_workers(cfg_val: int) -> int:
    """Mirror `ingest.dataset._auto_decode_workers`: 0 = auto."""
    if cfg_val > 0:
        return cfg_val
    return max(1, min(4, os.cpu_count() or 1))


def recommend(profile=None, knobs: Optional[Dict] = None) -> List[Recommendation]:
    """Run every policy over ``profile`` (default: a live
    `runtime.profiler.snapshot()`) and return the recommendations —
    NOTHING is applied. ``knobs`` overrides the current knob values
    the policies compare against (default: the live config), which is
    how benches/tests evaluate a policy against hypothetical settings.
    Deterministic: the same profile + knobs always recommend the same
    moves."""
    if profile is None:
        from . import profiler as _prof

        profile = _prof.snapshot(note="autotune.recommend")
    from .. import config as _config

    cfg = _config.get()
    k = dict(knobs or {})

    def _knob(name, default):
        return k[name] if name in k else default

    recs: List[Recommendation] = []
    recs += ladder_policy(
        profile,
        growth=float(_knob("shape_bucket_growth", cfg.shape_bucket_growth)),
        min_bucket=int(_knob("shape_bucket_min", cfg.shape_bucket_min)),
        recompile_warn_shapes=int(
            _knob("recompile_warn_shapes", cfg.recompile_warn_shapes) or 16
        ),
    )
    recs += ingest_policy(
        profile,
        decode_workers=int(_knob(
            "ingest_decode_workers",
            _effective_decode_workers(cfg.ingest_decode_workers),
        )),
        prefetch_depth=int(
            _knob("stream_prefetch_depth", cfg.stream_prefetch_depth)
        ),
    )
    recs += serving_policy(
        profile,
        window_ms=float(
            _knob("serve_batch_window_ms", cfg.serve_batch_window_ms)
        ),
        default_timeout_s=float(
            _knob("serve_default_timeout_s", cfg.serve_default_timeout_s)
        ),
        endpoint_windows=k.get("endpoint_windows", _endpoint_windows()),
    )
    recs += admission_policy(
        profile,
        limit=int(_knob("max_concurrent_verbs", cfg.max_concurrent_verbs)),
    )
    return recs


def _endpoint_windows() -> Dict[str, float]:
    """Per-endpoint tuned windows currently in force (registered
    endpoints whose ``batch_window_ms`` the tuner set)."""
    try:
        from ..serving import registry as _reg

        out = {}
        for desc in _reg.endpoints():
            ep = _reg.get(desc["name"])
            if ep.batch_window_ms is not None:
                out[desc["name"]] = float(ep.batch_window_ms)
        return out
    except Exception:
        return {}


# ---------------------------------------------------------------------------
# apply: pins, clamps, spans, counters, the decision ring
# ---------------------------------------------------------------------------

# bounded ring of every decision (applied AND skipped) for
# diagnostics / the profile snapshot
_DECISIONS: "deque" = deque(maxlen=64)


def decisions() -> List[Dict]:
    return list(_DECISIONS)


def apply(recs: List[Recommendation]) -> List[Dict]:
    """Apply recommendations through the tuned-config layer: a knob the
    operator pinned is SKIPPED (``outcome="skipped:pinned"``), applied
    values are clamped into `SAFETY_BOUNDS`, and every decision —
    applied or not — records a ``tuning``-kind span and lands in the
    decision ring; applied ones also count
    ``autotune_adjustments{knob=}``."""
    from .. import config as _config
    from ..utils import telemetry as _tele

    out: List[Dict] = []
    for r in recs:
        d = r.to_dict()
        d["at_unix"] = time.time()
        if r.scope == "config":
            val = _clamp(r.knob, r.proposed)
            # set_tuned is the atomic pin-check-and-write: its verdict
            # (not a separate is_explicit read) decides the outcome, so
            # an update() racing this cycle can never be misreported as
            # applied — or overwritten
            if _config.set_tuned(r.knob, val):
                d["outcome"] = "applied"
                d["applied_value"] = val
            else:
                d["outcome"] = "skipped:pinned"
        elif r.scope.startswith("endpoint:"):
            name = r.scope.split(":", 1)[1]
            if _config.is_explicit("serve_batch_window_ms"):
                # the global window pin covers its per-endpoint splits
                d["outcome"] = "skipped:pinned"
            else:
                try:
                    from ..serving import registry as _reg

                    ep = _reg.get(name)
                except Exception:
                    ep = None
                if ep is None:
                    d["outcome"] = "skipped:unknown-endpoint"
                else:
                    val = _clamp("serve_batch_window_ms", r.proposed)
                    ep.batch_window_ms = float(val)
                    d["outcome"] = "applied"
                    d["applied_value"] = float(val)
        else:
            d["outcome"] = f"skipped:unknown-scope:{r.scope}"
        with _tele.span(
            f"autotune.{r.knob}",
            kind="tuning",
            knob=r.knob,
            scope=r.scope,
            outcome=d["outcome"],
            current=r.current,
            proposed=r.proposed,
            reason=r.reason,
        ):
            pass
        if d["outcome"] == "applied":
            _tele.counter_inc("autotune_adjustments", 1.0, knob=r.knob)
            from ..utils.log import get_logger

            get_logger("autotune").info(
                "tuned %s (%s): %s -> %s — %s",
                r.knob, r.scope, r.current, d["applied_value"], r.reason,
            )
        _DECISIONS.append(d)
        out.append(d)
    if any(
        d["outcome"] == "applied"
        and d["knob"] in ("shape_bucket_growth", "shape_bucket_min")
        for d in out
    ):
        # a ladder re-shape moves every rung: warm-compiled serving
        # endpoints would otherwise pay fresh XLA compiles on the
        # request path (the PR 10 zero-steady-state-compiles
        # invariant). Re-warm here — off the request path — instead.
        _rewarm_endpoints()
    return out


def _rewarm_endpoints() -> None:
    """Warm-compile the CURRENT ladder's rungs for every previously
    warmed serving endpoint (no-op when serving is idle/unused)."""
    try:
        from ..serving import registry as _reg

        for desc in _reg.endpoints():
            ep = _reg.get(desc["name"])
            if ep.warmed_rungs:
                ep.warm()
    except Exception:
        from ..utils.log import get_logger

        get_logger("autotune").warning(
            "endpoint re-warm after ladder change failed", exc_info=True
        )


def autotune(profile=None, apply_recommendations: bool = True,
             knobs: Optional[Dict] = None) -> Dict:
    """One-shot tuning pass, exposed as ``tfs.autotune()``: recommend
    from ``profile`` (a `WorkloadProfile`, a saved-profile path, or
    None for a live snapshot) and — unless
    ``apply_recommendations=False`` — apply through the pin-respecting
    tuned layer. Returns ``{"recommendations": [...], "applied":
    [...]}`` (``applied`` holds the decision records, including
    skips)."""
    recs = recommend(profile, knobs=knobs)
    return {
        "recommendations": [r.to_dict() for r in recs],
        "applied": apply(recs) if apply_recommendations else [],
    }


# ---------------------------------------------------------------------------
# per-cycle deltas for the background loop
# ---------------------------------------------------------------------------


def _hist_delta(cur: Optional[Dict], prev: Optional[Dict]):
    if not cur:
        return cur
    if not prev or list(prev.get("buckets", [])) != list(cur["buckets"]):
        return dict(cur)  # ladder changed (or first cycle): take current
    return {
        "buckets": list(cur["buckets"]),
        "counts": [
            max(0, a - b) for a, b in zip(cur["counts"], prev["counts"])
        ],
        "sum": max(0.0, cur["sum"] - prev["sum"]),
        "count": max(0, cur["count"] - prev["count"]),
    }


def profile_delta(cur, prev) -> Dict:
    """The PER-CYCLE view of two cumulative profile snapshots: counter
    sections subtract, histograms subtract bucket-wise, structural
    sections (programs/rungs, residuals) ride the current snapshot.
    This is what lets the background loop tune on what happened since
    its last look instead of on all of history — apply a fix and the
    next cycle's signal reflects the fix, not the past."""
    c, p = _data(cur), _data(prev) if prev is not None else {}
    if not p:
        return dict(c)
    out = dict(c)
    cb, pb = c.get("bucketing", {}) or {}, p.get("bucketing", {}) or {}
    out["bucketing"] = {
        "padded_dispatches": max(
            0, cb.get("padded_dispatches", 0)
            - pb.get("padded_dispatches", 0)
        ),
        "pad_rows": max(0, cb.get("pad_rows", 0) - pb.get("pad_rows", 0)),
        "fill": {
            verb: _hist_delta(h, pb.get("fill", {}).get(verb))
            for verb, h in (cb.get("fill", {}) or {}).items()
        },
    }
    ci, pi = c.get("ingest", {}) or {}, p.get("ingest", {}) or {}
    out["ingest"] = {
        stage: {
            k: max(0.0, st.get(k, 0.0) - pi.get(stage, {}).get(k, 0.0))
            for k in ("chunks", "busy_s", "wait_s")
        }
        for stage, st in ci.items()
        if isinstance(st, dict)
    }
    cs, ps = c.get("serving", {}) or {}, p.get("serving", {}) or {}
    out["serving"] = {
        "endpoints": {
            name: {
                k: max(
                    0, ep.get(k, 0)
                    - ps.get("endpoints", {}).get(name, {}).get(k, 0)
                )
                for k in ("requests", "batches", "shed")
            }
            for name, ep in (cs.get("endpoints", {}) or {}).items()
        },
        **{
            k: _hist_delta(cs.get(k), ps.get(k))
            for k in ("batch_rows", "batch_requests", "queue_seconds")
        },
    }
    ca, pa = c.get("admission", {}) or {}, p.get("admission", {}) or {}
    out["admission"] = {
        "admitted": max(0, ca.get("admitted", 0) - pa.get("admitted", 0)),
        "shed": max(0, ca.get("shed", 0) - pa.get("shed", 0)),
        # peak is a cumulative high-water mark; the current value is
        # the honest read either way
        "peak_in_flight": ca.get("peak_in_flight", 0),
        "wait_seconds": max(
            0.0, ca.get("wait_seconds", 0.0) - pa.get("wait_seconds", 0.0)
        ),
        "deadline_exceeded": ca.get("deadline_exceeded", {}),
    }
    return out


# ---------------------------------------------------------------------------
# the background loop
# ---------------------------------------------------------------------------


class AutoTuner:
    """The in-process feedback loop: every ``interval_s``, snapshot the
    live profile, diff against the previous cycle, recommend, apply.
    One per process (`maybe_start`); a daemon thread that never blocks
    interpreter exit."""

    def __init__(self, interval_s: Optional[float] = None):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev = None
        self.cycles = 0

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def cycle(self) -> List[Dict]:
        """One deterministic tuning step (what the loop runs; callable
        directly from tests/benches): snapshot -> delta vs the previous
        cycle -> recommend -> apply."""
        from . import profiler as _prof

        cur = _prof.snapshot(note="autotune.cycle")
        delta = profile_delta(cur, self._prev)
        self._prev = cur
        self.cycles += 1
        from .profiler import WorkloadProfile

        return apply(recommend(WorkloadProfile(delta)))

    def _interval(self) -> float:
        if self.interval_s is not None:
            return float(self.interval_s)
        from .. import config as _config

        return max(
            1.0, float(getattr(_config.get(), "autotune_interval_s", 30.0))
        )

    def _run(self) -> None:
        while not self._stop.wait(self._interval()):
            try:
                self.cycle()
            except Exception:  # the loop must never die of one bad cycle
                from ..utils.log import get_logger

                get_logger("autotune").warning(
                    "autotune cycle failed", exc_info=True
                )

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tfs-autotune"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None


_tuner: Optional[AutoTuner] = None
_tuner_lock = threading.Lock()


def maybe_start() -> Optional[AutoTuner]:
    """Start the background loop IFF ``config.autotune`` is on (the
    import-time hook, like `telemetry.maybe_serve`). With the knob off
    — the default — this is a strict no-op: no thread, no state."""
    from .. import config as _config

    if not getattr(_config.get(), "autotune", False):
        return None
    global _tuner
    with _tuner_lock:
        if _tuner is None:
            _tuner = AutoTuner()
        _tuner.start()
        return _tuner


def stop() -> None:
    """Stop the background loop (test/teardown hook); keeps tuned
    values in force — `config.reset_tuning()` reverts those. The join
    happens OUTSIDE the module lock: a cycle mid-`snapshot()` calls
    `state()`, which takes the same lock — joining under it would
    always time out and leak the thread past the stop."""
    global _tuner
    with _tuner_lock:
        tuner, _tuner = _tuner, None
    if tuner is not None:
        tuner.stop()


def reset() -> None:
    """Stop the loop, forget the decision ring, and clear every tuned
    per-endpoint batch window (the tuned CONFIG values are
    `config.reset_tuning()`'s job — the two compose in the conftest
    autouse fixture and form the operator's full undo)."""
    stop()
    _DECISIONS.clear()
    try:
        from ..serving import registry as _reg

        for desc in _reg.endpoints():
            _reg.get(desc["name"]).batch_window_ms = None
    except Exception:
        pass  # serving never imported: no endpoint windows to clear


def state() -> Dict:
    """The tuner's live state for ``tfs.diagnostics()`` and the
    ``/profile`` snapshot: enabled/running flags, every currently
    tuned knob, the pin set it must respect, per-endpoint tuned
    windows, and the recent decision ring."""
    from .. import config as _config

    cfg = _config.get()
    with _tuner_lock:
        running = _tuner.running if _tuner is not None else False
        cycles = _tuner.cycles if _tuner is not None else 0
    return {
        "enabled": bool(getattr(cfg, "autotune", False)),
        "running": running,
        "cycles": cycles,
        "interval_s": float(getattr(cfg, "autotune_interval_s", 30.0)),
        "tuned": _config.tuned(),
        "pinned": sorted(_config.explicit_keys()),
        "endpoint_windows": _endpoint_windows(),
        "decisions": decisions(),
    }
