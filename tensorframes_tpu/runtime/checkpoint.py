"""Durable streams: checkpoint/resume for out-of-core reductions.

The reference's substrate recovered lost work via Spark lineage, and
"TensorFlow: A system for large-scale machine learning" (PAPERS.md)
makes periodic checkpointing the backbone of long-job fault tolerance.
Our north-star workload — a 1B-row out-of-core reduce over
`stream_dataset` — previously lost every folded partial when the
process died: the fault layer (PR 6) retries *within* a run and the
deadline layer (PR 9) accounts unissued work, but a crash, SIGKILL or
preemption restarted the stream from chunk zero. This module
externalizes the stream's progress state so a fresh interpreter picks
up where the dead one committed:

- **`CheckpointStore`** — one checkpoint file, committed ATOMICALLY
  (temp file in the same directory + flush + fsync + ``os.replace``, so
  a crash mid-write leaves either the previous checkpoint or none —
  never a torn one). Layout: an 8-byte magic, a length-prefixed JSON
  manifest, then the length-prefixed payload; the manifest records the
  payload's length AND sha256, so truncation or corruption anywhere in
  the file is detected at load and refused with a typed
  `CheckpointError` — never half-loaded, never silently restarted.

- **Manifest** (versioned, ``schema_version``): dataset fingerprint
  (from `Dataset.tasks()` METADATA — shard paths/sizes, group indices,
  row counts), program fingerprint (`Graph.fingerprint()`), per-fetch
  monoid kind (`aggregate._chunk_combiners` — the eligibility gate),
  a digest of the numerics-relevant config knobs, the resolved fold
  cadence, and the contiguous-chunk WATERMARK: every source chunk with
  ordinal < watermark is folded into the committed partials. The
  watermark is well-defined because the ingest pipeline's reorder
  buffer delivers chunks in order (ingest/pipeline.py).

- **Payload** — the live partial table, one frame row per partial,
  serialized with `io.frame_to_ipc_bytes` (the same Arrow IPC framing
  the serving wire uses). Scalar and rank-1 (vector) reduce cells
  round-trip exactly; higher-rank cells are refused at commit.

- **`StreamCheckpointer`** — the per-call protocol object
  `reduce_blocks_stream(checkpoint=...)` drives: resume validation
  (every manifest field checked, a mismatch refuses LOUDLY naming the
  drifted field unless ``resume="ignore"``), the eligibility gate
  (non-classifiable reduces reject ``checkpoint=`` with a typed
  error), periodic commits every ``checkpoint_every`` folded chunks,
  and commit-on-clean-exit for `DeadlineExceeded` / `Cancelled`.

Exactness: resuming seeds the fold with the restored partials at the
restored watermark, so the partial list evolves exactly as in an
uninterrupted run — bit-identical results for exact monoids (min / max
/ prod / integer sum), within the already-documented reassociation
tolerance for float sum/mean. Payload size is O(fold_every) partials
for tree-foldable streams and O(#chunks) for the single-final-combine
class (mean, transform-then-reduce) — the same bound as the stream's
own host memory.

Telemetry (always-live counters; spans/histograms gated on
``config.telemetry``): ``checkpoint_commits`` / ``checkpoint_resumes``
/ ``checkpoint_chunks_skipped`` counters, the
``checkpoint_write_seconds`` histogram, ``checkpoint``-kind spans
around commit/resume, and a "durable streams" section in
`tfs.diagnostics()`.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "SCHEMA_VERSION",
    "CheckpointError",
    "CheckpointStore",
    "StreamCheckpointer",
    "config_digest",
    "state",
    "reset_state",
]

MAGIC = b"TFSCKPT1"
SCHEMA_VERSION = 1
_LEN = struct.Struct(">Q")

# Config knobs folded into the manifest digest: the ones that change
# the NUMERICS of a reduce (masked-bucketed programs reassociate float
# accumulation; precision changes matmul-backed transforms; the
# scheduler's per-device folds reorder float combines). A resumed
# stream under a drifted digest could silently produce a result neither
# run would have produced alone, so drift refuses loudly instead.
_DIGEST_KNOBS = (
    "matmul_precision",
    "shape_bucketing",
    "shape_bucket_growth",
    "shape_bucket_min",
    "block_scheduler",
    "check_numerics",
)


class CheckpointError(RuntimeError):
    """A checkpoint could not be written, read, or trusted.

    ``kind`` is one of ``"corrupt"`` (truncated / garbled file),
    ``"drift"`` (a manifest field no longer matches the running call —
    ``field`` names which one), ``"ineligible"`` (the reduce is not a
    classifiable monoid, so its partials cannot be durably resumed), or
    ``"invalid"`` (bad arguments / unserializable partials). A drifted
    or corrupt checkpoint is never half-loaded and never silently
    restarted from zero — pass ``resume="ignore"`` to opt into a fresh
    start."""

    # retrying a corrupt/drifted/ineligible checkpoint re-reads the same
    # bytes — surface it once; message text ("INTERNAL:..." in a quoted
    # manifest field) must never pattern-match into the transient class
    tfs_fault_class = "deterministic"

    def __init__(
        self,
        message: str,
        field: Optional[str] = None,
        path: Optional[str] = None,
        kind: str = "invalid",
    ):
        super().__init__(message)
        self.field = field
        self.path = path
        self.kind = kind


def _record_incident(e: "CheckpointError") -> None:
    """Hand a commit/load failure to the flight recorder. A failing
    INCIDENT bundle commit cannot recurse: the recorder holds its
    reentrancy guard across its own store I/O."""
    try:
        from . import blackbox as _blackbox

        _blackbox.capture("checkpoint", e)
    except Exception:
        pass  # the recorder must never mask the checkpoint fault


# ---------------------------------------------------------------------------
# process-wide accounting (diagnostics section + test surface)
# ---------------------------------------------------------------------------

_acct_lock = threading.Lock()
_acct: Dict = {
    "commits": 0,
    "resumes": 0,
    "chunks_skipped": 0,
    "ignored": 0,  # resume="ignore" fresh starts over an existing file
    "last_commit": None,
    "last_resume": None,
}


def state() -> Dict:
    """Durable-stream accounting for ``tfs.diagnostics()``: commit /
    resume / skipped-chunk totals plus the most recent commit and
    resume descriptors."""
    with _acct_lock:
        out = dict(_acct)
        out["last_commit"] = (
            dict(_acct["last_commit"]) if _acct["last_commit"] else None
        )
        out["last_resume"] = (
            dict(_acct["last_resume"]) if _acct["last_resume"] else None
        )
    return out


def reset_state() -> None:
    """Test hook: forget the accounting."""
    with _acct_lock:
        _acct.update(
            commits=0, resumes=0, chunks_skipped=0, ignored=0,
            last_commit=None, last_resume=None,
        )


def _note_commit(path: str, watermark: int, partials: int,
                 nbytes: int, seconds: float) -> None:
    with _acct_lock:
        _acct["commits"] += 1
        _acct["last_commit"] = {
            "path": path,
            "watermark": watermark,
            "partials": partials,
            "bytes": nbytes,
            "write_seconds": seconds,
        }


def _note_resume(
    path: str, watermark: int, partials: int, skipped: int
) -> None:
    with _acct_lock:
        _acct["resumes"] += 1
        _acct["chunks_skipped"] += skipped
        _acct["last_resume"] = {
            "path": path,
            "watermark": watermark,
            "partials": partials,
        }


def _note_ignored() -> None:
    with _acct_lock:
        _acct["ignored"] += 1


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------


def config_digest() -> str:
    """Digest of the numerics-relevant config knobs (see
    `_DIGEST_KNOBS`): part of the manifest, so a resume under knobs
    that would change the reduce's accumulation refuses loudly."""
    from .. import config as _config

    cfg = _config.get()
    blob = json.dumps(
        {k: getattr(cfg, k, None) for k in _DIGEST_KNOBS}, sort_keys=True
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# the store: atomic commit + corruption-checked load
# ---------------------------------------------------------------------------


class CheckpointStore:
    """One checkpoint file. `commit` is atomic (temp + fsync +
    ``os.replace``); `load` verifies magic, framing lengths and the
    manifest's payload sha256 before returning anything — a truncated
    or garbled file raises `CheckpointError` (kind ``corrupt``) instead
    of half-loading."""

    def __init__(self, path):
        self.path = os.fspath(path)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def commit(self, manifest: Dict, payload: bytes) -> int:
        """Atomically replace the checkpoint with (manifest, payload);
        returns the file size written. The manifest is augmented with
        ``schema_version``, ``payload_len`` and ``payload_sha256``."""
        manifest = dict(manifest)
        manifest["schema_version"] = SCHEMA_VERSION
        manifest["payload_len"] = len(payload)
        manifest["payload_sha256"] = hashlib.sha256(payload).hexdigest()
        mbytes = json.dumps(manifest, sort_keys=True).encode()
        blob = (
            MAGIC + _LEN.pack(len(mbytes)) + mbytes
            + _LEN.pack(len(payload)) + payload
        )
        # a SIGKILL inside an earlier commit can strand
        # `<path>.tmp.<pid>` siblings; reap the ones whose writer pid
        # is DEAD so repeated preemptions don't litter the directory
        # with payload-sized orphans. A live pid's temp is left alone:
        # a preempted-but-still-running writer racing its replacement
        # must lose last-writer-wins, not crash on a vanished temp.
        import glob as _glob

        for stale in _glob.glob(f"{_glob.escape(self.path)}.tmp.*"):
            try:
                pid = int(stale.rsplit(".", 1)[1])
            except ValueError:
                continue
            if pid != os.getpid():
                try:
                    os.kill(pid, 0)
                    continue  # writer still alive (or pid reused)
                except ProcessLookupError:
                    pass  # dead: the orphan is safe to reap
                except OSError:
                    continue  # EPERM etc.: assume alive
            try:
                os.unlink(stale)
            except OSError:
                pass
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except CheckpointError as ce:
            _record_incident(ce)
            raise
        except Exception as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            err = CheckpointError(
                f"checkpoint commit to {self.path!r} failed: "
                f"{type(e).__name__}: {e}",
                path=self.path,
            )
            _record_incident(err)
            raise err from e
        # best-effort directory fsync so the rename itself is durable
        try:
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        return len(blob)

    def _corrupt(self, why: str) -> CheckpointError:
        return CheckpointError(
            f"checkpoint {self.path!r} is corrupt ({why}); refusing to "
            "load it — delete the file or pass resume=\"ignore\" to "
            "restart from chunk zero",
            path=self.path, kind="corrupt",
        )

    def load(self) -> Tuple[Dict, bytes]:
        """Read and verify the checkpoint; returns (manifest, payload).
        Raises `CheckpointError` kind ``corrupt`` for any framing /
        checksum violation and kind ``drift`` (field
        ``schema_version``) for a manifest written by a different
        schema generation."""
        try:
            return self._load_verified()
        except CheckpointError as e:
            _record_incident(e)
            raise

    def _load_verified(self) -> Tuple[Dict, bytes]:
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(
                f"checkpoint {self.path!r} unreadable: {e}",
                path=self.path, kind="corrupt",
            ) from e
        hdr = len(MAGIC) + _LEN.size
        if len(blob) < hdr:
            raise self._corrupt("truncated header")
        if blob[: len(MAGIC)] != MAGIC:
            raise self._corrupt("bad magic")
        (mlen,) = _LEN.unpack(blob[len(MAGIC):hdr])
        if len(blob) < hdr + mlen + _LEN.size:
            raise self._corrupt("truncated manifest")
        try:
            manifest = json.loads(blob[hdr:hdr + mlen].decode())
        except Exception:
            raise self._corrupt("unparseable manifest") from None
        if not isinstance(manifest, dict):
            raise self._corrupt("manifest is not an object")
        version = manifest.get("schema_version")
        if version != SCHEMA_VERSION:
            raise CheckpointError(
                f"checkpoint {self.path!r} was written by schema version "
                f"{version!r}; this build reads version {SCHEMA_VERSION} "
                "(drifted field: schema_version)",
                field="schema_version", path=self.path, kind="drift",
            )
        off = hdr + mlen
        (plen,) = _LEN.unpack(blob[off:off + _LEN.size])
        payload = blob[off + _LEN.size:]
        if len(payload) != plen or plen != manifest.get("payload_len"):
            raise self._corrupt("truncated payload")
        digest = hashlib.sha256(payload).hexdigest()
        if digest != manifest.get("payload_sha256"):
            raise self._corrupt("payload checksum mismatch")
        return manifest, payload


# ---------------------------------------------------------------------------
# partial-table serialization (Arrow IPC via io.frame_to_ipc_bytes)
# ---------------------------------------------------------------------------


def partials_to_payload(
    partials: List[Dict[str, object]], fetch_bases: List[str]
) -> Tuple[bytes, bool]:
    """Serialize the live partial list as ONE frame (row i = partial i,
    one column per fetch base) in Arrow IPC stream bytes. Returns
    ``(payload, synced)`` — ``synced`` is True when any partial lived
    on device (the copy is a real D2H sync, accounted by the caller).
    Device partials are COPIED to host; the live list is untouched, so
    the stream keeps overlapping after a commit."""
    from ..frame import TensorFrame
    from ..io import frame_to_ipc_bytes

    synced = False
    cols: Dict[str, np.ndarray] = {}
    for b in fetch_bases:
        vals = []
        for p in partials:
            v = p[b]
            if not isinstance(v, np.ndarray):
                synced = True
            vals.append(np.asarray(v))
        stacked = np.stack(vals)
        if stacked.ndim > 2:
            raise CheckpointError(
                f"checkpoint: fetch {b!r} produces rank-"
                f"{stacked.ndim - 1} partials; the durable payload "
                "round-trips scalar and rank-1 (vector) reduce cells "
                "only",
                field=b,
            )
        cols[b] = stacked
    try:
        return frame_to_ipc_bytes(TensorFrame.from_dict(cols)), synced
    except CheckpointError:
        raise
    except Exception as e:
        raise CheckpointError(
            f"checkpoint: partial table not serializable "
            f"({type(e).__name__}: {e})"
        ) from e


def payload_to_partials(
    payload: bytes, manifest: Dict, store: CheckpointStore
) -> List[Dict[str, np.ndarray]]:
    """Rebuild the partial list from a verified payload."""
    from ..io import frame_from_ipc_bytes

    try:
        frame = frame_from_ipc_bytes(payload)
    except Exception as e:
        raise store._corrupt(
            f"payload not an Arrow IPC stream ({type(e).__name__})"
        ) from e
    n = int(manifest.get("partials", -1))
    bases = list(manifest.get("fetch_names") or [])
    if frame.nrows != n or sorted(frame.columns) != sorted(bases):
        raise store._corrupt("payload does not match its manifest")
    cols = {b: np.asarray(frame.column(b).values) for b in bases}
    return [
        {b: np.asarray(cols[b][i]) for b in bases} for i in range(n)
    ]


# ---------------------------------------------------------------------------
# the per-call protocol object reduce_blocks_stream drives
# ---------------------------------------------------------------------------

_RESUME_MODES = ("auto", "ignore")


class StreamCheckpointer:
    """Checkpoint/resume protocol for ONE `reduce_blocks_stream` call.

    Lifecycle: construct at verb entry (validates arguments, attempts
    the ENTRY-time eligibility check when the graph's declared shapes
    allow it), `try_resume()` before the pipeline starts (loads +
    validates an existing checkpoint, returns the watermark and
    restored partials), `on_first_chunk()` once shapes are known (the
    final eligibility gate + monoid/fold-cadence drift checks),
    `note_chunk_folded()` after every folded chunk (commits every
    ``every`` folds), `on_interrupt()` for clean deadline/cancel exits,
    `finalize()` on success."""

    def __init__(
        self,
        path,
        graph,
        fetch_bases: List[str],
        every: Optional[int],
        resume: str,
        dataset_fingerprint: Optional[str],
    ):
        if resume not in _RESUME_MODES:
            raise CheckpointError(
                f"resume={resume!r} is not one of "
                + " | ".join(repr(m) for m in _RESUME_MODES)
            )
        from .. import config as _config

        if every is None:
            every = int(
                getattr(_config.get(), "stream_checkpoint_every", 16)
            )
        if int(every) < 1:
            raise CheckpointError(
                f"checkpoint_every must be >= 1, got {every!r}"
            )
        self.store = CheckpointStore(path)
        self.every = int(every)
        self.resume = resume
        self.graph = graph
        self.fetch_bases = list(fetch_bases)
        self.dataset_fingerprint = dataset_fingerprint
        self.program_fingerprint = graph.fingerprint()
        self.config_digest = config_digest()
        self.monoids: Optional[Dict[str, str]] = None
        self.fold_every: Optional[int] = None
        self._resumed_manifest: Optional[Dict] = None
        self._folded_since_commit = 0
        self._rank_checked = False
        self.watermark = 0  # last COMMITTED contiguous-chunk watermark

    # -- eligibility ----------------------------------------------------
    def entry_gate(self) -> None:
        """Best-effort eligibility check at verb ENTRY, before any
        chunk decodes: when the graph's declared placeholder shapes
        suffice for classification, a non-classifiable reduce is
        rejected here. Unknown shapes defer the verdict to
        `on_first_chunk` (which can never wrongly reject)."""
        from ..aggregate import _chunk_combiners
        from ..graph.analysis import analyze_graph

        try:
            summary = analyze_graph(self.graph, self.fetch_bases)
            comb = _chunk_combiners(self.graph, self.fetch_bases, summary)
        except Exception:
            return  # shapes unknown at entry; first chunk decides
        if comb is None:
            raise self._ineligible()

    def _ineligible(self) -> CheckpointError:
        return CheckpointError(
            "checkpoint= requires every fetch to be a classifiable "
            "monoid reduce (sum/min/max/prod, float mean) of a "
            "row-local transform — this graph's partials cannot be "
            "durably resumed (exactness could not be guaranteed)",
            kind="ineligible", path=self.store.path,
        )

    # -- resume ---------------------------------------------------------
    def _drift(self, field: str, committed, current) -> CheckpointError:
        return CheckpointError(
            f"checkpoint {self.store.path!r} does not match this call: "
            f"drifted field {field!r} (committed {committed!r}, current "
            f"{current!r}); refusing to resume — fix the drift or pass "
            "resume=\"ignore\" to restart from chunk zero",
            field=field, path=self.store.path, kind="drift",
        )

    def try_resume(self) -> Tuple[int, List[Dict[str, np.ndarray]]]:
        """Load + validate an existing checkpoint. Returns
        ``(watermark, restored_partials)`` — ``(0, [])`` when there is
        nothing (or ``resume="ignore"`` discards what exists). Raises
        `CheckpointError` on corruption or drift."""
        if not self.store.exists():
            return 0, []
        if self.resume == "ignore":
            _note_ignored()
            from ..utils.log import get_logger

            get_logger("checkpoint").warning(
                "resume=\"ignore\": existing checkpoint %s discarded; "
                "restarting the stream from chunk zero",
                self.store.path,
            )
            return 0, []
        manifest, payload = self.store.load()
        for field, current in (
            ("fetch_names", self.fetch_bases),
            ("program_fingerprint", self.program_fingerprint),
            ("dataset_fingerprint", self.dataset_fingerprint),
            ("config_digest", self.config_digest),
        ):
            committed = manifest.get(field)
            if committed != current:
                raise self._drift(field, committed, current)
        watermark = int(manifest.get("watermark", 0))
        if watermark < 0:
            raise self.store._corrupt("negative watermark")
        partials = payload_to_partials(payload, manifest, self.store)
        self._resumed_manifest = manifest
        self.watermark = watermark
        from ..utils import telemetry as _tele

        # "skipped" means NEVER RE-DECODED — true only for the dataset
        # (task-metadata) path; a plain iterator re-pulls committed
        # chunks from the producer (synthesis is paid, dispatch is not)
        skipped = watermark if self.dataset_fingerprint is not None else 0
        _tele.counter_inc("checkpoint_resumes", 1.0)
        if skipped:
            _tele.counter_inc("checkpoint_chunks_skipped", float(skipped))
        if _tele.enabled():
            with _tele.span(
                "checkpoint.resume", kind="checkpoint",
                watermark=watermark, partials=len(partials),
            ):
                pass
        _note_resume(self.store.path, watermark, len(partials), skipped)
        return watermark, partials

    # -- the per-chunk protocol ----------------------------------------
    def on_first_chunk(
        self, monoids: Optional[Dict[str, str]], fold_every: Optional[int]
    ) -> None:
        """The chunk-level eligibility gate + the deferred drift
        checks: ``monoids`` is the `_chunk_combiners` classification
        under the first chunk's real shapes, ``fold_every`` the
        resolved fold cadence. Both are recorded into every later
        manifest; on a resumed stream both are validated against the
        committed values."""
        if monoids is None:
            raise self._ineligible()
        self.monoids = dict(monoids)
        self.fold_every = fold_every
        m = self._resumed_manifest
        if m is not None:
            if m.get("monoids") != self.monoids:
                raise self._drift("monoids", m.get("monoids"), self.monoids)
            if m.get("fold_every") != fold_every:
                raise self._drift(
                    "fold_every", m.get("fold_every"), fold_every
                )

    def _manifest(self, watermark: int, n_partials: int) -> Dict:
        return {
            "fetch_names": self.fetch_bases,
            "program_fingerprint": self.program_fingerprint,
            "dataset_fingerprint": self.dataset_fingerprint,
            "config_digest": self.config_digest,
            "monoids": self.monoids,
            "fold_every": self.fold_every,
            "watermark": int(watermark),
            "partials": int(n_partials),
            "created_unix": time.time(),
        }

    def _commit(self, watermark: int, partials: List[Dict]) -> None:
        from ..utils import telemetry as _tele
        from ..utils.profiling import count as _count

        t0 = time.perf_counter()
        payload, synced = partials_to_payload(partials, self.fetch_bases)
        with _tele.span(
            "checkpoint.commit", kind="checkpoint",
            watermark=watermark, partials=len(partials),
            bytes=len(payload),
        ):
            nbytes = self.store.commit(
                self._manifest(watermark, len(partials)), payload
            )
        dt = time.perf_counter() - t0
        if synced:
            # the payload copy pulled device partials to host: a real
            # D2H sync, accounted like the unfoldable-stream spill
            _count("host_sync")
            if _tele.enabled():
                _tele.histogram_observe("d2h_bytes", float(len(payload)))
        _tele.counter_inc("checkpoint_commits", 1.0)
        if _tele.enabled():
            _tele.histogram_observe("checkpoint_write_seconds", dt)
        self.watermark = watermark
        self._folded_since_commit = 0
        _note_commit(
            self.store.path, watermark, len(partials), nbytes, dt
        )

    def note_chunk_folded(
        self, ordinal: int, partials: List[Dict]
    ) -> bool:
        """One more chunk folded into ``partials``; ``ordinal`` is the
        count of source chunks fully consumed (the candidate
        watermark). Commits when ``checkpoint_every`` folds have
        accumulated; returns True when a commit happened."""
        if not self._rank_checked and partials:
            # `np.ndim` reads metadata only — no D2H sync for device
            # partials; failing at the FIRST fold beats discovering an
            # unserializable payload checkpoint_every chunks later
            self._rank_checked = True
            for b in self.fetch_bases:
                if np.ndim(partials[-1][b]) > 1:
                    raise CheckpointError(
                        f"checkpoint: fetch {b!r} produces rank-"
                        f"{np.ndim(partials[-1][b])} partials; the "
                        "durable payload round-trips scalar and rank-1 "
                        "(vector) reduce cells only",
                        field=b,
                    )
        self._folded_since_commit += 1
        if self._folded_since_commit < self.every:
            return False
        self._commit(ordinal, partials)
        return True

    def on_interrupt(
        self, exc: BaseException, ordinal: int, partials: List[Dict]
    ) -> None:
        """Clean deadline/cancel exit: commit the progress so far (when
        anything new folded since the last commit) and stamp the
        committed watermark onto the escaping exception."""
        if self._folded_since_commit > 0 and partials:
            try:
                self._commit(ordinal, partials)
            except Exception as e:
                # the commit must never mask the typed exit — but the
                # lost recovery point deserves a trace (cf. finalize)
                from ..utils.log import get_logger

                get_logger("checkpoint").warning(
                    "interrupt-time checkpoint commit to %s failed "
                    "(%s: %s); resume will restart from watermark %d",
                    self.store.path, type(e).__name__, e, self.watermark,
                )
        try:
            exc.tfs_checkpoint_path = self.store.path
            exc.tfs_checkpoint_watermark = self.watermark
        except Exception:
            pass  # __slots__ errors refuse stamps; the typed exit raises

    def finalize(self, ordinal: int, partials: List[Dict]) -> None:
        """Successful completion: commit the final state (watermark =
        every chunk), so an identical re-run resumes to a no-op —
        restored partials combine, zero chunks re-decode. A failed
        FINAL commit is logged, not raised: the result already exists
        in memory, and durability bookkeeping must never destroy the
        very thing it protects (mirrors `on_interrupt`)."""
        if self._folded_since_commit > 0 and partials:
            try:
                self._commit(ordinal, partials)
            except Exception as e:
                from ..utils.log import get_logger

                get_logger("checkpoint").warning(
                    "final checkpoint commit to %s failed (%s: %s); "
                    "the completed result is returned anyway — an "
                    "identical re-run will resume from watermark %d",
                    self.store.path, type(e).__name__, e, self.watermark,
                )
