"""Multi-device block scheduler: data-parallel dispatch of blocks.

The reference ran one TF session per Spark partition on whatever
executor the cluster handed it; the port's non-mesh verbs inherited a
single-device analogue — every per-block jit dispatch landed on the
default JAX device, so on a multi-chip host every device but one sat
idle unless the user hand-built a mesh. Blocks are an embarrassingly
parallel unit of work; this module spreads them.

Placement is size-aware largest-first (LPT greedy): blocks sorted by
row count descending are assigned one at a time to the least-loaded
device, which bounds the makespan at 4/3 OPT and — crucially — is
DETERMINISTIC, so a re-run dispatches every block to the same device
and compiles nothing new. The dispatch loop itself stays in block
order: assignment decides *where*, never *when*, so partial lists keep
their block order and ordering-sensitive tests/semantics are untouched.

Execution placement rides jax's committed-input semantics: each block's
feeds are `jax.device_put` onto the assigned device (async; H2D copies
to different devices overlap) and the jitted program runs where its
inputs live. The executor cache entry is shared across devices — the
per-device program specialization happens in jit's own cache, which
keys on the committed device exactly as it keys on shape (the same
mechanism `shape_policy` leans on for bucketing), so per-device compile
counts are visible through `jit_shape_compiles` and bounded by
``ndev x`` the single-device count (``ndev x`` ladder rungs under
bucketing).

Reduce verbs fold per-device partials locally and run ONE final
cross-device combine on the anchor device (associative direct monoid
graphs only — see `api._combine_partials_scheduled`); everything stays
an async device op, so the number of host syncs does not grow.

Scheduling turns on via ``config.block_scheduler`` /
``TFS_BLOCK_SCHEDULER`` ("auto": on when >1 local device) or an
explicit ``devices=`` override on any non-mesh verb; ``mesh=`` always
takes precedence (a mesh owns its own placement). The native executor
(`NativeExecutor.supports_scheduling = False`) is never scheduled — it
owns its own PJRT host and `device_put` would initialize the
in-process JAX backend next to it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "BlockSchedule",
    "DeviceHealth",
    "device_health",
    "device_label",
    "global_device_set",
    "global_mode",
    "health_overview",
    "plan",
    "resolve",
    "schedule_for",
    "schedule_weights",
]

_MODES = ("auto", "on", "off", "global")


# ---------------------------------------------------------------------------
# device health: the failover circuit breaker
# ---------------------------------------------------------------------------


class DeviceHealth:
    """Per-device circuit breaker keyed by device label.

    State machine per device: *closed* (healthy — no entry in the
    table) → a transient dispatch failure OPENS the circuit for
    ``config.device_cooldown_s`` (doubling on repeated failures, capped
    at 8x) → after the cooldown the next `usable` check transitions to
    *half-open* and admits the device to ONE probing schedule (that
    check's caller; further `usable` checks exclude it again until the
    probe reaches a verdict, re-arming after another cooldown in case
    the probing schedule never dispatched to it) → a successful
    dispatch closes the circuit (entry removed), a failure re-opens it
    with the doubled cooldown. `resolve` filters circuit-open devices
    out of auto/on scheduling, so an evicted device's remaining blocks
    re-place onto healthy devices; explicit ``devices=`` pins bypass
    the filter (loudly).

    All timestamps ride an injectable ``now`` (monotonic seconds) so
    the state machine unit-tests without sleeping."""

    def __init__(self):
        self._lock = threading.Lock()
        self._states: Dict[str, Dict] = {}

    def mark_failure(self, label: str, now: Optional[float] = None) -> None:
        """A transient dispatch failure on ``label``: open (or re-open,
        with doubled cooldown) its circuit and count the eviction."""
        from .. import config as _config
        from ..utils import telemetry as _tele
        from ..utils.log import get_logger
        from . import faults as _faults

        now = time.monotonic() if now is None else now
        base = max(1e-3, float(_config.get().device_cooldown_s))
        with self._lock:
            st = self._states.get(label)
            if st is None:
                st = {
                    "state": "open", "failures": 0, "cooldown": base,
                    "until": 0.0, "warned_pin": False,
                }
                self._states[label] = st
            else:
                st["state"] = "open"
                st["cooldown"] = min(st["cooldown"] * 2.0, base * 8.0)
            st["failures"] += 1
            st["until"] = now + st["cooldown"]
            cooldown = st["cooldown"]
            failures = st["failures"]
        _faults.note_eviction()
        _tele.counter_inc("device_evictions", 1.0, device=label)
        get_logger("scheduler").warning(
            "device %s evicted after a transient dispatch failure; "
            "circuit open for %.1fs (half-open probe after cooldown)",
            label, cooldown,
        )
        try:
            # circuit-open eviction is an incident even though no
            # exception escapes (the work re-places); captured after
            # self._lock is released — the recorder does file I/O
            from . import blackbox as _blackbox

            _blackbox.capture(
                "eviction",
                extra={
                    "device": label, "failures": failures,
                    "cooldown_s": cooldown,
                },
            )
        except Exception:
            pass  # the recorder must never break an eviction path

    def mark_success(self, label: str) -> None:
        """A successful dispatch on ``label``: closes a half-open
        circuit (the probe passed). Fast path: no table entries, no
        lock contention — the steady state costs one dict check."""
        if not self._states:
            return
        with self._lock:
            st = self._states.get(label)
            if st is not None and st["state"] == "half-open":
                del self._states[label]

    def usable(self, label: str, now: Optional[float] = None) -> bool:
        """True when ``label`` may receive dispatches: circuit closed,
        or open-past-cooldown (transitions to half-open and admits ONE
        probing caller — later checks exclude the device again until
        the probe's verdict, re-arming after another cooldown so a
        probe that never dispatched cannot strand the device)."""
        if not self._states:
            return True
        now = time.monotonic() if now is None else now
        with self._lock:
            st = self._states.get(label)
            if st is None:
                return True
            if st["state"] == "open":
                if now >= st["until"]:
                    st["state"] = "half-open"
                    st["probe_rearm"] = now + st["cooldown"]
                    return True
                return False
            # half-open: the transition call above was the probe
            # admission; everyone else waits for the verdict (or for
            # the re-arm window, if the probing schedule never ran)
            if now >= st.get("probe_rearm", 0.0):
                st["probe_rearm"] = now + st["cooldown"]
                return True
            return False

    def filter(self, devices: Sequence, now: Optional[float] = None) -> List:
        return [d for d in devices if self.usable(device_label(d), now)]

    def table(self) -> List[Dict]:
        """Snapshot for `tfs.diagnostics()`: one row per non-closed
        circuit (an empty table means every device is healthy)."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "device": label,
                    "state": st["state"],
                    "failures": st["failures"],
                    "cooldown_s": round(st["cooldown"], 3),
                    "retry_in_s": round(max(0.0, st["until"] - now), 3),
                }
                for label, st in sorted(self._states.items())
            ]

    def warn_pinned(self, label: str) -> bool:
        """Explicit ``devices=`` pins opt out of failover — but a pin
        onto a circuit-open device deserves one loud warning per
        episode. Returns True when the warning should fire."""
        with self._lock:
            st = self._states.get(label)
            if st is None or st["warned_pin"]:
                return False
            st["warned_pin"] = True
            return True

    def reset(self) -> None:
        with self._lock:
            self._states.clear()


_health = DeviceHealth()


def device_health() -> DeviceHealth:
    """The process-wide device-health registry (one circuit breaker per
    device label, shared by every schedule)."""
    return _health


def health_overview() -> List[Dict]:
    """One row per LOCAL device — healthy devices included (unlike
    `DeviceHealth.table`, which lists only tripped circuits): label,
    kind, circuit state (``closed`` / ``open`` / ``half-open``),
    failure count and remaining cooldown. The /healthz endpoint's
    payload; circuits for devices no longer local (a fallback set after
    a grant timeout) are appended so they stay visible."""
    by_label = {row["device"]: row for row in _health.table()}
    rows: List[Dict] = []
    try:
        devices = _local_devices()
    except Exception:
        devices = []
    seen = set()
    for d in devices:
        lab = device_label(d)
        seen.add(lab)
        tripped = by_label.get(lab)
        rows.append(
            {
                "device": lab,
                "device_kind": getattr(d, "device_kind", None),
                "state": tripped["state"] if tripped else "closed",
                "failures": tripped["failures"] if tripped else 0,
                "cooldown_s": tripped["cooldown_s"] if tripped else 0.0,
                "retry_in_s": tripped["retry_in_s"] if tripped else 0.0,
            }
        )
    for lab, tripped in sorted(by_label.items()):
        if lab not in seen:
            rows.append({"device_kind": None, **tripped})
    return rows


def device_label(dev) -> str:
    """The telemetry label for a device: ``platform:id`` (what dispatch
    spans, per-device executor stats and the queue-depth gauge key on)."""
    return f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', '?')}"


def plan(weights: Sequence[int], ndev: int) -> List[Optional[int]]:
    """Size-aware largest-first placement: item indices sorted by weight
    descending (ties: lower index first) are greedily assigned to the
    least-loaded device slot (ties: lowest slot). Returns one slot per
    item; zero-weight items map to ``None`` (empty blocks are never
    dispatched, so they must not skew the load ledger)."""
    if ndev < 1:
        raise ValueError(f"plan needs >= 1 device, got {ndev}")
    order = sorted(range(len(weights)), key=lambda i: (-int(weights[i]), i))
    load = [0] * ndev
    out: List[Optional[int]] = [None] * len(weights)
    for i in order:
        w = int(weights[i])
        if w <= 0:
            continue
        slot = min(range(ndev), key=lambda s: (load[s], s))
        load[slot] += w
        out[i] = slot
    return out


def _local_devices() -> List:
    import jax

    from .. import config as _config
    from . import deadline as _dl

    t = _config.get().device_grant_timeout_s
    if (t and t > 0) or _dl.remaining() is not None:
        # device-grant watchdog: a wedged accelerator backend (stuck at
        # device grant — the shared-TPU failure mode) times out here and
        # the process degrades to the CPU backend with a loud one-time
        # warning instead of hanging forever. An active verb DEADLINE
        # arms the watchdog too (min of the two budgets, applied inside
        # device_grant): a deadlined verb can never wedge at grant even
        # with the config watchdog off.
        from . import faults as _faults

        return list(
            _faults.device_grant(
                grab=jax.local_devices, timeout_s=t if t and t > 0 else None
            )
        )
    return list(jax.local_devices())


def _normalize_devices(devices) -> Tuple:
    """Explicit ``devices=``: accept jax Device objects or local-device
    indices; reject empty (an empty override means the caller's intent
    is unclear — pass None for auto or set block_scheduler='off')."""
    devs = list(devices)
    if not devs:
        raise ValueError(
            "devices=[] is ambiguous; pass None (config decides) or "
            "disable with config.block_scheduler='off'"
        )
    local = None
    out = []
    for d in devs:
        if isinstance(d, (int, np.integer)):
            if local is None:
                local = _local_devices()
            if not 0 <= int(d) < len(local):
                raise ValueError(
                    f"devices: index {int(d)} out of range for "
                    f"{len(local)} local device(s)"
                )
            out.append(local[int(d)])
        else:
            out.append(d)
    return tuple(out)


def global_mode() -> bool:
    """True when ``config.block_scheduler == "global"`` — eligible verb
    dispatches route through the `GlobalFrame` SPMD path; everything
    ineligible falls back to per-block scheduling (``resolve`` treats
    the mode as "auto" for that fallback)."""
    from .. import config as _config

    return _config.get().block_scheduler == "global"


def global_device_set() -> List:
    """The local devices a `GlobalFrame` data mesh spans: every local
    device whose failover circuit is closed. When circuit-open devices
    shrink the set, say so LOUDLY — a shrunk mesh changes sharding (and
    therefore which compiled program runs), which an operator debugging
    throughput must be able to see. All circuits open falls back to the
    full set (same last-resort rule as `resolve`)."""
    devs = _local_devices()
    healthy = _health.filter(devs)
    if not healthy:
        from ..utils.log import get_logger

        get_logger("scheduler").warning(
            "every local device's failover circuit is open; building "
            "the global-frame mesh over the full device set anyway"
        )
        return devs
    if len(healthy) < len(devs):
        from ..utils.log import get_logger

        get_logger("scheduler").warning(
            "global-frame mesh shrunk to %d of %d local device(s): "
            "%s circuit-open after transient failures",
            len(healthy), len(devs),
            ",".join(
                device_label(d) for d in devs if d not in healthy
            ),
        )
    return healthy


def resolve(
    devices=None, executor=None, mesh=None
) -> Optional[Tuple]:
    """The device set a verb call should schedule blocks over, or None
    when scheduling is off for this dispatch.

    Precedence: ``mesh=`` wins outright (the mesh path owns placement);
    an executor that does not opt in (`supports_scheduling`) is never
    scheduled — with an explicit ``devices=`` that is a loud error, not
    a silent drop; an explicit ``devices=`` list wins over the config;
    otherwise ``config.block_scheduler``: "off" disables, "on" schedules
    onto all local devices (even one — useful to force the scheduled
    code path), "auto" (default) schedules only when >1 local device
    exists. "global" behaves like "auto" HERE: the GlobalFrame SPMD
    routing happens above this call at the verb layer, and everything
    that falls through (ineligible graphs, small frames) still deserves
    per-block scheduling."""
    if mesh is not None:
        if devices is not None:
            raise ValueError(
                "devices= and mesh= are mutually exclusive; the mesh "
                "owns block placement"
            )
        return None
    supported = executor is None or getattr(
        executor, "supports_scheduling", False
    )
    if devices is not None:
        if not supported:
            raise ValueError(
                "devices= needs an executor that supports block "
                f"scheduling; {type(executor).__name__} does not (the "
                "native host owns its own device)"
            )
        devs = _normalize_devices(devices)
        # pins opt OUT of failover — loudly: a pin onto a circuit-open
        # device is deliberate placement, but the operator should know
        # the scheduler would have avoided it
        for d in devs:
            lab = device_label(d)
            if not _health.usable(lab) and _health.warn_pinned(lab):
                from ..utils.log import get_logger

                get_logger("scheduler").warning(
                    "devices= pins dispatches to %s, whose failover "
                    "circuit is OPEN after transient failures; explicit "
                    "pins bypass device failover",
                    lab,
                )
        return devs
    if not supported:
        return None
    from .. import config as _config

    mode = _config.get().block_scheduler
    if mode not in _MODES:
        # fail loud: a typo'd mode silently meaning "off" would defeat
        # the knob (same discipline as config.native_executor)
        raise ValueError(
            f"config.block_scheduler={mode!r} is not one of "
            "'auto' | 'on' | 'off' | 'global'"
        )
    if mode == "off":
        return None
    devs = _local_devices()
    if mode in ("auto", "global") and len(devs) < 2:
        return None
    # failover: circuit-open devices drop out of auto/on scheduling
    # until their cooldown elapses (then ONE half-open probe re-admits
    # them on success). With every device evicted there is nothing left
    # to fail over to: schedule the full set rather than nothing.
    healthy = _health.filter(devs)
    if not healthy:
        from ..utils.log import get_logger

        get_logger("scheduler").warning(
            "every local device's failover circuit is open; scheduling "
            "over the full device set anyway"
        )
        healthy = devs
    return tuple(healthy)


class BlockSchedule:
    """One verb call's placement: device set + per-item slot assignment.

    ``bind(i, fn)`` returns the dispatch callable for item ``i``: it
    `device_put`s the feeds onto the assigned device, invokes ``fn``
    (committed inputs place the execution), and keeps the per-device
    dispatch/compile ledgers on the executor plus the per-device
    queue-depth gauge. ``put(i, feeds)`` is the feeds-only half for
    callers that invoke the program themselves."""

    __slots__ = (
        "devices", "labels", "assignment", "executor", "weights",
        "_issued", "_remaining", "_lock",
    )

    def __init__(self, devices: Tuple, assignment: List[Optional[int]],
                 executor=None, weights: Optional[Sequence[int]] = None):
        self.devices = tuple(devices)
        self.labels = tuple(device_label(d) for d in self.devices)
        self.assignment = list(assignment)
        self.executor = executor
        # per-item weights (row counts): what `evict` re-places by.
        # Callers constructing BlockSchedule directly (tests) may omit
        # them — failover then re-places with unit weights.
        self.weights = (
            [1 if s is not None else 0 for s in self.assignment]
            if weights is None
            else [int(w) for w in weights]
        )
        self._issued = [False] * len(self.assignment)
        self._remaining = [0] * len(self.devices)
        for s in self.assignment:
            if s is not None:
                self._remaining[s] += 1
        self._lock = threading.Lock()

    @property
    def ndev(self) -> int:
        return len(self.devices)

    def slot(self, i: int) -> Optional[int]:
        return self.assignment[i]

    def device(self, i: int):
        s = self.assignment[i]
        return None if s is None else self.devices[s]

    def label(self, i: int) -> Optional[str]:
        s = self.assignment[i]
        return None if s is None else self.labels[s]

    def anchor_device(self):
        """Where cross-device results converge (final combines, gathered
        partials): slot 0, deterministically."""
        return self.devices[0]

    # -- dispatch ------------------------------------------------------
    def put(self, i: int, feeds: Sequence) -> List:
        """`device_put` the feeds onto item ``i``'s device (async) and
        account the dispatch (per-device ledger + queue-depth gauge)."""
        import jax

        s = self.assignment[i]
        if s is None:
            return list(feeds)
        dev = self.devices[s]
        out = [jax.device_put(f, dev) for f in feeds]
        self._note_dispatch(i, s)
        # put-path verbs (reduce_rows folds, chunked aggregation) are
        # the only dispatches some workloads ever issue — a successful
        # transfer onto the device must close its half-open circuit
        # too, or a probe could hang in half-open forever
        _health.mark_success(self.labels[s])
        return out

    def bind(self, i: int, fn, valid=None):
        """The dispatch callable for item ``i``: feeds -> outputs on the
        assigned device. ``valid`` prefixes the call with the traced
        true-row-count scalar of a masked bucketed reduce program
        (`shape_policy.build_masked_reduce`'s calling convention).
        Detects per-device jit compiles by watching the program's jit
        cache across the call (best-effort under concurrent verbs —
        same caveat as `Executor._instrument`). The slot is read at
        CALL time, so a thunk rebuilt after `evict` re-placed the item
        dispatches to the item's NEW device; a successful call feeds
        the device-health registry (closes a half-open circuit)."""

        def call(*feeds):
            import jax

            s = self.assignment[i]
            if s is None:
                return fn(*feeds) if valid is None else fn(
                    np.int32(valid), *feeds
                )
            dev = self.devices[s]
            put = [jax.device_put(f, dev) for f in feeds]
            sizer = getattr(fn, "_cache_size", None)
            n0 = None
            if callable(sizer):
                try:
                    n0 = sizer()
                except Exception:
                    n0 = None
            if valid is None:
                out = fn(*put)
            else:
                out = fn(np.int32(valid), *put)
            if n0 is not None:
                try:
                    n1 = sizer()
                except Exception:
                    n1 = None
                if n1 is not None and n1 > n0:
                    _bump(self.executor, "device_compiles",
                          self.labels[s], n1 - n0)
            self._note_dispatch(i, s)
            _health.mark_success(self.labels[s])
            return out

        return call

    def progress(self) -> Dict[str, int]:
        """Partial-work accounting: how many planned dispatches have
        been issued vs not. What a `DeadlineExceeded` escaping a
        scheduled verb is stamped with (``tfs_blocks_issued`` /
        ``tfs_blocks_unissued``) — a cancelled verb stops issuing at
        the next boundary check, and this says exactly how far it
        got."""
        with self._lock:
            planned = sum(1 for s in self.assignment if s is not None)
            issued = sum(
                1
                for i, s in enumerate(self.assignment)
                if s is not None and self._issued[i]
            )
        return {
            "planned": planned,
            "issued": issued,
            "unissued": planned - issued,
        }

    def evict(self, index: int) -> Optional[str]:
        """Failover after a transient failure of item ``index``: open
        the circuit of its device (`DeviceHealth.mark_failure`) and
        re-place every not-yet-issued item — including ``index``
        itself — LPT onto the remaining usable devices, on top of the
        load the already-issued items put there. Already-computed
        partials stay where they are (their buffers are assumed
        readable — a HARD device loss surfaces at the combine and
        fails the verb after the budget). Returns the evicted device's
        label, or None when the item was unscheduled or no other
        usable device exists — in which case NOTHING is counted or
        circuit-opened: the retry re-runs in place, and an "eviction"
        with nowhere to go would overcount the re-placement metric
        (and, on a single-device schedule, open the only circuit)."""
        s = self.assignment[index]
        if s is None:
            return None
        label = self.labels[s]
        with self._lock:
            alive = [
                t for t in range(self.ndev)
                if t != s and _health.usable(self.labels[t])
            ]
        if not alive:
            return None
        _health.mark_failure(label)
        with self._lock:
            load = {t: 0 for t in alive}
            pending: List[int] = []
            for i, slot in enumerate(self.assignment):
                if slot is None:
                    continue
                if self._issued[i] and i != index:
                    if slot in load:
                        load[slot] += self.weights[i]
                else:
                    pending.append(i)
            # LPT over the survivors: heaviest pending item first onto
            # the least-loaded usable slot — same policy, same
            # determinism, as the original plan()
            pending.sort(key=lambda i: (-self.weights[i], i))
            for i in pending:
                t = min(alive, key=lambda a: (load[a], a))
                load[t] += max(1, self.weights[i])
                self.assignment[i] = t
            # rebuild the queue-depth ledger from the new assignment
            self._remaining = [0] * self.ndev
            for i, slot in enumerate(self.assignment):
                if slot is not None and not self._issued[i]:
                    self._remaining[slot] += 1
        return label

    def _note_dispatch(self, i: int, s: int) -> None:
        _bump(self.executor, "device_dispatches", self.labels[s], 1)
        from ..utils import telemetry as _tele

        with self._lock:
            self._issued[i] = True
            self._remaining[s] = max(0, self._remaining[s] - 1)
            depth = self._remaining[s]
        if _tele.enabled():
            # host-side dispatch queue: how many planned dispatches for
            # this device have not been issued yet this verb call
            _tele.gauge_set(
                "scheduler_queue_depth", depth, device=self.labels[s]
            )


def _bump(ex, attr: str, label: str, n: int) -> None:
    """Increment a per-device ledger dict on the executor, under its
    lock when it has one. Executors without the ledger (stubs, native)
    are silently skipped — the ledgers are observability, not
    correctness."""
    d = getattr(ex, attr, None)
    if d is None:
        return
    lock = getattr(ex, "_lock", None)
    if lock is not None:
        with lock:
            d[label] = d.get(label, 0) + n
    else:  # pragma: no cover - executors always carry _lock today
        d[label] = d.get(label, 0) + n


def schedule_weights(
    weights: Sequence[int], devices=None, executor=None, mesh=None
) -> Optional[BlockSchedule]:
    """Resolve the device set and plan ``weights`` over it; None when
    scheduling is off for this dispatch (the caller then runs the
    ordinary unscheduled loop)."""
    devs = resolve(devices=devices, executor=executor, mesh=mesh)
    if devs is None:
        return None
    return BlockSchedule(
        devs, plan(weights, len(devs)), executor=executor, weights=weights
    )


def schedule_for(
    frame, devices=None, executor=None, mesh=None
) -> Optional[BlockSchedule]:
    """`schedule_weights` over a frame's block sizes — the per-block
    verbs' entry point (one dispatch per non-empty block, weighted by
    row count)."""
    return schedule_weights(
        frame.block_sizes(), devices=devices, executor=executor, mesh=mesh
    )
