"""NativeExecutor: run verbs through the C++ PJRT host.

Drop-in for `runtime.Executor`: graphs lower to StableHLO once (JAX used
as a tracer only — no JAX backend touches the device), then compile and
EVERY execution (H2D, run, D2H) goes through the native host
(native/pjrt_host.cc). Pass ``executor=NativeExecutor(...)`` to any verb.

This completes the reference-parity story for the native runtime: where
TensorFrames' workers called libtensorflow through JNI per partition
(`DebugRowOps.scala:790-809`), the verbs here call a C++ PJRT host that
owns the TPU client.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..graph.ir import Graph
from ..ops.lowering import build_callable
from .pjrt_host import PjrtHost, stablehlo_for

__all__ = ["NativeExecutor"]


class NativeExecutor:
    """Compile cache + execution via the native PJRT host.

    Note: one host per process per plugin; don't mix with a JAX backend
    that owns the same device in-process.
    """

    def __init__(
        self, plugin_path: Optional[str] = None, jax_fallback: bool = False
    ):
        self.host = PjrtHost(plugin_path)
        self._cache: Dict[Tuple, Callable] = {}
        self.compile_count = 0
        self._allow_jax_fallback = jax_fallback
        self._jax_fallback = None

    def cached(self, kind, graph, fetches, feed_names, make):
        # Non-block execution kinds (vmapped rows, scan folds, shard_map)
        # need the in-process JAX executor: the native host is a
        # single-program-at-a-time engine by design. Running a JAX backend
        # next to a native host that owns the same device is unsafe
        # (double TPU client), so it is strictly opt-in.
        if not self._allow_jax_fallback:
            raise NotImplementedError(
                f"NativeExecutor runs block-level programs only; {kind!r} "
                "execution needs the in-process JAX executor. Construct "
                "NativeExecutor(jax_fallback=True) ONLY if the JAX backend "
                "does not own the same device as the native host."
            )
        if self._jax_fallback is None:
            from .executor import Executor

            self._jax_fallback = Executor()
        return self._jax_fallback.cached(kind, graph, fetches, feed_names, make)

    def callable_for(
        self,
        graph: Graph,
        fetches: Sequence[str],
        feed_names: Sequence[str],
    ) -> Callable:
        key = (graph.fingerprint(), tuple(fetches), tuple(feed_names))
        fn = self._cache.get(key)
        if fn is not None:
            return fn
        raw = build_callable(graph, list(fetches), list(feed_names))
        exe_cache: Dict[Tuple, Tuple] = {}

        def run(*arrays):
            import jax

            arrays = [np.asarray(a) for a in arrays]
            shape_key = tuple((a.shape, str(a.dtype)) for a in arrays)
            entry = exe_cache.get(shape_key)
            if entry is None:
                import jax.numpy as jnp

                structs = [
                    jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays
                ]
                out_structs = jax.eval_shape(raw, *structs)
                out_specs = [
                    (tuple(o.shape), np.dtype(o.dtype)) for o in out_structs
                ]
                mlir = stablehlo_for(raw, *structs)
                exe = self.host.compile(mlir)
                self.compile_count += 1
                entry = (exe, out_specs)
                exe_cache[shape_key] = entry
            exe, out_specs = entry
            return tuple(exe(*arrays, out_specs=out_specs))

        self._cache[key] = run
        return run
