"""NativeExecutor: run verbs through the C++ PJRT host.

Drop-in for `runtime.Executor`: graphs lower to StableHLO once (JAX used
as a tracer only — no JAX backend touches the device), then compile and
EVERY execution (H2D, run, D2H) goes through the native host
(native/pjrt_host.cc). Pass ``executor=NativeExecutor(...)`` to any verb.

All single-program execution kinds run natively: plain block calls,
vmapped per-row programs, `lax.scan` folds, and the chunked-aggregate
stages each lower to ONE StableHLO module, which is exactly what the
host consumes. shard_map MESH kinds run natively too when the host's
plugin exposes enough devices (``NativeExecutor(devices=8)`` with the
repo CPU plugin): the lowered module carries ``mhlo.num_partitions``,
the plugin compiles it SPMD and executes all partitions in parallel,
and the host keeps its global-view calling convention — zero Python,
zero in-process JAX backend in the execution path. On a single-device
plugin (the one-chip TPU tunnel) mesh kinds still need the in-process
JAX backend and remain opt-in via ``jax_fallback``.

This completes the reference-parity story for the native runtime: where
TensorFrames' workers called libtensorflow through JNI per partition for
EVERY verb (`DebugRowOps.scala:790-809`), the verbs here call a C++ PJRT
host that owns the TPU client.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..graph.ir import Graph
from ..ops.lowering import build_callable
from .pjrt_host import PjrtHost

__all__ = ["NativeExecutor"]

# shard_map programs span a multi-device mesh; they execute natively
# when the host has enough devices, otherwise they need the in-process
# JAX executor (see `cached`).
_MESH_KIND_PREFIXES = ("shmap-", "shred-", "shfold-", "shagg-")

# Lowering flips the PROCESS-GLOBAL jax_use_shardy_partitioner flag
# (restored in a finally); concurrent first-call compiles from two
# threads would race the flip/restore and could leave the flag off for
# unrelated JAX code. One lock serializes all native lowerings.
_LOWER_LOCK = threading.Lock()


class NativeExecutor:
    """Compile cache + execution via the native PJRT host.

    ``devices``: request a device count from the plugin (the repo CPU
    plugin honors ``cpu_device_count``; required for native mesh
    execution). Note: one host per process per plugin; don't mix with a
    JAX backend that owns the same device in-process.
    """

    def __init__(
        self,
        plugin_path: Optional[str] = None,
        jax_fallback: bool = False,
        devices: Optional[int] = None,
    ):
        create_options = (
            {"cpu_device_count": int(devices)} if devices else None
        )
        self._bind_host(
            PjrtHost(plugin_path, create_options=create_options),
            jax_fallback,
        )

    # The host executes lowered modules through its own buffer protocol;
    # donation aliasing is not part of that contract, so verbs build
    # non-donating combine programs for this executor.
    supports_donation = False
    # Shape bucketing applies here too: `_native_run` compiles one host
    # executable per input shape signature, so quantizing block shapes
    # bounds native compiles exactly as it bounds jit specializations.
    supports_bucketing = True
    # Never block-scheduled: execution flows through the host's own
    # buffer protocol, and an in-process jax.device_put beside a host
    # that may own the same device is the documented double-client
    # hazard. The block scheduler skips this executor (and an explicit
    # devices= on a verb raises).
    supports_scheduling = False

    def _bind_host(self, host, jax_fallback: bool = False) -> None:
        """All non-host state in one place (also the seam tests use to
        wrap an existing host without claiming the plugin twice)."""
        self.host = host
        self._cache: "OrderedDict[Tuple, Callable]" = OrderedDict()
        self._lock = threading.Lock()
        self.compile_count = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self._allow_jax_fallback = jax_fallback
        self._jax_fallback = None

    @classmethod
    def for_host(cls, host, jax_fallback: bool = False) -> "NativeExecutor":
        """Executor over an ALREADY-CREATED host (one host per process
        per plugin; creating a second claims the device again)."""
        ex = cls.__new__(cls)
        ex._bind_host(host, jax_fallback)
        return ex

    @staticmethod
    def _ledger_key(label: Optional[Tuple], traceable: Callable) -> Tuple:
        """The cost ledger's (kind, fingerprint) for a native program:
        the executor cache key when `cached` routed here, else the
        function front-end's name (the same fallback labeling
        `record_compile` uses)."""
        if label is not None:
            return label
        return ("fn", getattr(traceable, "__name__", "<fn>"))

    def _native_run(
        self, traceable: Callable, label: Optional[Tuple] = None
    ) -> Callable:
        """Wrap a jittable function (possibly taking/returning pytrees)
        as a native-host call: lower per concrete input-shape signature,
        compile through the host, execute with flat numpy buffers, and
        rebuild the output pytree. The lowered module's parameter and
        result orders are the flattened pytree orders, which is what
        makes this correct for dict-carrying folds too. ``label`` (the
        executor cache key, when called from `cached`) attributes each
        per-shape host compile to its graph fingerprint in telemetry."""
        exe_cache: Dict[Tuple, Tuple] = {}

        def run(*args):
            import jax

            flat_in, in_tree = jax.tree_util.tree_flatten(args)
            flat_in = [np.asarray(a) for a in flat_in]
            shape_key = (
                in_tree,
                tuple((a.shape, str(a.dtype)) for a in flat_in),
            )
            entry = exe_cache.get(shape_key)
            if entry is None:
                import time as _time

                _t0 = _time.perf_counter()
                structs = jax.tree_util.tree_unflatten(
                    in_tree,
                    [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat_in],
                )
                # keep_unused: without it jit DCEs unused arguments out
                # of the module's parameter list and execution fails
                # with a buffer-count mismatch (e.g. the segment
                # aggregate's counts input when no fetch is a Mean).
                # Shardy is disabled for the lowering: the host's plugins
                # consume classic GSPMD StableHLO (custom_call @Sharding /
                # SPMDFullToShardShape), not the sdy dialect.
                with _LOWER_LOCK:
                    prev_sdy = jax.config.jax_use_shardy_partitioner
                    jax.config.update("jax_use_shardy_partitioner", False)
                    try:
                        lowered = jax.jit(traceable, keep_unused=True).lower(
                            *structs
                        )
                        mlir = str(lowered.compiler_ir(dialect="stablehlo"))
                    finally:
                        jax.config.update(
                            "jax_use_shardy_partitioner", prev_sdy
                        )
                out_flat, out_tree = jax.tree_util.tree_flatten(
                    lowered.out_info
                )
                out_specs = [
                    (tuple(o.shape), np.dtype(o.dtype)) for o in out_flat
                ]
                m = re.search(r"mhlo\.num_partitions = (\d+)", mlir)
                nparts = int(m.group(1)) if m else 1
                if nparts > self.host.device_count:
                    if getattr(self, "_allow_jax_fallback", False):
                        # the opted-in fallback covers this case too: a
                        # multi-device host that is still SMALLER than
                        # the program's partition count executes via the
                        # in-process JAX backend (the traceable is the
                        # already-jitted mesh program)
                        entry = ("jax", traceable, None)
                        exe_cache[shape_key] = entry
                    else:
                        raise RuntimeError(
                            f"program wants {nparts} partitions but the "
                            f"native host has {self.host.device_count} "
                            "device(s); construct NativeExecutor(devices=N) "
                            "with a multi-device plugin, or opt into "
                            "jax_fallback=True"
                        )
                else:
                    exe = self.host.compile(mlir)
                    with self._lock:  # += is not atomic; keep exact
                        self.compile_count += 1
                    entry = (exe, out_specs, out_tree)
                    exe_cache[shape_key] = entry
                    # each (program, shape signature) is one real host
                    # compile — attribute it like the jit "xla" phase
                    from ..utils import telemetry as _tele

                    _t1 = _time.perf_counter()
                    _tele.record_compile(
                        label[1] if label else getattr(
                            traceable, "__name__", "<fn>"
                        ),
                        label[0] if label else "fn",
                        _t1 - _t0,
                        "native",
                        _t0,
                        _t1,
                    )
                    # cost ledger: the Lowered is already in hand here,
                    # so modeled flops/bytes cost one HLO cost analysis
                    from . import costmodel as _cm

                    if _cm.enabled():
                        _cm.capture(
                            self._ledger_key(label, traceable),
                            None, args, lowered=lowered, phase="native",
                        )
            from . import costmodel as _cm

            if entry[0] == "jax":
                out = entry[1](*args)
                # the opted-in fallback has no Lowered to capture cost
                # from, but its executions still count — the program
                # stays visible in the ledger with honest None cost
                if _cm.enabled():
                    _cm.note_exec(
                        self._ledger_key(label, traceable), args, out
                    )
                return out
            exe, out_specs, out_tree = entry
            outs = exe(*flat_in, out_specs=out_specs)
            out = jax.tree_util.tree_unflatten(out_tree, outs)
            if _cm.enabled():
                _cm.note_exec(self._ledger_key(label, traceable), args, out)
            return out

        return run

    def jit(self, fn: Callable) -> Callable:
        """The function-front-end seam: compile an arbitrary jittable
        through the native host (per-shape-signature cache inside
        `_native_run`), so plain-function verbs run on the C++ PJRT
        host too when this executor is the default."""
        return self._native_run(fn)

    def cached(self, kind, graph, fetches, feed_names, make):
        if (
            kind.startswith(_MESH_KIND_PREFIXES)
            and self.host.device_count <= 1
        ):
            # A single-device host cannot satisfy a multi-partition
            # program. Mesh execution then needs the in-process JAX
            # executor — but running a JAX backend next to a native host
            # that owns the same device is unsafe (double TPU client),
            # so it is strictly opt-in.
            if not getattr(self, "_allow_jax_fallback", False):
                raise NotImplementedError(
                    f"this NativeExecutor's host has one device; {kind!r} "
                    "(shard_map over a mesh) needs either a multi-device "
                    "plugin (NativeExecutor(devices=N)) or the in-process "
                    "JAX executor. Construct NativeExecutor("
                    "jax_fallback=True) ONLY if the JAX backend does not "
                    "own the same device as the native host."
                )
            if self._jax_fallback is None:
                from .executor import Executor

                self._jax_fallback = Executor()
            return self._jax_fallback.cached(
                kind, graph, fetches, feed_names, make
            )
        key = (kind, graph.fingerprint(), tuple(fetches), tuple(feed_names))
        from .. import config as _config
        from .executor import lru_get_or_insert

        # the shared locked-LRU discipline (evicted wrappers free their
        # PJRT executables via NativeExecutable.__del__ once no call
        # holds them). `make()` hands back a jax.jit-wrapped program —
        # used purely as a lowering recipe; execution never touches the
        # in-process JAX backend.
        fn, inserted = lru_get_or_insert(
            self._cache, self._lock, key,
            lambda: self._native_run(make(), label=key),
            _config.get().executor_cache_entries,
        )
        with self._lock:  # mirror Executor.cached's hit/miss accounting
            if inserted:
                self.cache_misses += 1
            else:
                self.cache_hits += 1
        from . import executor as _exmod

        if _exmod._fault_injector is not None:  # shared injection seam
            fn = _exmod._fault_injector(fn, key)
        return fn

    def callable_for(
        self,
        graph: Graph,
        fetches: Sequence[str],
        feed_names: Sequence[str],
    ) -> Callable:
        return self.cached(
            "block",
            graph,
            fetches,
            feed_names,
            lambda: build_callable(graph, list(fetches), list(feed_names)),
        )

    def cache_keys(self):
        """Interface parity with `Executor.cache_keys` (live compile-cache
        key snapshot; the fusion bench/tests count kinds through it)."""
        with self._lock:
            return list(self._cache.keys())

    def jit_shape_compiles(self) -> int:
        """Interface parity with `Executor.jit_shape_compiles`. The
        native host compiles one executable per (program, input shape
        signature) — and `compile_count` increments on exactly those
        compiles — so here the two metrics coincide."""
        return int(self.compile_count)

    def run(
        self,
        graph: Graph,
        fetches: Sequence[str],
        feeds: Dict[str, np.ndarray],
        materialize: bool = False,
    ):
        """Mirror of `Executor.run`'s contract. The native host's
        execute already lands results in host buffers (its D2H is part
        of the call), so both modes return numpy; ``materialize`` exists
        so callers can be executor-agnostic about the boundary."""
        feed_names = sorted(feeds)
        fn = self.callable_for(graph, fetches, feed_names)
        out = fn(*[feeds[n] for n in feed_names])
        if materialize:
            return [np.asarray(o) for o in out]
        return list(out)
