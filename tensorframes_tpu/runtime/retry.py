"""Failure handling: deterministic block re-execution.

The reference outsourced fault tolerance to Spark's task retry + lineage
recomputation (SURVEY.md §5: worker kernels are pure functions of
(broadcast graph, partition rows), so a failed task is simply re-run).
The same property holds here — every block execution is a pure function
of (compiled executable, block arrays) — so the framework's retry is a
plain re-invocation: enable with ``tfs.config.update(
block_retry_attempts=N)``. Transient device/runtime errors (preempted
chip, dropped tunnel RPC) get N extra attempts; deterministic errors
fail after exhausting them with the original exception.
"""

from __future__ import annotations

from typing import Callable

from ..utils.log import get_logger

__all__ = ["run_with_retries"]

_log = get_logger("retry")


def run_with_retries(fn: Callable, *args, attempts: int = 0, what: str = "block"):
    """Call ``fn(*args)``; on exception retry up to ``attempts`` times."""
    for attempt in range(attempts + 1):
        try:
            return fn(*args)
        except Exception as e:  # noqa: BLE001 — Spark-style blanket retry
            if attempt >= attempts:
                raise
            _log.warning(
                "%s execution failed (attempt %d/%d): %s — retrying",
                what, attempt + 1, attempts + 1, e,
            )
    raise AssertionError("unreachable")


def maybe_check_numerics(fetch_names, outs, what: str):
    """Debug-mode numerics guard (``tfs.config.update(check_numerics=True)``):
    raise FloatingPointError naming the verb, block, and fetch when an
    output contains NaN/Inf — the role `CheckNumerics` nodes play in the
    reference's graphs, applied to every fetch without editing the graph.
    Costs one device sync per checked call; off by default."""
    from .. import config

    if not config.get().check_numerics:
        return
    import jax.numpy as jnp
    import numpy as np

    for name, o in zip(fetch_names, outs):
        arr = jnp.asarray(o)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if not bool(jnp.all(jnp.isfinite(arr))):
            bad = int(np.sum(~np.asarray(jnp.isfinite(arr))))
            raise FloatingPointError(
                f"{what}: fetch {name!r} contains {bad} non-finite "
                "value(s) (check_numerics is on)"
            )
