"""DEPRECATED compat shim — everything lives in `runtime.faults` now.

The blanket retry that used to live here grew into the classified
fault-tolerance layer (`runtime.faults`, ISSUE 6), and
`maybe_check_numerics` — the CheckNumerics role for every verb output —
moved there too (failure handling and failure detection are one
subsystem). This module remains only so historical imports keep
resolving; new code should import from `runtime.faults` directly.
"""

from __future__ import annotations

from .faults import maybe_check_numerics, run_with_retries  # noqa: F401

__all__ = ["run_with_retries", "maybe_check_numerics"]
