"""Failure handling: deterministic block re-execution.

The reference outsourced fault tolerance to Spark's task retry + lineage
recomputation (SURVEY.md §5: worker kernels are pure functions of
(broadcast graph, partition rows), so a failed task is simply re-run).
The same property holds here — every block execution is a pure function
of (compiled executable, block arrays) — so the framework's retry is a
plain re-invocation: enable with ``tfs.config.update(
block_retry_attempts=N)``. Transient device/runtime errors (preempted
chip, dropped tunnel RPC) get N extra attempts; deterministic errors
fail after exhausting them with the original exception.
"""

from __future__ import annotations

from typing import Callable

from ..utils.log import get_logger

__all__ = ["run_with_retries"]

_log = get_logger("retry")


def run_with_retries(fn: Callable, *args, attempts: int = 0, what: str = "block"):
    """Call ``fn(*args)``; on exception retry up to ``attempts`` times."""
    for attempt in range(attempts + 1):
        try:
            return fn(*args)
        except Exception as e:  # noqa: BLE001 — Spark-style blanket retry
            if attempt >= attempts:
                raise
            _log.warning(
                "%s execution failed (attempt %d/%d): %s — retrying",
                what, attempt + 1, attempts + 1, e,
            )
    raise AssertionError("unreachable")


def maybe_check_numerics(fetch_names, outs, what: str):
    """Debug-mode numerics guard (``tfs.config.update(check_numerics=True)``):
    raise FloatingPointError naming the verb, block, and fetch when an
    output contains NaN/Inf — the role `CheckNumerics` nodes play in the
    reference's graphs, applied to every fetch without editing the graph.

    The finite-mask reduction runs ON DEVICE: every float fetch folds to
    one boolean, the booleans fold to one scalar verdict, and the clean
    path pays exactly ONE host sync for that scalar — the outputs
    themselves never leave device memory. Only when the verdict fires
    does the failure path sync per fetch to name the culprit and count
    its bad values (also reduced on device). Off by default."""
    from .. import config

    if not config.get().check_numerics:
        return
    import jax.numpy as jnp

    finites = []  # (name, array, all-finite scalar) per float fetch
    for name, o in zip(fetch_names, outs):
        arr = jnp.asarray(o)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        finites.append((name, arr, jnp.all(jnp.isfinite(arr))))
    if not finites:
        return
    verdict = (
        finites[0][2]
        if len(finites) == 1
        else jnp.all(jnp.stack([f for _, _, f in finites]))
    )
    if bool(verdict):  # the one sync on the clean path
        return
    for name, arr, fin in finites:
        if not bool(fin):
            bad = int(jnp.sum(~jnp.isfinite(arr)))
            raise FloatingPointError(
                f"{what}: fetch {name!r} contains {bad} non-finite "
                "value(s) (check_numerics is on)"
            )
    raise AssertionError("unreachable: verdict fired but no fetch did")
