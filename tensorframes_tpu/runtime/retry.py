"""Failure handling shims: classified retry + the numerics guard.

The blanket retry that used to live here (re-invoke N times on ANY
exception) grew into the fault-tolerance layer in `runtime.faults`:
errors are now CLASSIFIED (transient / resource / deterministic),
transient retries back off exponentially with deterministic jitter, and
deterministic errors surface after exactly one attempt instead of
burning the whole budget. `run_with_retries` is re-exported so existing
imports keep resolving; `maybe_check_numerics` (the CheckNumerics role
for every verb output) still lives here.
"""

from __future__ import annotations

from .faults import run_with_retries  # noqa: F401  (compat re-export)

__all__ = ["run_with_retries", "maybe_check_numerics"]


def maybe_check_numerics(fetch_names, outs, what: str):
    """Debug-mode numerics guard (``tfs.config.update(check_numerics=True)``):
    raise FloatingPointError naming the verb, block, and fetch when an
    output contains NaN/Inf — the role `CheckNumerics` nodes play in the
    reference's graphs, applied to every fetch without editing the graph.

    The finite-mask reduction runs ON DEVICE: every float fetch folds to
    one boolean, the booleans fold to one scalar verdict, and the clean
    path pays exactly ONE host sync for that scalar — the outputs
    themselves never leave device memory. Only when the verdict fires
    does the failure path sync per fetch to name the culprit and count
    its bad values (also reduced on device). Off by default."""
    from .. import config

    if not config.get().check_numerics:
        return
    import jax.numpy as jnp

    finites = []  # (name, array, all-finite scalar) per float fetch
    for name, o in zip(fetch_names, outs):
        arr = jnp.asarray(o)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        finites.append((name, arr, jnp.all(jnp.isfinite(arr))))
    if not finites:
        return
    verdict = (
        finites[0][2]
        if len(finites) == 1
        else jnp.all(jnp.stack([f for _, _, f in finites]))
    )
    if bool(verdict):  # the one sync on the clean path
        return
    for name, arr, fin in finites:
        if not bool(fin):
            bad = int(jnp.sum(~jnp.isfinite(arr)))
            raise FloatingPointError(
                f"{what}: fetch {name!r} contains {bad} non-finite "
                "value(s) (check_numerics is on)"
            )
    raise AssertionError("unreachable: verdict fired but no fetch did")
