"""Runtime layer: compile-cached execution."""

from .executor import Executor, default_executor

__all__ = ["Executor", "default_executor"]
