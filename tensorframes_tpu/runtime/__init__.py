"""Runtime layer: compile-cached execution + fault-tolerant dispatch."""

from . import faults
from .executor import Executor, default_executor

__all__ = ["Executor", "default_executor", "faults"]
