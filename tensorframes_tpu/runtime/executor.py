"""Executor: compile-once-run-many graph execution.

Replaces the reference's per-task session churn — every Spark task imported
the graph into a fresh native TF Graph+Session and tore it down afterwards
(`DebugRowOps.scala:790`, `TensorFlowOps.scala:76-95`). Here a graph is
lowered once into a jitted XLA executable and cached by
(graph fingerprint, fetches, feed order); `jax.jit` then re-specializes per
concrete block shape, so running B same-shaped blocks costs one compile +
B executions instead of B session setups.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..graph.ir import Graph
from ..ops.lowering import build_callable

__all__ = ["Executor", "default_executor", "lru_get_or_insert"]


def lru_get_or_insert(cache, lock, key, make, limit):
    """The ONE locked-LRU discipline both executors use: hit moves to
    the tail; a miss builds OUTSIDE the lock (tracing/compiling can be
    slow) and a lost insert race reuses the winner's value, costing only
    the redundant build. Returns (value, inserted)."""
    with lock:
        fn = cache.get(key)
        if fn is not None:
            cache.move_to_end(key)
            return fn, False
    fn = make()
    with lock:
        winner = cache.get(key)
        if winner is not None:
            cache.move_to_end(key)
            return winner, False
        cache[key] = fn
        while len(cache) > max(1, int(limit)):
            cache.popitem(last=False)
    return fn, True


class Executor:
    def __init__(self):
        self._cache: "OrderedDict[Tuple, Callable]" = OrderedDict()
        self._lock = threading.Lock()
        self.compile_count = 0  # observability: distinct lowered callables

    def cached(
        self,
        kind: str,
        graph: Graph,
        fetches: Sequence[str],
        feed_names: Sequence[str],
        make: Callable[[], Callable],
    ) -> Callable:
        """Generic compile cache: ``kind`` distinguishes execution styles of
        the same graph (plain block call, vmapped per-row, scan fold, ...).
        LRU-bounded (`config.executor_cache_entries`) so a long-lived
        process whose graphs drift does not accumulate compiled
        executables without limit; see `lru_get_or_insert` for the
        locking discipline (the default executor is shared across
        threads)."""
        key = (kind, graph.fingerprint(), tuple(fetches), tuple(feed_names))
        from .. import config as _config

        fn, inserted = lru_get_or_insert(
            self._cache, self._lock, key, make,
            _config.get().executor_cache_entries,
        )
        if inserted:
            with self._lock:  # += is not atomic; keep the count exact
                self.compile_count += 1
        return fn

    def callable_for(
        self,
        graph: Graph,
        fetches: Sequence[str],
        feed_names: Sequence[str],
    ) -> Callable:
        return self.cached(
            "block",
            graph,
            fetches,
            feed_names,
            lambda: jax.jit(
                build_callable(graph, list(fetches), list(feed_names))
            ),
        )

    def run(
        self,
        graph: Graph,
        fetches: Sequence[str],
        feeds: Dict[str, np.ndarray],
    ) -> List[np.ndarray]:
        feed_names = sorted(feeds)
        fn = self.callable_for(graph, fetches, feed_names)
        out = fn(*[feeds[n] for n in feed_names])
        return [np.asarray(o) for o in out]

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()


_default: Optional[Executor] = None


def default_executor() -> Executor:
    global _default
    if _default is None:
        _default = Executor()
    return _default
