"""Executor: compile-once-run-many graph execution.

Replaces the reference's per-task session churn — every Spark task imported
the graph into a fresh native TF Graph+Session and tore it down afterwards
(`DebugRowOps.scala:790`, `TensorFlowOps.scala:76-95`). Here a graph is
lowered once into a jitted XLA executable and cached by
(graph fingerprint, fetches, feed order); `jax.jit` then re-specializes per
concrete block shape, so running B same-shaped blocks costs one compile +
B executions instead of B session setups.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from ..graph.ir import Graph
from ..ops.lowering import build_callable

__all__ = [
    "Executor",
    "default_executor",
    "lru_get_or_insert",
    "set_fault_injector",
]


# Fault-injection seam (`tensorframes_tpu.testing.faults`): when
# installed, ``hook(fn, key) -> fn`` wraps every program handed out by
# `Executor.cached` — the one boundary EVERY dispatch crosses (block
# maps, vmapped rows, folds, combines, shard_map programs) — so a
# deterministic chaos harness can fault any dispatch by ordinal /
# device / program / kind without touching verb code. The wrapper is
# applied on the way OUT of the cache (never stored), so the compiled
# program itself is never poisoned. None = production path: one module
# attribute read per cached() call.
_fault_injector = None


def set_fault_injector(hook) -> None:
    global _fault_injector
    _fault_injector = hook


def lru_get_or_insert(cache, lock, key, make, limit):
    """The ONE locked-LRU discipline both executors use: hit moves to
    the tail; a miss builds OUTSIDE the lock (tracing/compiling can be
    slow) and a lost insert race reuses the winner's value, costing only
    the redundant build. Returns (value, inserted)."""
    with lock:
        fn = cache.get(key)
        if fn is not None:
            cache.move_to_end(key)
            return fn, False
    fn = make()
    with lock:
        winner = cache.get(key)
        if winner is not None:
            cache.move_to_end(key)
            return winner, False
        cache[key] = fn
        while len(cache) > max(1, int(limit)):
            cache.popitem(last=False)
    return fn, True


class Executor:
    # Compiled programs from this executor may carry `donate_argnums`
    # (the reduce-combine path): the in-process JAX runtime honors
    # buffer donation. The native host executes lowered modules through
    # its own buffer protocol, so `NativeExecutor` sets this False and
    # verbs build non-donating combines for it.
    supports_donation = True
    # Verbs may route eligible dispatches through the shape-bucketing
    # policy (`shape_policy`) on this executor: jit re-specializes per
    # concrete shape, so quantizing block shapes bounds its compiles.
    supports_bucketing = True
    # The multi-device block scheduler (`runtime.scheduler`) may spread
    # this executor's per-block dispatches across jax.local_devices():
    # programs run wherever their committed inputs live, so placement is
    # a device_put away. The native executor sets this False — it owns
    # its own PJRT host and must never see in-process device_put arrays.
    supports_scheduling = True

    def __init__(self):
        self._cache: "OrderedDict[Tuple, Callable]" = OrderedDict()
        self._lock = threading.Lock()
        self.compile_count = 0  # observability: distinct lowered callables
        # cache observability (surfaced via utils.inspection.executor_stats):
        # a recompile storm shows up as misses growing with call count
        self.cache_hits = 0
        self.cache_misses = 0
        # per-device scheduler ledgers (device label -> count), kept by
        # `runtime.scheduler` under self._lock and surfaced through
        # executor_stats: where dispatches landed and which devices paid
        # jit specializations (compiles are best-effort under
        # concurrent verbs, same caveat as _instrument)
        self.device_dispatches: Dict[str, int] = {}
        self.device_compiles: Dict[str, int] = {}
        # cached-program keys already flagged by the recompile-storm
        # warning (one warning per program, ever)
        self._storm_warned: set = set()

    def jit(self, fn: Callable) -> Callable:
        """Compile an arbitrary jittable for this executor's runtime.
        The function front-end kernels route through this seam so the
        native executor (which overrides it) runs them on the C++ PJRT
        host instead of in-process JAX."""
        return jax.jit(fn)

    def cached(
        self,
        kind: str,
        graph: Graph,
        fetches: Sequence[str],
        feed_names: Sequence[str],
        make: Callable[[], Callable],
    ) -> Callable:
        """Generic compile cache: ``kind`` distinguishes execution styles of
        the same graph (plain block call, vmapped per-row, scan fold, ...).
        LRU-bounded (`config.executor_cache_entries`) so a long-lived
        process whose graphs drift does not accumulate compiled
        executables without limit; see `lru_get_or_insert` for the
        locking discipline (the default executor is shared across
        threads)."""
        key = (kind, graph.fingerprint(), tuple(fetches), tuple(feed_names))
        from .. import config as _config

        def timed_make():
            # compile-time attribution (`utils.telemetry`): every cache
            # miss is timed and labeled by graph fingerprint — this is
            # the "trace" phase (lowering + jit wrapping); the real XLA
            # compile per input shape is timed in `_instrument`'s
            # wrapper ("xla" phase)
            from ..utils import telemetry as _tele

            t0 = time.perf_counter()
            fn = self._instrument(key, make())
            t1 = time.perf_counter()
            _tele.record_compile(key[1], kind, t1 - t0, "trace", t0, t1)
            return fn

        fn, inserted = lru_get_or_insert(
            self._cache, self._lock, key,
            timed_make,
            _config.get().executor_cache_entries,
        )
        with self._lock:  # += is not atomic; keep the counts exact
            if inserted:
                self.compile_count += 1
                self.cache_misses += 1
            else:
                self.cache_hits += 1
        if _fault_injector is not None:
            fn = _fault_injector(fn, key)
        return fn

    def _instrument(self, key: Tuple, fn: Callable) -> Callable:
        """Wrap a freshly built cached program with per-shape compile
        observability. jit re-specializes (full XLA compile) per distinct
        input shape signature, invisibly to `compile_count` — the
        wrapper watches the jit cache size (`_cache_size`) and logs a
        ONE-TIME recompile-storm warning when a single program crosses
        `config.recompile_warn_shapes` distinct shapes. Programs without
        a `_cache_size` (native-host wrappers, plain callables) pass
        through untouched; the jit cache handle is re-exposed on the
        wrapper so introspection (`jit_shape_compiles`, tests poking
        `fn._cache_size()`) keeps working."""
        sizer = getattr(fn, "_cache_size", None)
        if not callable(sizer):
            return fn

        # high-water mark of the jit cache size already ATTRIBUTED to a
        # compile event: under concurrent dispatch of one program,
        # several threads can observe the same cache growth (one thread
        # compiles a new shape while another executes a compiled one),
        # and without this gate each would record its own call window as
        # a compile. The first exiting observer of each new size wins —
        # event COUNTS stay exact per specialization; the recorded
        # window is that observer's call, so duration is best-effort
        # under contention.
        compile_seen = [0]
        seen_lock = threading.Lock()

        def wrapped(*args, **kwargs):
            from ..utils import telemetry as _tele
            from . import costmodel as _cm

            # jit shape re-specialization attribution: when this call
            # grows the jit cache, the (synchronous) trace+XLA-compile
            # happened inside it — time the call and label the compile
            # event with the program fingerprint. Tracked when telemetry
            # OR the cost ledger is on (the ledger captures the
            # compiler's modeled cost at exactly these events); with
            # both disabled runs pay nothing beyond the storm check
            # below.
            ledger = _cm.enabled()
            n0 = None
            if _tele.enabled() or ledger:
                try:
                    n0 = sizer()
                except Exception:
                    n0 = None
                t0 = time.perf_counter()
            if n0 is not None:
                with seen_lock:
                    if compile_seen[0] < n0:
                        compile_seen[0] = n0  # pre-instrumentation shapes
            out = fn(*args, **kwargs)
            if n0 is not None:
                try:
                    n1 = sizer()
                except Exception:
                    n1 = None
                record = False
                if n1 is not None and n1 > n0:
                    with seen_lock:
                        if n1 > compile_seen[0]:
                            compile_seen[0] = n1
                            record = True
                if record:
                    t1 = time.perf_counter()
                    _tele.record_compile(
                        key[1], key[0], t1 - t0, "xla", t0, t1
                    )
                    if ledger:
                        # the XLA compile for this shape just happened;
                        # lowering again here is tracing + HLO cost
                        # analysis only (no second backend compile) —
                        # the ONE window where modeled cost is captured
                        _cm.capture(key, fn, args)
            if ledger:
                _cm.note_exec(key, args, out)
            from .. import config as _config

            threshold = _config.get().recompile_warn_shapes
            if threshold and key not in self._storm_warned:
                try:
                    n = sizer()
                except Exception:
                    return out
                if n > threshold:
                    with self._lock:
                        if key in self._storm_warned:
                            return out
                        # bounded: programs come and go in a long-lived
                        # service while this set never follows cache
                        # eviction — past the cap an arbitrary entry is
                        # dropped (worst case: an evicted-and-rebuilt
                        # program warns once more)
                        while len(self._storm_warned) >= 1024:
                            self._storm_warned.pop()
                        self._storm_warned.add(key)
                    from ..utils.log import get_logger

                    if _config.get().shape_bucketing:
                        # bucketing is already on: the storm means this
                        # program is not bucketable (non-row-local map /
                        # unclassified reduce) or the ladder itself is
                        # longer than the threshold — don't send the
                        # operator to a knob that is already set
                        remedy = (
                            "this program is not eligible for "
                            "shape_bucketing (non-row-local or "
                            "unclassified graph) or its bucket ladder "
                            "exceeds the threshold; repartition to stable "
                            "block sizes, coarsen shape_bucket_growth, or "
                            "raise recompile_warn_shapes"
                        )
                    else:
                        remedy = (
                            "enable config.shape_bucketing (or "
                            "repartition to stable block sizes) to bound "
                            "XLA compiles"
                        )
                    get_logger("executor").warning(
                        "recompile storm: program %s/%s has compiled %d "
                        "distinct input shapes (> recompile_warn_shapes=%d);"
                        " block shapes are drifting per call — %s",
                        key[0], str(key[1])[:12], n, threshold, remedy,
                    )
            return out

        wrapped._cache_size = sizer
        wrapped.__wrapped__ = fn
        return wrapped

    def program_shape_compiles(self) -> Dict[Tuple, int]:
        """Per-program XLA shape specializations: cache key ``(kind,
        fingerprint, fetches, feeds)`` -> the program's live jit cache
        size. The per-program view behind `jit_shape_compiles` — and
        what `tfs.diagnostics()` renders as the recompile-storm table
        ("which program is eating my startup"). Entries without a jit
        cache handle count as 1."""
        with self._lock:
            items = list(self._cache.items())
        out: Dict[Tuple, int] = {}
        for key, fn in items:
            sizer = getattr(fn, "_cache_size", None)
            if callable(sizer):
                try:
                    out[key] = int(sizer())
                    continue
                except Exception:
                    pass  # a broken sizer reads as 1, never breaks stats
            out[key] = 1
        return out

    def jit_shape_compiles(self) -> int:
        """Total XLA shape specializations across LIVE cached programs:
        the sum of every program's jit cache size (each distinct input
        shape signature = one real compile). This is the recompile-storm
        metric `compile_count` cannot see — under shape bucketing it
        stays O(log max-block-rows) per program no matter how block
        sizes drift. Entries without a jit cache handle count as 1;
        evicted entries' compiles are forgotten with them."""
        return sum(self.program_shape_compiles().values())

    def callable_for(
        self,
        graph: Graph,
        fetches: Sequence[str],
        feed_names: Sequence[str],
    ) -> Callable:
        return self.cached(
            "block",
            graph,
            fetches,
            feed_names,
            lambda: jax.jit(
                build_callable(graph, list(fetches), list(feed_names))
            ),
        )

    def run(
        self,
        graph: Graph,
        fetches: Sequence[str],
        feeds: Dict[str, np.ndarray],
        materialize: bool = False,
    ) -> List[Union["jax.Array", np.ndarray]]:
        """Execute the graph once over ``feeds``.

        Returns DEVICE arrays by default: the call is an async dispatch
        and results stay in device memory, so chained runs pipeline
        without a host round-trip (the reference synced every
        `session.run` to the JVM heap, `DebugRowOps.scala:790-809`).
        Pass ``materialize=True`` to block and copy results to host
        numpy — the explicit opt-in boundary, same contract as
        `Column.host_values`.
        """
        feed_names = sorted(feeds)
        fn = self.callable_for(graph, fetches, feed_names)
        out = fn(*[feeds[n] for n in feed_names])
        if materialize:
            return [np.asarray(o) for o in out]
        return list(out)

    def cache_keys(self) -> List[Tuple]:
        """Snapshot of live compile-cache keys
        ``(kind, graph fingerprint, fetches, feed names)`` — the
        introspection surface `benchmarks/fusion_bench.py` and the
        fusion tests use to prove cache keying: a fused lazy pipeline
        must create exactly ONE ``"block"``-kind entry (the fused
        fingerprint) where the eager chain creates one per verb."""
        with self._lock:
            return list(self._cache.keys())

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()


_default: Optional[Executor] = None
_native_default: Optional[object] = None
_native_unavailable: Optional[str] = None
_native_lock = threading.Lock()


def _native_default_executor():
    """Lazy process-wide NativeExecutor over the repo CPU plugin, or
    None with the reason recorded. jax_fallback=True is safe HERE
    because the repo CPU plugin claims no shared accelerator device
    (`pjrt_host.cpu_plugin_path` docstring) — mesh kinds on this
    single-device plugin fall back to the in-process JAX executor."""
    global _native_default, _native_unavailable
    # lock-free fast path: after initialization every verb dispatch
    # reads one attribute instead of serializing on the process lock
    if _native_default is not None:
        return _native_default
    if _native_unavailable is not None:
        return None
    with _native_lock:
        if _native_default is not None:
            return _native_default
        if _native_unavailable is not None:
            return None
        try:
            from .native_executor import NativeExecutor
            from .pjrt_host import cpu_plugin_path

            path = cpu_plugin_path()
            if path is None:
                _native_unavailable = (
                    "native/libtfs_pjrt_cpu.so is not built (make -C native)"
                )
                return None
            _native_default = NativeExecutor(path, jax_fallback=True)
            return _native_default
        except Exception as e:  # plugin load/claim failure
            _native_unavailable = f"plugin load failed: {e}"
            return None


def default_executor() -> Executor:
    """The executor verbs use when no ``executor=`` is passed. With
    ``config.native_executor`` = "auto"/"require", single-program kinds
    route through the C++ PJRT host (`NativeExecutor`) — the
    libtensorflow-equivalent spine as the default, not an opt-in."""
    from .. import config as _config

    mode = _config.get().native_executor
    if mode not in ("off", "auto", "require"):
        # fail loud: a typo'd mode silently meaning "off" would defeat
        # exactly the guarantee "require" exists to provide
        raise ValueError(
            f"config.native_executor={mode!r} is not one of "
            "'off' | 'auto' | 'require'"
        )
    if mode in ("auto", "require"):
        ex = _native_default_executor()
        if ex is not None:
            return ex  # type: ignore[return-value]
        if mode == "require":
            raise RuntimeError(
                "config.native_executor='require' but the native host is "
                f"unavailable: {_native_unavailable}"
            )
    global _default
    if _default is None:
        _default = Executor()
    return _default
